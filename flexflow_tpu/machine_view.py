"""MachineView: device-grid assignment of an op.

Analog of the reference's ``MachineView`` (include/flexflow/machine_view.h:14-35)
and ``MachineResource`` (:51). On TPU a MachineView denotes a logical sub-grid of
the global ``jax.sharding.Mesh``: ``dim[i]`` counts devices along the i-th view
axis and the view is realized as a NamedSharding over mesh axes (see
``flexflow_tpu.parallel.sharding``). ``start_device_id`` is retained for strategy
(de)serialization parity but XLA SPMD places all ops on the full mesh; a view
whose extent is smaller than the mesh means the op is *replicated* over the
remaining axes.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class MachineView:
    device_type: str = "TPU"  # reference supports CPU/GPU; here TPU (or CPU for tests)
    start_device_id: int = 0
    dim: Tuple[int, ...] = (1,)
    stride: Tuple[int, ...] = (1,)

    def __post_init__(self):
        object.__setattr__(self, "dim", tuple(int(d) for d in self.dim))
        object.__setattr__(self, "stride", tuple(int(s) for s in self.stride))
        assert len(self.dim) == len(self.stride)

    @property
    def ndims(self) -> int:
        return len(self.dim)

    def num_parts(self) -> int:
        n = 1
        for d in self.dim:
            n *= d
        return n

    def get_device_id(self, point: Sequence[int]) -> int:
        """Device for a grid point (reference: mapper.cc:452-470)."""
        assert len(point) == self.ndims
        dev = self.start_device_id
        for p, s in zip(point, self.stride):
            dev += p * s
        return dev

    def device_ids(self) -> Tuple[int, ...]:
        ids = []

        def rec(axis, base):
            if axis == self.ndims:
                ids.append(base)
                return
            for p in range(self.dim[axis]):
                rec(axis + 1, base + p * self.stride[axis])

        rec(0, self.start_device_id)
        return tuple(ids)

    def hash(self) -> int:
        return hash((self.device_type, self.start_device_id, self.dim, self.stride))

    @staticmethod
    def data_parallel(num_devices: int) -> "MachineView":
        """The reference's default 1-D strategy (config.h:95-100)."""
        return MachineView(dim=(num_devices,), stride=(1,))


@dataclasses.dataclass(frozen=True)
class MachineResource:
    """Available resources for the search (reference: machine_view.h:51)."""

    num_nodes: int = 1
    all_tpus_per_node: int = 1
    available_tpus_per_node: int = 1
    all_cpus_per_node: int = 1
    available_cpus_per_node: int = 1
    start_tpu_id: int = 0
    start_cpu_id: int = 0

    def num_devices(self) -> int:
        return self.num_nodes * self.available_tpus_per_node
