"""User-facing tensor of the layer graph.

Analog of the reference's ``TensorBase`` (include/flexflow/tensor.h) built by the
``FFModel`` op-builder API before ``compile``. Shapes are numpy-ordered (batch
first), unlike the reference's Legion dim ordering.
"""
from __future__ import annotations

import itertools
from typing import Optional, Sequence, Tuple, TYPE_CHECKING

import numpy as np

from .ffconst import DataType

if TYPE_CHECKING:
    from .layer import Layer
    from .model import FFModel

_guid_counter = itertools.count(1000)


class Tensor:
    """A node edge in the user layer graph (pre-compile, unsharded)."""

    def __init__(
        self,
        shape: Sequence[int],
        dtype: DataType = DataType.DT_FLOAT,
        owner_layer: Optional["Layer"] = None,
        owner_idx: int = 0,
        create_grad: bool = True,
        name: str = "",
        model: Optional["FFModel"] = None,
    ):
        self.guid: int = next(_guid_counter)
        self.dims: Tuple[int, ...] = tuple(int(d) for d in shape)
        self.dtype = dtype
        self.owner_layer = owner_layer
        self.owner_idx = owner_idx
        self.create_grad = create_grad
        self.name = name or f"tensor_{self.guid}"
        self.model = model

    # -- reference-parity accessors (tensor.h / flexflow_cffi.py:572-881) -------
    @property
    def num_dims(self) -> int:
        return len(self.dims)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.dims

    def get_volume(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 0

    def get_dims(self) -> Tuple[int, ...]:
        return self.dims

    # weight access is resolved through the owning model after compile
    # (reference: ParallelTensorBase::get_tensor/set_tensor,
    #  src/runtime/parallel_tensor.cc:650,698)
    def get_weights(self, ff_model: Optional["FFModel"] = None) -> np.ndarray:
        model = ff_model or self.model
        if model is None:
            raise RuntimeError("tensor is not attached to a model")
        return model._get_weight_by_tensor(self)

    def set_weights(self, ff_model, np_array: np.ndarray) -> None:
        model = ff_model or self.model
        model._set_weight_by_tensor(self, np_array)

    # -- host staging for the manual-phase loop (flexflow_cffi.py:660,682
    #    set_tensor/get_tensor; the attach-style examples drive batches this
    #    way: mnist_mlp_attach.py next_batch -> set_tensor -> forward) -------
    def set_tensor(self, ff_model, np_array: np.ndarray) -> None:
        model = ff_model or self.model
        if self.owner_layer is None or self is model.label_tensor:
            model._stage_tensor_value(self, np_array)
        elif self.owner_idx < 0:
            model._set_weight_by_tensor(self, np_array)
        else:
            raise ValueError(
                f"{self.name} is an activation output of layer "
                f"'{self.owner_layer.name}'; set_tensor accepts model "
                "inputs, the label tensor, or weight tensors")

    def get_tensor(self, ff_model=None, comm_type=None) -> np.ndarray:
        model = ff_model or self.model
        if self.owner_layer is None or self is model.label_tensor:
            return model._staged_tensor_value(self)
        if self.owner_idx < 0:
            return model._get_weight_by_tensor(self)
        return model._activation_value(self)

    def attach_numpy_array(self, ff_model, ff_config=None,
                           np_array: Optional[np.ndarray] = None) -> None:
        """reference: Tensor.attach_numpy_array (flexflow_cffi.py) — zero-copy
        region attach there, host staging here. Accepts the reference's
        (ffmodel, ffconfig, array) form or the short (ffmodel, array)."""
        if np_array is None:  # short form attach(ffmodel, array)
            np_array, ff_config = ff_config, None
        self.set_tensor(ff_model, np_array)

    def detach_numpy_array(self, ff_config=None) -> None:
        return None

    # inline mapping is a no-op under XLA — host access is a device_get;
    # kept for API parity (flexflow_cffi.py:601-658 inline_map/get_array)
    def inline_map(self, ff_model=None, ff_config=None) -> None:
        return None

    def inline_unmap(self, ff_model=None, ff_config=None) -> None:
        return None

    def get_array(self, ff_model=None, ff_config=None) -> np.ndarray:
        return self.get_tensor(ff_model)

    def __repr__(self) -> str:
        return f"Tensor(name={self.name}, dims={self.dims}, dtype={self.dtype.name})"
