"""FFModel: the central model object.

TPU-native rebuild of the reference's ``FFModel`` (include/flexflow/model.h:326,
src/runtime/model.cc:4708): the op-builder API (model.h:336-554, mirrored from
the Python surface flexflow_cffi.py:883-2100 which is the compatibility
contract), ``compile`` (model.cc:2803), and the train-step drivers
(forward/backward/update/fit/eval).

``compile`` here follows the same pipeline as the reference's (SURVEY §3.3):
Layer graph -> PCG (`create_operators_from_layers`, model.cc:2785) -> strategy
selection (Unity search / data-parallel default / imported strategy) -> lowering
(Executor builds the jitted step; XLA replaces Legion mapping + regions).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .config import FFConfig
from .ffconst import (ActiMode, AggrMode, CompMode, DataType, LossType,
                      MetricsType, OperatorType, PoolType, dtype_to_jnp,
                      jnp_to_dtype)
from .layer import Layer
from .tensor import Tensor
from .execution.losses import loss_value
from .execution.metrics import Metrics, PerfMetrics
from .execution.optimizers import Optimizer, SGDOptimizer


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self._layers: List[Layer] = []
        self._input_tensors: List[Tensor] = []
        self.optimizer: Optional[Optimizer] = None

        # populated by compile()
        self.pcg = None
        self.strategy = None
        self.mesh = None
        self.executor = None
        self.params = None
        self.opt_state = None
        self.metrics_obj: Optional[Metrics] = None
        self.loss_type: Optional[LossType] = None
        self.label_tensor: Optional[Tensor] = None
        self._perf = PerfMetrics()
        self._tensor_to_node: Dict[int, int] = {}  # tensor.guid -> pcg guid/idx
        self._layer_to_node: Dict[int, int] = {}
        self._rng_counter = 0
        # manual-loop staging (API parity: forward/backward/update phases)
        self._staged: Dict[str, Any] = {}
        self._recompile_state = None
        self._pipeline_trainer = None  # set by compile for GPipe strategies
        # {cache_op_name: latest score_fn value} filled during fit
        # (reference: cache.cc score futures read by the recompile trigger)
        self.cache_scores: Dict[str, float] = {}

    # ======================================================= tensor creation ==
    def create_tensor(self, dims: Sequence[int],
                      dtype: DataType = DataType.DT_FLOAT,
                      create_grad: bool = True, name: str = "") -> Tensor:
        if not isinstance(dtype, DataType):
            raise TypeError(
                f"create_tensor dtype must be a DataType, got {dtype!r} "
                "(signature: create_tensor(dims, dtype, create_grad, name))")
        t = Tensor(dims, dtype, create_grad=create_grad,
                   name=name or f"input_{len(self._input_tensors)}", model=self)
        self._input_tensors.append(t)
        return t

    # ================================================================ builders ==
    def _add_layer(self, op_type: OperatorType, inputs: List[Tensor],
                   attrs: Dict[str, Any], dtype: Optional[DataType] = None,
                   name: Optional[str] = None
                   ) -> Union[Tensor, List[Tensor]]:
        from .ops.base import op_class_for

        dtype = dtype or (inputs[0].dtype if inputs else DataType.DT_FLOAT)
        layer = Layer(op_type, dtype, name, inputs, attrs=attrs,
                      index=len(self._layers))
        op = op_class_for(op_type)(layer.name, attrs, dtype,
                                   num_inputs=len(inputs))
        out_shapes = op.infer_output_shapes([t.dims for t in inputs])
        out_dtype = op.output_dtype([t.dtype for t in inputs])
        # surface declared weights as user-visible tensors (reference parity)
        for wname, (shape, wdtype, init) in op.weight_specs(
                [t.dims for t in inputs]).items():
            layer.add_weight(wname, shape, wdtype, init)
        outs = []
        for i, s in enumerate(out_shapes):
            t = Tensor(s, out_dtype, owner_layer=layer, owner_idx=i, model=self)
            t.name = f"{layer.name}:out{i}"
            outs.append(t)
        layer.outputs = outs
        self._layers.append(layer)
        return outs[0] if len(outs) == 1 else outs

    # ---- dense / conv / pool (reference model.h:336-554) ----------------------
    def dense(self, input: Tensor, out_dim: int,
              activation: ActiMode = ActiMode.AC_MODE_NONE,
              use_bias: bool = True, datatype: Optional[DataType] = None,
              kernel_initializer=None, bias_initializer=None,
              kernel_regularizer=None,
              name: Optional[str] = None) -> Tensor:
        """kernel_regularizer: ("l1"|"l2", lambda) weight-decay spec added to
        the training loss (reference: RegularizerMode on Linear)."""
        return self._add_layer(
            OperatorType.OP_LINEAR, [input],
            {"out_dim": out_dim, "activation": activation, "use_bias": use_bias,
             "kernel_initializer": kernel_initializer,
             "bias_initializer": bias_initializer,
             "kernel_regularizer": kernel_regularizer},
            datatype or input.dtype, name)

    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int,
               kernel_w: int, stride_h: int, stride_w: int, padding_h: int,
               padding_w: int, activation: ActiMode = ActiMode.AC_MODE_NONE,
               groups: int = 1, use_bias: bool = True,
               kernel_initializer=None, bias_initializer=None,
               name: Optional[str] = None) -> Tensor:
        return self._add_layer(
            OperatorType.OP_CONV2D, [input],
            {"out_channels": out_channels, "kernel_h": kernel_h,
             "kernel_w": kernel_w, "stride_h": stride_h, "stride_w": stride_w,
             "padding_h": padding_h, "padding_w": padding_w,
             "activation": activation, "groups": groups, "use_bias": use_bias,
             "kernel_initializer": kernel_initializer,
             "bias_initializer": bias_initializer},
            input.dtype, name)

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int,
               stride_h: int, stride_w: int, padding_h: int, padding_w: int,
               pool_type: PoolType = PoolType.POOL_MAX,
               activation: ActiMode = ActiMode.AC_MODE_NONE,
               name: Optional[str] = None) -> Tensor:
        return self._add_layer(
            OperatorType.OP_POOL2D, [input],
            {"kernel_h": kernel_h, "kernel_w": kernel_w, "stride_h": stride_h,
             "stride_w": stride_w, "padding_h": padding_h,
             "padding_w": padding_w, "pool_type": pool_type,
             "activation": activation}, input.dtype, name)

    def batch_norm(self, input: Tensor, relu: bool = True,
                   name: Optional[str] = None) -> Tensor:
        return self._add_layer(OperatorType.OP_BATCHNORM, [input],
                               {"relu": relu}, input.dtype, name)

    def layer_norm(self, input: Tensor, axes: Sequence[int],
                   elementwise_affine: bool = True, eps: float = 1e-5,
                   name: Optional[str] = None) -> Tensor:
        return self._add_layer(
            OperatorType.OP_LAYERNORM, [input],
            {"axes": list(axes), "elementwise_affine": elementwise_affine,
             "eps": eps}, input.dtype, name)

    def rms_norm(self, input: Tensor, axes: Sequence[int] = (-1,),
                 eps: float = 1e-6, name: Optional[str] = None) -> Tensor:
        return self._add_layer(OperatorType.OP_RMSNORM, [input],
                               {"axes": list(axes), "eps": eps},
                               input.dtype, name)

    def batch_matmul(self, A: Tensor, B: Tensor,
                     name: Optional[str] = None) -> Tensor:
        return self._add_layer(OperatorType.OP_BATCHMATMUL, [A, B], {},
                               A.dtype, name)

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: AggrMode = AggrMode.AGGR_MODE_NONE,
                  dtype: DataType = DataType.DT_FLOAT, shared_op=None,
                  kernel_initializer=None, name: Optional[str] = None
                  ) -> Tensor:
        return self._add_layer(
            OperatorType.OP_EMBEDDING, [input],
            {"num_entries": num_entries, "out_dim": out_dim, "aggr": aggr,
             "kernel_initializer": kernel_initializer}, dtype, name)

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0,
                            vdim: int = 0, dropout: float = 0.0,
                            bias: bool = True, add_bias_kv: bool = False,
                            add_zero_attn: bool = False,
                            kernel_initializer=None, causal: bool = False,
                            name: Optional[str] = None) -> Tensor:
        return self._add_layer(
            OperatorType.OP_MULTIHEAD_ATTENTION, [query, key, value],
            {"embed_dim": embed_dim, "num_heads": num_heads, "kdim": kdim,
             "vdim": vdim, "dropout": dropout, "bias": bias,
             "add_bias_kv": add_bias_kv, "add_zero_attn": add_zero_attn,
             "kernel_initializer": kernel_initializer, "causal": causal},
            query.dtype, name)

    # ---- elementwise ----------------------------------------------------------
    def _binary(self, op_type, x, y, name=None, inplace_a=False):
        return self._add_layer(op_type, [x, y], {}, x.dtype, name)

    def add(self, x, y, inplace_a=False, name=None):
        return self._binary(OperatorType.OP_EW_ADD, x, y, name, inplace_a)

    def subtract(self, x, y, inplace_a=False, name=None):
        return self._binary(OperatorType.OP_EW_SUB, x, y, name, inplace_a)

    def multiply(self, x, y, inplace_a=False, name=None):
        return self._binary(OperatorType.OP_EW_MUL, x, y, name, inplace_a)

    def divide(self, x, y, inplace_a=False, name=None):
        return self._binary(OperatorType.OP_EW_DIV, x, y, name, inplace_a)

    def max(self, x, y, inplace_a=False, name=None):
        return self._binary(OperatorType.OP_EW_MAX, x, y, name, inplace_a)

    def min(self, x, y, inplace_a=False, name=None):
        return self._binary(OperatorType.OP_EW_MIN, x, y, name, inplace_a)

    def _unary(self, op_type, x, attrs=None, name=None):
        return self._add_layer(op_type, [x], attrs or {}, x.dtype, name)

    def exp(self, x, name=None):
        return self._unary(OperatorType.OP_EXP, x, name=name)

    def log(self, x, name=None):
        return self._unary(OperatorType.OP_LOG, x, name=name)

    def sin(self, x, name=None):
        return self._unary(OperatorType.OP_SIN, x, name=name)

    def cos(self, x, name=None):
        return self._unary(OperatorType.OP_COS, x, name=name)

    def rsqrt(self, x, name=None):
        return self._unary(OperatorType.OP_RSQRT, x, name=name)

    def pow(self, x, exponent: float, name=None):
        return self._unary(OperatorType.OP_POW, x, {"exponent": exponent}, name)

    def scalar_multiply(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OperatorType.OP_SCALAR_MULTIPLY, x,
                           {"scalar": scalar}, name)

    def scalar_add(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OperatorType.OP_SCALAR_ADD, x, {"scalar": scalar},
                           name)

    def scalar_sub(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OperatorType.OP_SCALAR_SUB, x, {"scalar": scalar},
                           name)

    def scalar_true_divide(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OperatorType.OP_SCALAR_TRUE_DIV, x,
                           {"scalar": scalar}, name)

    def relu(self, x, inplace=True, name=None):
        return self._unary(OperatorType.OP_RELU, x, name=name)

    def identity(self, x, name=None):
        return self._unary(OperatorType.OP_IDENTITY, x, name=name)

    def sigmoid(self, x, name=None):
        return self._unary(OperatorType.OP_SIGMOID, x, name=name)

    def tanh(self, x, name=None):
        return self._unary(OperatorType.OP_TANH, x, name=name)

    def elu(self, x, inplace=True, name=None):
        return self._unary(OperatorType.OP_ELU, x, name=name)

    def gelu(self, x, name=None):
        return self._unary(OperatorType.OP_GELU, x, name=name)

    def dropout(self, x, rate: float = 0.5, seed: int = 0, name=None):
        return self._unary(OperatorType.OP_DROPOUT, x,
                           {"rate": rate, "seed": seed}, name)

    # ---- shape ops ------------------------------------------------------------
    def flat(self, x, name=None):
        return self._unary(OperatorType.OP_FLAT, x, name=name)

    def softmax(self, x, axis: int = -1, name=None,
                use_pallas: bool = False):
        """use_pallas opts aligned last-axis rows into the Pallas row-softmax
        kernel on TPU (kernels/softmax.py; default jax.nn.softmax — measured
        at parity on v5e, see the kernel docstring)."""
        return self._unary(OperatorType.OP_SOFTMAX, x,
                           {"axis": axis, "use_pallas": use_pallas}, name)

    def reshape(self, x, shape: Sequence[int], name=None):
        return self._unary(OperatorType.OP_RESHAPE, x,
                           {"shape": list(shape)}, name)

    def transpose(self, x, perm: Sequence[int], name=None):
        return self._unary(OperatorType.OP_TRANSPOSE, x,
                           {"perm": list(perm)}, name)

    def reverse(self, x, axis: int, name=None):
        return self._unary(OperatorType.OP_REVERSE, x, {"axis": axis}, name)

    def slice_tensor(self, x, items, name=None):
        """Static getitem: items is a tuple of slice/int/None (torch frontend
        getitem; reference OP_SLICE)."""
        from .ops.tensor_ops import encode_slice_items

        return self._unary(OperatorType.OP_SLICE, x,
                           {"items": encode_slice_items(items)}, name)

    def constant(self, value, dtype: Optional[DataType] = None, name=None):
        """Frozen host tensor as a graph node (traced buffers like
        position_ids; reference analog: non-trainable weight tensors)."""
        import numpy as np

        from .ffconst import jnp_to_dtype

        value = np.asarray(value)
        if dtype is None:
            dtype = jnp_to_dtype(value.dtype)
        return self._add_layer(OperatorType.OP_CONSTANT, [],
                               {"value": value}, dtype, name)

    def sdpa(self, q: Tensor, k: Tensor, v: Tensor,
             attn_mask: Optional[Tensor] = None, dropout: float = 0.0,
             causal: bool = False, scale: Optional[float] = None, name=None):
        """Attention core on pre-projected (batch, heads, seq, head_dim)
        tensors (torch F.scaled_dot_product_attention)."""
        inputs = [q, k, v] + ([attn_mask] if attn_mask is not None else [])
        return self._add_layer(OperatorType.OP_SDPA, inputs,
                               {"dropout": dropout, "causal": causal,
                                "scale": scale}, q.dtype, name)

    def lstm(self, input: Tensor, hidden_size: int,
             initial_state: Optional[Tensor] = None,
             name: Optional[str] = None) -> List[Tensor]:
        """LSTM over (batch, seq, dim) -> [(batch, seq, hidden),
        final_state (batch, 2*hidden)]. Reference: nmt/lstm.cu (cuDNN RNN);
        here a first-class op (ops/recurrent.py)."""
        inputs = [input] + ([initial_state] if initial_state is not None
                            else [])
        return self._add_layer(OperatorType.OP_LSTM, inputs,
                               {"hidden_size": hidden_size},
                               input.dtype, name)

    def concat(self, tensors: List[Tensor], axis: int, name=None):
        return self._add_layer(OperatorType.OP_CONCAT, list(tensors),
                               {"axis": axis}, tensors[0].dtype, name)

    def split(self, x, sizes: Union[int, List[int]], axis: int, name=None):
        if isinstance(sizes, int):
            dim = x.dims[axis % len(x.dims)]
            assert dim % sizes == 0
            sizes = [dim // sizes] * sizes
        outs = self._add_layer(OperatorType.OP_SPLIT, [x],
                               {"sizes": list(sizes), "axis": axis},
                               x.dtype, name)
        return outs if isinstance(outs, list) else [outs]

    def gather(self, x, index: Tensor, dim: int, name=None):
        return self._add_layer(OperatorType.OP_GATHER, [x, index],
                               {"dim": dim}, x.dtype, name)

    def cast(self, x, dtype: DataType, name=None):
        return self._add_layer(OperatorType.OP_CAST, [x],
                               {"target_dtype": dtype}, dtype, name)

    def mean(self, x, dims: Sequence[int], keepdims: bool = False, name=None):
        return self._unary(OperatorType.OP_MEAN, x,
                           {"axes": list(dims), "keepdims": keepdims}, name)

    def reduce_sum(self, x, axes: Sequence[int], keepdims: bool = False,
                   name=None):
        return self._unary(OperatorType.OP_REDUCE_SUM, x,
                           {"axes": list(axes), "keepdims": keepdims}, name)

    def top_k(self, x, k: int, sorted: bool = True, name=None,
              use_pallas: bool = False):
        # use_pallas AFTER name: positional reference-compat signature is
        # top_k(input, k, sorted, name) (flexflow_cffi surface)
        return self._add_layer(OperatorType.OP_TOPK, [x],
                               {"k": k, "sorted": sorted,
                                "use_pallas": use_pallas}, x.dtype, name)

    # ---- MoE (reference: src/ops/moe.cc, group_by.cc, aggregate.cc) -----------
    def group_by(self, input: Tensor, assign: Tensor, n: int,
                 alpha: float = 1.0, name=None) -> List[Tensor]:
        outs = self._add_layer(OperatorType.OP_GROUP_BY, [input, assign],
                               {"n": n, "alpha": alpha}, input.dtype, name)
        return outs if isinstance(outs, list) else [outs]

    def aggregate(self, gate_preds: Tensor, gate_assign: Tensor,
                  true_gate_assign: Tensor, full_gate_grads: Tensor,
                  exp_preds: List[Tensor], n: int, lambda_bal: float = 0.0,
                  name=None) -> Tensor:
        ins = [gate_preds, gate_assign, true_gate_assign, full_gate_grads] + \
            list(exp_preds)
        return self._add_layer(OperatorType.OP_AGGREGATE, ins,
                               {"n": n, "lambda_bal": lambda_bal},
                               exp_preds[0].dtype, name)

    def aggregate_spec(self, gate_preds, gate_assign, true_gate_assign,
                       full_gate_grads, exp_preds: List[Tensor], n: int,
                       lambda_bal: float = 0.0, name=None) -> Tensor:
        ins = [gate_preds, gate_assign, true_gate_assign, full_gate_grads] + \
            list(exp_preds)
        return self._add_layer(OperatorType.OP_AGG_SPEC, ins,
                               {"n": n, "lambda_bal": lambda_bal},
                               exp_preds[0].dtype, name)

    def cache(self, input: Tensor, num_batches: int, score_fn=None, name=None):
        return self._unary(OperatorType.OP_CACHE, input,
                           {"num_batches": num_batches, "score_fn": score_fn},
                           name)

    def moe(self, input: Tensor, num_exp: int, num_select: int,
            expert_hidden_size: int, alpha: float = 2.0,
            lambda_bal: float = 0.04) -> Tensor:
        """Composite MoE layer (reference: FFModel::moe, src/ops/moe.cc:20-45):
        gate dense -> softmax -> top_k -> group_by -> per-expert dense ->
        aggregate."""
        gate = self.dense(input, num_exp, name="moe_gate")
        gate = self.softmax(gate)
        topk_out = self.top_k(gate, num_select)
        topk_values, topk_assign = topk_out[0], topk_out[1]
        grouped = self.group_by(input, topk_assign, num_exp, alpha)
        exp_preds = [
            self.dense(g, expert_hidden_size,
                       activation=ActiMode.AC_MODE_RELU,
                       name=f"moe_expert_{i}")
            for i, g in enumerate(grouped)
        ]
        return self.aggregate(topk_values, topk_assign, topk_assign, gate,
                              exp_preds, num_exp, lambda_bal)

    def experts(self, dispatched: Tensor, out_dim: int,
                activation=ActiMode.AC_MODE_RELU, use_bias: bool = True,
                name=None) -> Tensor:
        """Batched expert FFN over a stacked (n, cap, d) dispatch (TPU-native
        form of the reference's per-expert dense nodes; see ops/moe_ops.py
        ExpertsOp). Expert-parallel shardable over the expert dim."""
        n = dispatched.dims[0]
        return self._unary(OperatorType.OP_EXPERTS, dispatched,
                           {"n": n, "out_dim": out_dim,
                            "activation": activation, "use_bias": use_bias},
                           name)

    def moe_experts(self, input: Tensor, num_exp: int, num_select: int,
                    expert_hidden_size: int, alpha: float = 2.0,
                    lambda_bal: float = 0.04) -> Tensor:
        """MoE layer through the batched Experts op: gate dense -> softmax ->
        top_k -> stacked group_by -> Experts (one bmm) -> aggregate. Same
        semantics as ``moe`` (reference src/ops/moe.cc:20-45) but
        expert-parallel-searchable: the Unity search can shard the expert
        dim (EP), which XLA lowers to a token all-to-all over ICI."""
        gate = self.dense(input, num_exp, name="moe_gate")
        gate = self.softmax(gate)
        topk_out = self.top_k(gate, num_select)
        topk_values, topk_assign = topk_out[0], topk_out[1]
        grouped = self._add_layer(
            OperatorType.OP_GROUP_BY, [input, topk_assign],
            {"n": num_exp, "alpha": alpha, "stacked": True},
            input.dtype, "moe_group_by")
        exp_out = self.experts(grouped, expert_hidden_size,
                               name="moe_experts")
        return self.aggregate(topk_values, topk_assign, topk_assign, gate,
                              [exp_out], num_exp, lambda_bal)

    # ======================================================== observability ==
    def _obs_tracer(self):
        """The process tracer, auto-enabled the first time when the config
        asks for a trace file (obs stays a no-op singleton otherwise)."""
        from .obs import enable, get_tracer

        t = get_tracer()
        if not t.enabled and self.config.trace_file:
            t = enable(trace_file=self.config.trace_file)
        return t

    def get_telemetry(self):
        """StepTelemetry of the most recent fit() (None when observability
        was disabled for that run)."""
        return getattr(self, "_telemetry", None)

    def _make_telemetry(self, tracer, batch_size: int, phase: str):
        """A StepTelemetry when either sink wants one, else None — the
        None-ness is the hot loop's single instrumentation gate.
        ``_telemetry_requested`` is the in-process opt-in used by callers
        that consume get_telemetry() directly (keras TelemetryCallback).
        It is CONSUMED here (one fit per arm): if the requester dies before
        its cleanup hook, at most one later fit runs instrumented."""
        requested = getattr(self, "_telemetry_requested", False)
        if requested:
            self._telemetry_requested = False
        if not (self.config.telemetry_file or tracer.enabled or requested):
            return None
        from .obs.telemetry import (StepTelemetry, detect_peak_flops,
                                    model_flops_per_step)

        tel = StepTelemetry(batch_size=batch_size, phase=phase)
        try:
            if self.pcg is not None:
                tel.flops_per_step = model_flops_per_step(self.pcg)
        except Exception:
            pass
        peak = detect_peak_flops()  # per chip
        if peak is not None:
            # the step's model FLOPs cover the whole global batch, executed
            # across the chips the step actually runs on — MFU divides by
            # the EXECUTOR MESH's peak (a sub-mesh run must not be judged
            # against idle chips)
            if self.mesh is not None:
                n_chips = int(self.mesh.devices.size)
            else:
                import jax

                n_chips = len(jax.devices())
            peak *= max(n_chips, 1)
        tel.peak_flops = peak
        return tel

    # ============================================================== compile ==
    def compile(self, optimizer: Optional[Optimizer] = None,
                loss_type: LossType = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                metrics: Optional[List[MetricsType]] = None,
                comp_mode: CompMode = CompMode.COMP_MODE_TRAINING,
                strategy=None, strategy_fn=None,
                final_tensor: Optional[Tensor] = None) -> None:
        """Traced wrapper over :meth:`_compile_impl` — the whole lowering
        pipeline (PCG build, strategy search, executor + param init) lands as
        one "compile" span in the obs trace. The explicit signature is kept
        in sync with ``_compile_impl`` (it IS the public API surface the
        frontends introspect)."""
        tracer = self._obs_tracer()
        with tracer.span("compile", layers=len(self._layers)):
            self._compile_impl(optimizer, loss_type, metrics, comp_mode,
                               strategy, strategy_fn, final_tensor)
        if tracer.enabled and self.config.trace_file:
            # flush after each top-level phase so compile-only sessions
            # (and crashes later on) still leave a loadable trace
            tracer.write(self.config.trace_file)

    def _compile_impl(self, optimizer: Optional[Optimizer] = None,
                      loss_type: LossType = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                      metrics: Optional[List[MetricsType]] = None,
                      comp_mode: CompMode = CompMode.COMP_MODE_TRAINING,
                      strategy=None, strategy_fn=None,
                      final_tensor: Optional[Tensor] = None) -> None:
        """Lower the Layer graph to a PCG, pick a strategy, build the executor
        (reference pipeline: src/runtime/model.cc:2803, SURVEY §3.3).

        final_tensor: anchor the loss/outputs to this tensor instead of the
        graph sink (needed for multi-output frontends, e.g. HF ModelOutput
        dicts where last_hidden_state is not a sink)."""
        from .execution.executor import Executor
        from .parallel.mesh import build_mesh, mesh_for_strategy
        from .parallel.pcg import PCG
        from .parallel.strategy import Strategy, data_parallel_strategy
        from .ops.base import op_class_for
        from .resilience.preflight import (preflight_config,
                                           preflight_strategy)

        # flag-combination sanity before any expensive work (ISSUE 5;
        # parse-time single-flag checks live in FFConfig.parse_args, this
        # covers programmatic attribute assignment too)
        preflight_config(self.config)
        if optimizer is not None:
            self.optimizer = optimizer
        if self.optimizer is None:
            self.optimizer = SGDOptimizer(self)
        self.loss_type = loss_type
        self.metrics_obj = Metrics(loss_type, metrics or [])
        # each compile decides afresh whether the export slot was consumed
        # by a --search-num-* target-machine strategy
        self._exported_search_target = False
        # ranked fallback candidates from the previous search do not carry
        # over: _run_search repopulates them when this compile searches
        self._search_result = None
        self._strategy_candidates = []

        # -- create_operators_from_layers (model.cc:2785) -----------------------
        pcg = self.create_pcg()

        # final op = last compute node (the reference uses the graph's sink)
        if final_tensor is not None:
            final = pcg.nodes[self._tensor_to_node[final_tensor.guid]]
            self.final_out_idx = final_tensor.owner_idx or 0
        else:
            sinks = [n for n in pcg.sinks()
                     if n.op.op_type != OperatorType.OP_INPUT]
            final = sinks[-1]
            self.final_out_idx = 0
        self.final_guid = final.guid
        repl_labels = final.op.op_type == OperatorType.OP_AGG_SPEC

        # -- mesh + strategy ----------------------------------------------------
        import jax

        if self.config.debug_nans:
            jax.config.update("jax_debug_nans", True)
        devices = jax.devices()
        n_dev = len(devices)
        # elastic restart (resilience/elastic.py): a degraded-topology
        # restore re-plans for the SURVIVING device count, which may be a
        # strict subset of what this host still enumerates
        elastic_n = getattr(self, "_elastic_n_dev", None)
        if elastic_n:
            n_dev = min(int(elastic_n), n_dev)
        if strategy_fn is not None:
            strategy = strategy_fn(pcg)
        if strategy is not None:
            # explicit strategy (hand-written or search output) — the
            # untrusted input: preflight BEFORE building the mesh so an
            # indivisible plan dies with an actionable error, not a
            # mesh-construction assert or an XLA sharding failure
            preflight_strategy(pcg, strategy, n_dev=n_dev,
                               batch_size=self.config.batch_size)
            self.strategy = strategy
            self.mesh = mesh_for_strategy(self.config, strategy)
        elif self.config.import_strategy_file:
            with open(self.config.import_strategy_file) as f:
                self.strategy = Strategy.from_json(f.read(), pcg)
            preflight_strategy(pcg, self.strategy, n_dev=n_dev,
                               batch_size=self.config.batch_size)
            self.mesh = mesh_for_strategy(self.config, self.strategy)
        elif self.config.only_data_parallel or (
                n_dev == 1 and not (self.config.search_num_nodes > 0
                                    or self.config.search_num_workers > 0)):
            # --search-num-* must still reach _run_search on a 1-device host:
            # exporting a strategy for a bigger target machine from a small
            # one is the flags' whole workflow (graph.cc:1892-1897)
            if self.config.mesh_shape:
                # honor an explicit user mesh: batch shards over the first axis
                self.mesh = build_mesh(self.config)
                axes = tuple(self.mesh.axis_names)
                self.strategy = data_parallel_strategy(
                    pcg, int(self.mesh.shape[axes[0]]), axis_names=axes)
            else:
                self.strategy = data_parallel_strategy(pcg, n_dev)
                self.mesh = build_mesh(self.config, mesh_shape=(n_dev,),
                                       axis_names=("data",))
        else:
            # Unity search (SURVEY §7 stage 5); falls back to DP if the
            # search finds nothing better
            self.strategy = self._run_search(pcg, n_dev)
            self.mesh = mesh_for_strategy(self.config, self.strategy)

        # --static-analysis strict: ShardLint judges EVERY compiled plan
        # (explicit, imported, or searched) before the executor exists —
        # the compile-time analog of cascade stage 0 (ISSUE 7). The
        # default "on" runs analysis only where it replaces dynamic work
        # (cascade, search pruning, pre-serve), keeping plain compiles at
        # zero added cost.
        if (getattr(self.config, "static_analysis", "on") or "on") == \
                "strict" and self.strategy is not None:
            from .analysis import StaticAnalysisError, analyze_model

            # the SAME full pass the cascade's stage 0 runs (remat plan
            # resolved, donation contract included) — one entry point, so
            # the two paths cannot drift; pcg is passed explicitly
            # because self.pcg binds later in compile
            report = analyze_model(self, pcg=pcg)
            if report.errors:
                raise StaticAnalysisError(
                    report, context="compile under --static-analysis "
                    "strict")

        if self.config.export_strategy_file and \
                not getattr(self, "_exported_search_target", False):
            with open(self.config.export_strategy_file, "w") as f:
                f.write(self.strategy.to_json(pcg))
        if self.config.export_strategy_computation_graph_file:
            with open(self.config.export_strategy_computation_graph_file,
                      "w") as f:
                f.write(pcg.to_dot(
                    include_costs=self.config.include_costs_dot_graph))

        # -- fusion (model.cc:2965-3040, gated by --fusion) ---------------------
        if self.config.perform_fusion:
            from .ops.fused import apply_fusion

            pcg, n_fused, fusion_remap = apply_fusion(
                pcg, self.strategy, barrier_guids=(self.final_guid,))
            if n_fused:
                if final_tensor is not None:
                    # the barrier guarantees the anchor is unfused or a
                    # region tail; follow the remap either way
                    new_guid, new_idx = fusion_remap[self.final_guid]
                    self.final_guid = new_guid
                    if new_idx >= 0:
                        self.final_out_idx = new_idx
                    final = pcg.nodes[self.final_guid]
                else:
                    sinks = [n for n in pcg.sinks()
                             if n.op.op_type != OperatorType.OP_INPUT]
                    final = sinks[-1]
                    self.final_guid = final.guid
                    self.final_out_idx = 0
                repl_labels = final.op.op_type == OperatorType.OP_AGG_SPEC

        # -- label tensor (model.cc:3090-3124) ----------------------------------
        out_shape = final.out_shapes[self.final_out_idx]
        if loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
            label_shape = (out_shape[0], 1)
            label_dtype = DataType.DT_INT32
        else:
            label_shape = out_shape
            label_dtype = final.out_dtypes[self.final_out_idx]
        self.label_tensor = Tensor(label_shape, label_dtype, name="label",
                                   model=self)

        self.pcg = pcg
        self.executor = Executor(pcg, self.mesh, self.strategy, loss_type,
                                 self.metrics_obj, self.optimizer, self.config,
                                 self.final_guid, label_dtype, repl_labels,
                                 final_out_idx=self.final_out_idx)
        self.params = self.executor.init_params(self.config.numpy_seed())
        self.opt_state = self.optimizer.init_state(self.params)

        # searched GPipe pipeline: training routes through PipelineTrainer
        # on a (pp, dp) grid seeded with the SAME initialized params; fit
        # copies the trained weights back so eval/predict/checkpoint see
        # them (reference: OP_PIPELINE is enum-only — this is beyond parity)
        self._pipeline_trainer = None
        if getattr(self.strategy, "pipeline", None):
            from .execution.remat import resolve_stage_remat
            from .parallel.pipeline import PipelineTrainer, resolve_schedule

            pp, pdp, n_micro = self.strategy.pipeline
            # schedule: --schedule flag > searched strategy.schedule >
            # classic gpipe (parallel.pipeline.resolve_schedule)
            sched, v = resolve_schedule(self.config, self.strategy)
            self._pipeline_trainer = PipelineTrainer(
                self, pp=pp, dp=pdp, n_micro=n_micro,
                optimizer=self.optimizer, loss_type=loss_type,
                init_params=False,  # fit() seeds from the live params
                # stage remat: --remat flag > searched level > GPipe full
                remat=resolve_stage_remat(self.config, self.strategy),
                schedule=sched, virtual_stages=v)

    def create_pcg(self):
        """Layer graph -> PCG (reference: create_operators_from_layers,
        src/runtime/model.cc:2785). Usable standalone for search experiments
        without allocating parameters."""
        from .parallel.pcg import PCG
        from .ops.base import op_class_for

        pcg = PCG()
        tensor_to_out: Dict[int, Tuple[int, int]] = {}
        for t in self._input_tensors:
            node = pcg.add_node(
                op_class_for(OperatorType.OP_INPUT)(
                    t.name, {"shape": t.dims, "dtype": t.dtype}, t.dtype, 0),
                [])
            tensor_to_out[t.guid] = (node.guid, 0)
            self._tensor_to_node[t.guid] = node.guid
        for layer in self._layers:
            op = op_class_for(layer.op_type)(
                layer.name, layer.attrs, layer.data_type,
                num_inputs=len(layer.inputs))
            inputs = [tensor_to_out[t.guid] for t in layer.inputs]
            node = pcg.add_node(op, inputs)
            self._layer_to_node[layer.guid] = node.guid
            for i, t in enumerate(layer.outputs):
                tensor_to_out[t.guid] = (node.guid, i)
                self._tensor_to_node[t.guid] = node.guid
        self.pcg = pcg
        return pcg

    def _run_search(self, pcg, n_dev):
        from .parallel.strategy import data_parallel_strategy

        try:
            from .search.unity import unity_search
        except ImportError:
            return data_parallel_strategy(pcg, n_dev)
        # --search-num-nodes/--search-num-workers: search for a TARGET
        # machine that may differ from the one we are running on (reference:
        # graph.cc:1892-1897 overrides numNodes/workersPerNode for the
        # search only — the export-strategy-for-a-bigger-machine workflow)
        n_search = n_dev
        if self.config.search_num_nodes > 0 or \
                self.config.search_num_workers > 0:
            nodes = (self.config.search_num_nodes
                     if self.config.search_num_nodes > 0
                     else self.config.num_nodes)
            workers = (self.config.search_num_workers
                       if self.config.search_num_workers > 0
                       else max(self.config.workers_per_node, 1))
            n_search = max(nodes * workers, 1)
        if n_search != n_dev:
            # searched strategy targets a different chip count: export it
            # (that is what the flags are for), then run data-parallel on
            # the machine we actually have. Without an export file the
            # search would burn its whole budget producing nothing — skip.
            if self.config.export_strategy_file:
                # multi-node target: the machine model carries the DCN
                # factor so the search prices inter-node collectives
                # (reference: EnhancedMachineModel, simulator.h:212-606)
                machine = None
                # unity_search only reads the file when version == 1, so a
                # file set under version 0 must not suppress the detected
                # multi-node model (it would silently drop the host factor)
                file_used = (self.config.machine_model_version == 1
                             and self.config.machine_model_file)
                if nodes > 1 and n_search % nodes == 0 and not file_used:
                    from .search.machine_model import TPUMachineModel

                    # num_hosts at construction so the per-slice torus
                    # invariant (prod == chips per slice) holds
                    machine = TPUMachineModel.detect(n_search,
                                                     num_hosts=nodes)
                target_pcg = pcg.copy()
                strat = unity_search(target_pcg, self.config, n_search,
                                     machine=machine,
                                     protected_guids=(self.final_guid,))
                with open(self.config.export_strategy_file, "w") as f:
                    f.write(strat.to_json(target_pcg))
                self._exported_search_target = True
            else:
                import warnings

                warnings.warn(
                    "--search-num-nodes/--search-num-workers target "
                    f"{n_search} devices but {n_dev} are available and no "
                    "--export-strategy file is set; skipping the target "
                    "search and running data-parallel")
            return data_parallel_strategy(pcg, n_dev)
        # the final (loss-anchored) node must survive graph rewrites so the
        # label tensor and executor anchor stay valid (the reference protects
        # its sink the same way via the output-shape contract).
        # _search_sim: an elastic restart hands the previous search's warm
        # Simulator in so the re-plan reuses its memoized delta-cost tables
        from .search.unity import SearchResult

        res = unity_search(pcg, self.config, n_dev,
                           protected_guids=(self.final_guid,),
                           return_result=True,
                           sim=getattr(self, "_search_sim", None))
        if isinstance(res, SearchResult):
            # ranked top-K fallback chain (ISSUE 5): kept on the model so
            # the strategy-safety cascade can degrade through runners-up
            # when the winner fails to compile / OOMs / fails the audit
            self._search_result = res
            self._strategy_candidates = list(res.ranked)
            # warm search simulator (ISSUE 8): the drift sentinel's closed
            # loop repairs THIS ruler (selective delta-cost invalidation)
            # and an elastic restart reuses its memoized tables. A new
            # search ruler obsoletes any cached sentinel sim/history from
            # an earlier compile — the loop must repair the sim that
            # ranked the LIVE plan, not a predecessor's
            self._search_sim = res.sim
            self._calibration_sim = None
            self._drift_sentinel = None
            return res.strategy
        return res  # search found nothing: plain data-parallel Strategy

    # ============================================================ training ==
    def _next_rng(self):
        import jax

        self._rng_counter += 1
        return jax.random.PRNGKey(
            self.config.numpy_seed() * 100003 + self._rng_counter)

    def _as_input_list(self, x) -> List[np.ndarray]:
        if isinstance(x, (list, tuple)):
            return [np.asarray(a) for a in x]
        return [np.asarray(x)]

    def _prep_label(self, y: np.ndarray) -> np.ndarray:
        y = np.asarray(y)
        if self.loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
            if y.ndim >= 2 and int(np.prod(y.shape[1:])) > 1:
                return y.astype(np.int32)  # token-level targets (causal LM)
            y = y.reshape(y.shape[0], 1).astype(np.int32)
        return y

    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: Optional[int] = None, callbacks=None,
            recompile_state=None, shuffle: bool = True,
            chaos=None) -> PerfMetrics:
        """Training loop (reference: flexflow_cffi.py:2058-2100 — per batch:
        next_batch -> forward -> zero_gradients -> backward -> update inside a
        Legion trace; here one fused jitted step per batch).

        CacheOps in the graph are threaded as a device-side cache pytree;
        their ``score_fn`` runs host-side every ``num_batches`` steps and the
        scores land in ``self.cache_scores`` — the signal the MoE
        cache/recompile pairing consumes (reference: cache.cc:291 +
        moe.cc:180,204). ``recompile_state`` hooks the per-iteration dynamic
        recompile check (FFModel::recompile_on_condition, model.cc:2422).

        Fault tolerance (ISSUE 4, docs/fault_tolerance.md): when the config
        asks for it (``--checkpoint-dir``/``--checkpoint-every``/
        ``--resume``/``--max-bad-steps``) a ResilienceSession wraps the
        loop — periodic async atomic checkpoints, SIGTERM/SIGINT preemption
        flush, exact resume of the data-pipeline cursor, and the divergence
        sentinel that skips non-finite steps and rolls back to the last
        committed checkpoint. ``chaos`` takes a
        ``resilience.ChaosPlan`` for deterministic fault injection (tests).
        All of this is scoped to the SPMD path; the GPipe pipeline trainer
        checkpoints only via explicit ``save_checkpoint`` calls."""
        import jax

        assert self.executor is not None, "call compile() first"
        if recompile_state is not None:
            self._recompile_state = recompile_state
            recompile_state.ffmodel = self
        xs = self._as_input_list(x)
        y = self._prep_label(y)
        batch_size = batch_size or self.config.batch_size
        epochs = epochs or self.config.epochs
        from .resilience.preflight import validate_batch

        # fail on a mis-shaped/mis-typed batch HERE, naming the tensor and
        # axis — not as a cryptic XLA error mid-epoch (ISSUE 5 satellite)
        validate_batch(self, xs, y, phase="fit")
        if self._pipeline_trainer is not None:
            if chaos is not None:
                raise ValueError(
                    "chaos injection targets the SPMD fit loop; the GPipe "
                    "pipeline trainer is not covered (see "
                    "docs/fault_tolerance.md)")
            return self._fit_pipeline(xs, y, batch_size, epochs, shuffle)
        # strategy-safety cascade (ISSUE 5, docs/strategy_safety.md): when
        # armed (--audit-strategy / --memory-budget-mb / strategy chaos),
        # verify the plan BEFORE the loop — preflight, compile + one probe
        # step, memory budget, parallel-correctness audit — degrading
        # through the search's ranked candidates on failure. May swap
        # self.executor/strategy, so it runs before anything binds them.
        from .resilience.fallback import StrategyCascade

        cascade = StrategyCascade.maybe_create(self, chaos)
        self._last_cascade = cascade
        if cascade is not None:
            cascade.preverify(xs, y, batch_size)
        from .resilience.session import ResilienceSession

        session = None
        if ResilienceSession.wanted(self.config, chaos):
            session = ResilienceSession(self, chaos=chaos)
            session.install_signal_handlers()
        guard = session.guard if session is not None else None
        # guarded mode dispatches through `guard` (which owns its jitted
        # variant); step_fn is the unguarded path's handle only
        step_fn = (None if guard is not None
                   else self.executor.make_train_step())
        from .data.dataloader import batch_iterator, prefetch_iterator

        in_shardings = [self.executor.batch_sharding(a.ndim) for a in xs]
        label_sharding = self.executor.batch_sharding(y.ndim)

        self._perf = PerfMetrics()
        num_samples = xs[0].shape[0]
        steps_per_epoch = num_samples // batch_size
        epoch0, skip_batches = 0, 0
        step_count = 0
        executed_steps = 0  # actual dispatches: THROUGHPUT must not count
        # steps a preemption/resume skipped (step_count can also rewind on
        # rollback; replayed steps were genuinely executed and do count)
        self._preempted_at_step = None
        if session is not None:
            resumed = session.maybe_resume()
            if resumed is not None:
                step_count, epoch0, skip_batches = resumed
                if steps_per_epoch and skip_batches >= steps_per_epoch:
                    epoch0 += skip_batches // steps_per_epoch
                    skip_batches %= steps_per_epoch
        t0 = time.time()
        loss_val = None
        cache = (self.executor.init_cache()
                 if self.executor.cache_nodes else None)
        # observability: with both sinks off, `telemetry` is None and the hot
        # loop pays two `if x is not None` tests per step — no allocations,
        # no file I/O, no device syncs beyond the pre-existing ones
        tracer = self._obs_tracer()
        telemetry = self._make_telemetry(tracer, batch_size, "train")
        self._telemetry = telemetry
        if cascade is not None:
            # counters are final after preverify; the final strategy the
            # cascade settled on lands in the telemetry record
            cascade.merge_telemetry(telemetry)
        last_batch = None
        if self.config.profiling:
            self.profile_operators()
            t0 = time.time()  # per-op measurement must not skew THROUGHPUT
        # closed-loop calibration (ISSUE 8, docs/calibration.md): with
        # --profile-ops, ONE ProfiledStep pass per fit times every distinct
        # op shape on device, streams OpRecords to the JSONL profile +
        # tracer, feeds the drift sentinel, and (with --auto-recalibrate)
        # repairs the simulator's per-key calibration in place. A plain fit
        # pays one getattr.
        from .obs.drift import CalibrationLoop

        calib = CalibrationLoop.maybe_create(self)
        if calib is not None:
            calib.run_pass(xs, batch_size, telemetry, step=step_count)
            t0 = time.time()  # profiled pass must not skew THROUGHPUT
        # Legion Prof analog (-lg:prof_logfile): XLA trace of the whole loop,
        # viewable in TensorBoard/Perfetto (SURVEY §5 tracing subsystem)
        tracing = bool(self.config.profiler_trace_dir)
        if tracing:
            jax.profiler.start_trace(self.config.profiler_trace_dir)
        try:
            epoch = epoch0
            preempted = False
            while epoch < epochs:
                # shuffled epochs by default (the reference's loaders shuffle);
                # the shuffled path stages batches through the native C++
                # double-buffered BatchPipeline (data/dataloader.py).
                # start_batch replays an interrupted epoch's tail: the same
                # seed reproduces the shuffle, the cursor skips what the
                # restored checkpoint already consumed
                it = batch_iterator(xs + [y], batch_size, shuffle=shuffle,
                                    seed=self.config.numpy_seed() + epoch,
                                    start_batch=skip_batches)
                batch_in_epoch = skip_batches
                skip_batches = 0
                epoch_metrics = []  # device-side; folded at epoch end (async)
                recompiled = False
                rolled_back = False
                t_epoch = time.perf_counter()
                for batch in prefetch_iterator(
                        it, in_shardings + [label_sharding]):
                    bx, by = batch[:-1], batch[-1]
                    if session is not None and session.chaos is not None:
                        bx = session.chaos.poison_batch(step_count, bx)
                        session.chaos.maybe_preempt(step_count)
                    if telemetry is not None:
                        t_step = time.perf_counter()
                    step_ok = True
                    if guard is not None:
                        rng = self._next_rng()
                        if cache is not None:
                            outs, step_ok = guard(self.params, self.opt_state,
                                                  bx, by, rng, cache)
                            (self.params, self.opt_state, loss_val, m,
                             fresh) = outs
                        else:
                            outs, step_ok = guard(self.params, self.opt_state,
                                                  bx, by, rng)
                            self.params, self.opt_state, loss_val, m = outs
                            fresh = None
                    elif cache is not None:
                        (self.params, self.opt_state, loss_val, m,
                         fresh) = step_fn(self.params, self.opt_state, bx, by,
                                          self._next_rng(), cache)
                    else:
                        self.params, self.opt_state, loss_val, m = step_fn(
                            self.params, self.opt_state, bx, by,
                            self._next_rng())
                        fresh = None
                    if cache is not None and step_ok:
                        self._score_caches(cache, fresh, step_count)
                        cache.update(fresh)
                    step_count += 1
                    batch_in_epoch += 1
                    executed_steps += 1
                    if step_ok:
                        # a guarded bad step left params untouched; its
                        # NaN metrics must not poison the epoch fold
                        epoch_metrics.append(m)
                    loss_f = None
                    if telemetry is not None:
                        # observability is opt-in: the per-step sync it costs
                        # is what buys true step walls + the compile split
                        jax.block_until_ready(loss_val)
                        wall = time.perf_counter() - t_step
                        loss_f = float(loss_val) if step_ok else None
                        telemetry.record_step(wall, loss_f)
                        tracer.complete("train_step", wall, step=step_count,
                                        loss=loss_f)
                        last_batch = (bx, by)
                    if not step_ok:
                        session.record_fault(step_count - 1)
                        if guard.should_rollback:
                            step_count, epoch, skip_batches = \
                                session.rollback()
                            cache = (self.executor.init_cache()
                                     if self.executor.cache_nodes else None)
                            epoch_metrics = []  # poisoned partials discarded
                            rolled_back = True
                            break
                    if session is not None:
                        session.on_step(step_count, epoch, batch_in_epoch,
                                        steps_per_epoch)
                        if session.preempted:
                            # preemption grace window: flush a final
                            # committed checkpoint, then stop cleanly
                            self._preempted_at_step = step_count
                            session.note_preemption(step_count)
                            session.final_checkpoint(step_count, epoch,
                                                     batch_in_epoch,
                                                     steps_per_epoch)
                            preempted = True
                            break
                    if self._recompile_state is not None and \
                            self.recompile_on_condition(self._recompile_state):
                        # executor rebuilt: refresh the jitted step and cache,
                        # then RE-RUN this epoch on the new shardings (the break
                        # abandons the rest of its batches)
                        if guard is not None:
                            guard.executor = self.executor
                            guard.rebuild()
                        else:
                            step_fn = self.executor.make_train_step()
                        cache = (self.executor.init_cache()
                                 if self.executor.cache_nodes else None)
                        recompiled = True
                        break
                    if self.config.profiling and \
                            step_count % max(self.config.print_freq, 1) == 0:
                        # legacy stdout line, byte-identical to the pre-obs
                        # print so existing scripts keep parsing it
                        print(f"step {step_count}: loss="
                              f"{float(loss_val) if loss_f is None else loss_f:.4f}")
                # fold whatever the epoch produced (also the partial pre-recompile
                # batches — their steps trained the old graph but still count);
                # ONE host transfer for the whole epoch instead of a blocking
                # int()/float() per scalar per step
                if epoch_metrics:
                    for m in jax.device_get(epoch_metrics):
                        self._perf.update(m)
                if rolled_back:
                    continue  # re-enter at the restored epoch/batch cursor
                if preempted:
                    break
                if recompiled:
                    in_shardings = [self.executor.batch_sharding(a.ndim)
                                    for a in xs]
                    label_sharding = self.executor.batch_sharding(y.ndim)
                    continue  # restart the SAME epoch
                if telemetry is not None:
                    loss_f = (float(loss_val) if loss_val is not None
                              else None)
                    telemetry.record_epoch(loss_f)
                    tracer.complete("epoch", time.perf_counter() - t_epoch,
                                    index=epoch, loss=loss_f)
                if self.config.profiling:
                    print(f"epoch {epoch}: loss={float(loss_val):.4f}")
                epoch += 1
            if loss_val is not None:
                jax.block_until_ready(loss_val)
        finally:
            if tracing:
                jax.profiler.stop_trace()
            if session is not None:
                session.close(telemetry)
        elapsed = time.time() - t0
        self._last_fit_time = elapsed
        self._last_fit_samples = executed_steps * batch_size
        if elapsed > 0:
            throughput = self._last_fit_samples / elapsed
            if tracer.enabled:
                tracer.counter("throughput_samples_per_sec",
                               round(throughput, 2))
            if self.config.profiling:
                # legacy stdout line (kept verbatim for script compatibility)
                print(f"THROUGHPUT = {throughput:.2f} samples/s")
        if telemetry is not None:
            telemetry.finalize()
            if self.config.telemetry_file and last_batch is not None:
                from .obs.telemetry import capture_memory_analysis

                telemetry.device_memory = capture_memory_analysis(
                    self.executor, self.params, self.opt_state, *last_batch)
            if self.config.telemetry_file:
                telemetry.write(self.config.telemetry_file)
        if tracer.enabled and self.config.trace_file:
            tracer.write(self.config.trace_file)
        return self._perf

    def _param_stamp(self):
        """Identity snapshot of the param arrays. Holds REFERENCES (not raw
        ids) so CPython id reuse after a free can never fake a match."""
        return {(ln, wn): a for ln, ws in self.params.items()
                for wn, a in ws.items()}

    def _params_match_stamp(self) -> bool:
        old = getattr(self, "_pipeline_param_stamp", None)
        if old is None:
            return False
        new = self._param_stamp()
        return old.keys() == new.keys() and \
            all(new[k] is old[k] for k in new)

    def _fit_pipeline(self, xs, y, batch_size, epochs, shuffle) -> PerfMetrics:
        """GPipe training loop for a searched pipeline strategy: batches go
        through PipelineTrainer.train_step; the trained stage params are
        copied back into the Executor's pytree afterwards so
        eval/predict/checkpoint operate on the trained weights."""
        import jax

        from .data.dataloader import batch_iterator

        tr = self._pipeline_trainer
        # seed from the CURRENT executor params when they changed since the
        # last pipeline sync (post-compile weight edits: copy_torch_weights,
        # Layer.set_weights). Unchanged params keep the trainer's optimizer
        # state across fit() calls, like the SPMD path's opt_state.
        if tr.params is None or not self._params_match_stamp():
            tr.load_params(self.params)
        # the microbatch count was chosen for config.batch_size at search
        # time; re-derive it for the batch size actually passed
        if batch_size % tr.dp != 0:
            raise ValueError(
                f"pipeline strategy needs batch_size % dp == 0 "
                f"(batch {batch_size}, dp {tr.dp})")
        micro_ok = [m for m in (2 * tr.pp, tr.pp, 2, 1)
                    if batch_size % m == 0 and
                    (batch_size // m) % tr.dp == 0 and
                    # interleaved advances microbatches in rounds of pp
                    (tr.schedule != "interleaved" or m % tr.pp == 0)]
        if not micro_ok:
            raise ValueError(
                f"pipeline schedule {tr.schedule!r} found no microbatch "
                f"count for batch_size {batch_size} (pp={tr.pp}, "
                f"dp={tr.dp}); use a batch divisible by pp*dp")
        tr.n_micro = micro_ok[0]
        loss_key = {
            LossType.LOSS_CATEGORICAL_CROSSENTROPY: "cce_loss",
            LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
                "sparse_cce_loss",
            LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE: "mse_loss",
            LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE: "mse_loss",
        }.get(self.loss_type, "sparse_cce_loss")
        self._perf = PerfMetrics()
        tracer = self._obs_tracer()
        telemetry = self._make_telemetry(tracer, batch_size, "train_pipeline")
        self._telemetry = telemetry
        t0 = time.time()
        step = 0
        loss = None
        for epoch in range(epochs):
            it = batch_iterator(xs + [y], batch_size, shuffle=shuffle,
                                seed=self.config.numpy_seed() + epoch)
            t_epoch = time.perf_counter()
            for batch in it:
                bx, by = batch[:-1], batch[-1]
                t_step = time.perf_counter()
                loss = tr.train_step(list(bx), by, rng_seed=step)
                step += 1
                # loss-only metrics: train_step returns the scalar loss
                # (accuracy-style metrics need the eval path)
                loss_f = float(loss)
                if telemetry is not None:
                    wall = time.perf_counter() - t_step
                    telemetry.record_step(wall, loss_f)
                    tracer.complete("train_step", wall, step=step,
                                    loss=loss_f)
                self._perf.update({
                    "train_all": by.shape[0],
                    loss_key: loss_f * by.shape[0]})
                if self.config.profiling and \
                        step % max(self.config.print_freq, 1) == 0:
                    print(f"step {step}: loss={loss_f:.4f}")
            if telemetry is not None:
                telemetry.record_epoch(float(loss) if loss is not None
                                       else None)
                tracer.complete("epoch", time.perf_counter() - t_epoch,
                                index=epoch)
        for lname, ws in tr.export_params().items():
            for wname, arr in ws.items():
                cur = self.params[lname][wname]
                self.params[lname][wname] = jax.device_put(
                    np.asarray(arr, dtype=np.asarray(cur).dtype),
                    cur.sharding if hasattr(cur, "sharding") else None)
        # record the sync point: a following fit() without external weight
        # edits reuses the trainer's params AND optimizer state
        self._pipeline_param_stamp = self._param_stamp()
        self._last_fit_time = time.time() - t0
        self._last_fit_samples = step * batch_size
        if self._last_fit_time > 0:
            throughput = self._last_fit_samples / self._last_fit_time
            if tracer.enabled:
                tracer.counter("throughput_samples_per_sec",
                               round(throughput, 2))
            if self.config.profiling:
                print(f"THROUGHPUT = {throughput:.2f} samples/s")
        if telemetry is not None:
            telemetry.finalize()
            if self.config.telemetry_file:
                telemetry.write(self.config.telemetry_file)
        if tracer.enabled and self.config.trace_file:
            tracer.write(self.config.trace_file)
        return self._perf

    def eval(self, x=None, y=None, batch_size: Optional[int] = None
             ) -> PerfMetrics:
        """reference: flexflow_cffi.py:2102."""
        import jax

        xs = self._as_input_list(x)
        y = self._prep_label(y)
        batch_size = batch_size or self.config.batch_size
        from .resilience.preflight import validate_batch

        validate_batch(self, xs, y, phase="eval")
        estep = self.executor.make_eval_step()
        from .data.dataloader import batch_iterator

        tracer = self._obs_tracer()
        perf = PerfMetrics()
        t_eval = time.perf_counter()
        n_batches = 0
        loss_val = None
        for batch in batch_iterator(xs + [y], batch_size,
                                    drop_remainder=False):
            bx, by = batch[:-1], batch[-1]
            loss_val, m = estep(self.params, bx, by)
            # one host transfer per batch instead of one per metric scalar
            perf.update(jax.device_get(m))
            n_batches += 1
        if tracer.enabled:
            tracer.complete("eval", time.perf_counter() - t_eval,
                            batches=n_batches,
                            loss=(float(loss_val) if loss_val is not None
                                  else None))
            if self.config.trace_file:
                # eval-only / inference workloads must still get their
                # trace file — fit() is not the only exit point
                tracer.write(self.config.trace_file)
        return perf

    def predict(self, x, batch_size: Optional[int] = None) -> np.ndarray:
        """Batched inference forward (ISSUE 6 satellite). Two hot-path
        fixes over the per-batch loop this replaces: the final non-full
        batch from ``batch_iterator(drop_remainder=False)`` is PADDED to
        the full batch size (repeating the last row) and trimmed
        host-side — one jit specialization instead of a second compile for
        the tail shape — and results stay on device until ONE
        ``jax.device_get`` at the end instead of an ``np.asarray`` device
        sync per batch (the same batching PerfMetrics got in PR 1)."""
        import jax

        xs = self._as_input_list(x)
        batch_size = batch_size or self.config.batch_size
        from .resilience.preflight import validate_batch

        validate_batch(self, xs, None, phase="predict")
        fwd = self.executor.make_forward()
        from .data.dataloader import batch_iterator

        # static rows-per-sample of the final output (nmt-style graphs
        # flatten (b, t) -> b*t rows; trimming must drop whole samples)
        final = self.pcg.nodes[self.final_guid]
        out_rows = final.out_shapes[self.executor.final_out_idx][0]
        in_rows = self.pcg.input_nodes()[0].out_shapes[0][0]
        per_sample = out_rows // in_rows if in_rows and \
            out_rows % in_rows == 0 else None
        outs = []
        tail_rows = None
        for batch in batch_iterator(xs, batch_size, drop_remainder=False):
            nb = batch[0].shape[0]
            if nb < batch_size:
                if per_sample is None:
                    # output rows don't divide per sample: a padded batch
                    # could not be trimmed — pay the tail recompile
                    outs.append(fwd(self.params, batch))
                    continue
                pad = batch_size - nb
                batch = [np.concatenate([a, np.repeat(a[-1:], pad, axis=0)],
                                        axis=0) for a in batch]
                tail_rows = nb
            outs.append(fwd(self.params, batch))
        host = [np.asarray(o) for o in jax.device_get(outs)]
        if tail_rows is not None:
            host[-1] = host[-1][:tail_rows * per_sample]
        return np.concatenate(host, axis=0)

    def generate(self, prompts, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_inflight: Optional[int] = None,
                 max_decode_len: Optional[int] = None) -> List[List[int]]:
        """Autoregressive generation through the serving engine (ISSUE 6,
        docs/serving.md): prefill/decode split with a KV-cache pytree and
        continuous batching over ``--max-inflight`` decode slots. Greedy
        when ``temperature <= 0``; otherwise top-k filtered sampling (the
        Pallas top-k kernel where eligible). ``prompts`` is a list of
        token-id sequences; returns the generated continuations in
        submission order. The engine (and its compiled prefill/decode
        steps) is cached on the model across calls."""
        from .serving.engine import ServingEngine

        eng = getattr(self, "_serving_engine", None)
        if eng is None or eng.executor is not self.executor or \
                (max_inflight and eng.n_slots != max_inflight) or \
                (max_decode_len and
                 eng.requested_max_decode_len != max_decode_len):
            # eos_id stays per-call (threaded below), never baked into the
            # cached engine — a prior call's EOS must not truncate later
            # calls that didn't ask for one
            eng = ServingEngine(self, n_slots=max_inflight,
                                max_decode_len=max_decode_len)
            self._serving_engine = eng
        return eng.generate(prompts, max_new_tokens=max_new_tokens,
                            temperature=temperature, top_k=top_k,
                            eos_id=eos_id, seed=seed)

    # ---- manual-loop API parity (model.cc:2415-2469) --------------------------
    def init_operators(self) -> None:
        pass  # op state is created lazily by jit; kept for API parity

    def init_layers(self) -> None:
        pass  # reference name (flexflow_cffi.py init_layers); same no-op

    def forward(self, seq_length: Optional[int] = None) -> None:
        self._ensure_staged_batch()
        assert self._staged.get("batch") is not None, \
            "bind a batch first via next_batch/set_batch/set_tensor"
        fwd = self.executor.make_forward()
        xs, _ = self._staged["batch"]
        self._staged["logits"] = fwd(self.params, xs)

    def zero_gradients(self) -> None:
        self._staged.pop("grads", None)

    def backward(self, seq_length: Optional[int] = None) -> None:
        self._ensure_staged_batch()
        assert self._staged.get("batch") is not None, \
            "bind a batch first via next_batch/set_batch/set_tensor"
        if self._staged.get("label_placeholder"):
            raise RuntimeError(
                "backward() needs a real label batch: stage one via "
                "label_tensor.set_tensor(...) or set_batch(x, y) — refusing "
                "to train against the zero placeholder")
        import jax

        xs, y = self._staged["batch"]

        from .ops.base import OpContext

        def loss_fn(params):
            fwdvals = self.executor.forward_outputs(
                params, self.executor._bind_inputs(xs),
                OpContext(training=True, rng=self._next_rng(), mesh=self.mesh))
            logits = fwdvals[self.final_guid][self.executor.final_out_idx]
            return loss_value(self.loss_type, logits, y,
                              self.executor.repl_labels)

        self._staged["loss"], self._staged["grads"] = jax.value_and_grad(
            loss_fn)(self.params)

    def update(self) -> None:
        grads = self._staged.get("grads")
        assert grads is not None, "call backward() first"
        self.params, self.opt_state = self.optimizer.update(
            self.params, grads, self.opt_state)

    def set_batch(self, x, y) -> None:
        import jax

        xs = [jax.device_put(np.asarray(a)) for a in self._as_input_list(x)]
        self._staged["batch"] = (xs, jax.device_put(self._prep_label(y)))
        self._staged["label_placeholder"] = False  # y is a real label

    def _stage_tensor_value(self, tensor, np_array) -> None:
        """Tensor.set_tensor host staging (reference:
        ParallelTensorBase::set_tensor, parallel_tensor.cc:698). Staging only
        marks the batch dirty; composition + device_put happen lazily in the
        next forward/backward so the attach loop's set_tensor(input) +
        set_tensor(label) pair costs ONE host->device transfer per batch."""
        per = self._staged.setdefault("per_tensor", {})
        per[tensor.guid] = np.asarray(np_array)
        self._staged["per_tensor_dirty"] = True

    def _ensure_staged_batch(self) -> None:
        if not self._staged.get("per_tensor_dirty"):
            return
        per = self._staged.get("per_tensor", {})
        if not all(t.guid in per for t in self._input_tensors):
            return  # forward() will assert if nothing was ever bound
        xs = [per[t.guid] for t in self._input_tensors]
        placeholder = False
        if self.label_tensor is not None and self.label_tensor.guid in per:
            y = per[self.label_tensor.guid]
        elif self.label_tensor is not None:
            # forward-only staging: a zero placeholder keeps forward()
            # usable, but backward() refuses to train on it (below)
            y = np.zeros(self.label_tensor.dims,
                         dtype=dtype_to_jnp(self.label_tensor.dtype))
            placeholder = True
        else:
            return
        self.set_batch(xs, y)
        self._staged["label_placeholder"] = placeholder
        self._staged["per_tensor_dirty"] = False

    def _activation_value(self, tensor) -> np.ndarray:
        """get_tensor on an activation output: recompute forward on the
        staged batch and return that layer's output (reference analog:
        inline-mapping an output region, flexflow_cffi.py:601-658)."""
        from .ops.base import OpContext

        self._ensure_staged_batch()
        assert self._staged.get("batch") is not None, \
            f"bind a batch before reading activation {tensor.name}"
        xs, _ = self._staged["batch"]
        guid = self._tensor_to_node.get(tensor.guid)
        import jax

        # constant key: a read-only getter must not advance the training
        # rng stream (rng is unused under training=False anyway)
        vals = self.executor.forward_outputs(
            self.params, self.executor._bind_inputs(xs),
            OpContext(training=False, rng=jax.random.PRNGKey(0),
                      mesh=self.mesh))
        if guid not in vals:
            raise KeyError(
                f"{tensor.name}: its op was fused away; re-compile with "
                "--disable-fusion to inline-read intermediate activations")
        return np.asarray(vals[guid][tensor.owner_idx])

    def _staged_tensor_value(self, tensor) -> np.ndarray:
        per = self._staged.get("per_tensor", {})
        if tensor.guid in per:
            return np.asarray(per[tensor.guid])
        if self.label_tensor is not None and tensor is self.label_tensor:
            return np.zeros(self.label_tensor.dims,
                            dtype=dtype_to_jnp(self.label_tensor.dtype))
        raise KeyError(f"{tensor.name}: no value staged; call set_tensor")

    def reset_metrics(self) -> None:
        """reference: flexflow_cffi.py:1968."""
        self._perf = PerfMetrics()

    # ---- recompilation (reference: RecompileState, model.cc:2422) -------------
    def profile_operators(self, max_ops: int = 8) -> None:
        """Per-op timing printout behind ``--profiling`` (reference:
        FFConfig::profiling gating per-op kernel timing prints in every
        kernel wrapper, model.cc:110,155). The ``max_ops`` heaviest distinct
        op shapes (by analytical cost) are measured standalone via the
        simulator's microbench (the cudaEvent analog) and printed once —
        bounded because each measurement pays a jit compile."""
        if getattr(self, "_per_op_profiled", False) or self.pcg is None:
            return
        self._per_op_profiled = True
        from .search.machine_model import TPUMachineModel
        from .search.simulator import OpSharding, Simulator

        sim = Simulator(TPUMachineModel.detect(1))
        distinct = {}
        for node in self.pcg.compute_nodes():
            in_shapes = [self.pcg.nodes[g].out_shapes[i]
                         for g, i in node.inputs]
            key = sim._op_key(node, in_shapes)
            if key not in distinct:
                est = sim.op_cost(node, in_shapes, OpSharding()).forward_time
                distinct[key] = (est, node, in_shapes)
        heaviest = sorted(distinct.values(), key=lambda x: -x[0])[:max_ops]
        tracer = self._obs_tracer()
        # legacy stdout block kept verbatim; the same measurements also land
        # as machine-readable tracer events
        print("PER-OP PROFILE (fwd, measured standalone, "
              f"top {len(heaviest)} by estimated cost):")
        for _est, node, in_shapes in heaviest:
            try:
                t = sim.measure_operator_cost(node, in_shapes)
            except Exception:
                continue
            if tracer.enabled:
                tracer.event("per_op_profile", op=node.name,
                             op_type=node.op.op_type.name,
                             forward_us=round(t * 1e6, 1))
            print(f"  {node.name:24s} {node.op.op_type.name:28s} "
                  f"{t * 1e6:10.1f} us")

    def _score_caches(self, cache, fresh, step_count: int) -> None:
        """Host-side cache scoring (reference: cache.cc score tasks): every
        ``num_batches`` steps run each CacheOp's score_fn(cached, fresh)."""
        for node in self.executor.cache_nodes:
            nb = max(int(node.op.attrs.get("num_batches", 1) or 1), 1)
            if (step_count + 1) % nb:
                continue
            score_fn = node.op.attrs.get("score_fn")
            if score_fn is None:
                continue
            self.cache_scores[node.name] = float(score_fn(
                np.asarray(cache[node.name]), np.asarray(fresh[node.name])))

    def recompile_on_condition(self, recompile_state) -> bool:
        if recompile_state.trigger():
            recompile_state.alter(self)
            from .execution.recompile import recompile

            recompile(self)
            return True
        return False

    # ================================================== weights / dataloaders ==
    def create_data_loader(self, batch_tensor: Tensor, full_array: np.ndarray):
        from .data.dataloader import SingleDataLoader

        return SingleDataLoader(self, batch_tensor, full_array)

    def _locate_weight(self, tensor: Tensor) -> Tuple[str, str]:
        layer = tensor.owner_layer
        assert layer is not None and tensor.owner_idx < 0, \
            f"{tensor.name} is not a weight tensor"
        wname = tensor.name.split(".")[-1]
        return layer.name, wname

    def _get_weight_by_tensor(self, tensor: Tensor) -> np.ndarray:
        node_name, wname = self._locate_weight(tensor)
        return np.asarray(self.params[node_name][wname])

    def _set_weight_by_tensor(self, tensor: Tensor, arr: np.ndarray) -> None:
        import jax

        node_name, wname = self._locate_weight(tensor)
        cur = self.params[node_name][wname]
        arr = np.asarray(arr, dtype=np.asarray(cur).dtype)
        assert arr.shape == cur.shape, (arr.shape, cur.shape)
        self.params[node_name][wname] = jax.device_put(
            arr, cur.sharding if hasattr(cur, "sharding") else None)

    # ================================================================= misc ==
    def get_layers(self) -> Dict[int, Layer]:
        return {i: l for i, l in enumerate(self._layers)}

    def get_layer_by_id(self, layer_id: int) -> Layer:
        return self._layers[layer_id]

    def get_layer_by_name(self, name: str) -> Optional[Layer]:
        for l in self._layers:
            if l.name == name:
                return l
        return None

    def get_tensor_by_id(self, id: int) -> Tensor:
        """Weight tensors in declaration order (reference:
        flexflow_cffi.py:2179 — parameter id over the whole model)."""
        weights = [w for l in self._layers for w in l.weights]
        return weights[id]

    def get_perf_metrics(self) -> PerfMetrics:
        return self._perf

    def __repr__(self) -> str:
        return f"FFModel({len(self._layers)} layers)"
