"""Deterministic fault injection: the resilience paths must be testable.

Real failures (a TPU preemption SIGTERM, a NaN'd loss, bit rot in a
checkpoint) are rare and non-deterministic; this harness scripts them so
every recovery path runs on CPU in the fast test tier:

* ``ChaosPlan(nan_at_steps={K})`` — poison the batch of step K with NaN
  (the sentinel sees a genuinely non-finite loss/grad, exactly as a real
  divergence would produce one).
* ``ChaosPlan(preempt_at_step=M)`` — deliver a real ``SIGTERM`` to the
  process right before step M dispatches, driving the same signal handler
  a preemptible TPU pool would (``Model.fit`` installs it; the step
  finishes, a final checkpoint is flushed, fit returns).
* ``corrupt_checkpoint(path)`` — truncate / bit-flip / un-commit a written
  checkpoint, for exercising the commit-marker and checksum defenses.
* ``ChaosPlan(fail_compiles=N)`` — the strategy-safety cascade's
  compile check (resilience/fallback.py) raises a scripted XLA-compile
  failure for the first N candidates, driving the ranked-fallback path.
* ``ChaosPlan(wrong_reshard=True)`` — a wrong-reshard defect for the
  strategy-safety layer, in one of three modes
  (``wrong_reshard_mode``):

  - ``"duplicate"`` (graph-level): :func:`inject_wrong_reshard`
    inserts a REAL doubled-reduction node into the candidate PCG —
    statically visible (the analyzer's FF001 names it with zero step
    executions) AND dynamically real (the node scales its value by
    ``wrong_reshard_factor`` under a multi-device mesh, so the
    parallel-correctness audit's probe diverges from the single-device
    reference exactly like a double-counted allreduce). The static check
    and the dynamic audit are exercised against the same concrete
    defect. Note the end-to-end loss/grad-norm movement is damped by the
    loss (softmax shift tolerance): with the default ``--audit-tol``
    0.05 pass ``wrong_reshard_factor >= 3`` for a reliably-failing
    audit; the static FF001 catch is factor-independent.
  - ``"drop"`` (graph-level): remove a real reduction edge — the
    unreduced-partial FF001 class. Statically caught; dynamically
    invisible under XLA SPMD (the partitioner re-derives the psum from
    the shardings), which is precisely why the static check exists.
  - ``"scale"`` (legacy, the default): the auditor merely scales the
    candidate's reported grad norm by ``wrong_reshard_factor`` — no
    graph change; works on any graph, including pure-dp plans with no
    reduction to break.

Pass a plan to ``Model.fit(..., chaos=plan)``. Injection is once-per-step
by default so a run that rolls back and re-executes step K replays it
*clean* — the transient-fault model under which recovery must reconverge
to the uninterrupted trajectory. The strategy-safety injections follow the
same once model: the NEXT candidate compiles/audits clean, so the cascade
lands on a working fallback.
"""
from __future__ import annotations

import os
import signal
from typing import Iterable, List, Optional

from ..execution.checkpoint import COMMIT_MARKER, read_meta


class ChaosPlan:
    """Scripted fault schedule for one training run.

    Steps are global 0-based step indices (the value ``step_count`` holds
    as the step is about to dispatch). With ``once=True`` (default) each
    scripted fault fires a single time even if the step is re-executed
    after a rollback — the transient-fault model.
    """

    def __init__(self, nan_at_steps: Iterable[int] = (),
                 preempt_at_step: Optional[int] = None,
                 preempt_signal: int = signal.SIGTERM,
                 once: bool = True,
                 fail_compiles: int = 0,
                 wrong_reshard: bool = False,
                 wrong_reshard_factor: float = 2.0,
                 wrong_reshard_mode: str = "scale",
                 poison_decode_at: Optional[dict] = None,
                 storm_queue: Optional[dict] = None,
                 storm_max_new_tokens: int = 4,
                 preempt_serving_at: Optional[int] = None,
                 drop_devices_at: Optional[dict] = None):
        self.nan_at_steps = {int(s) for s in nan_at_steps}
        self.preempt_at_step = (None if preempt_at_step is None
                                else int(preempt_at_step))
        self.preempt_signal = preempt_signal
        self.once = once
        self.injected_nan_steps: List[int] = []
        self.preempted_at: Optional[int] = None
        self._nan_done: set = set()
        # serving extensions (ISSUE 9, serving/resilience.py): step indices
        # are DECODE-step counts of the serve loop (the serving analog of
        # the training step index). poison_decode_at {step: slot} NaN's one
        # slot's KV cache before that decode step dispatches (the guarded
        # decode's isfinite verdict sees genuinely non-finite logits, as a
        # flaky HBM bank would produce); storm_queue {step: [prompt, ...]}
        # submits a scripted burst through the engine's admission control
        # (driving shed-vs-accept deterministically); preempt_serving_at
        # delivers a REAL SIGTERM before that decode step (graceful-drain
        # path); drop_devices_at {step: surviving_n_dev} raises a scripted
        # device loss (auto elastic_replan path).
        self.poison_decode_at = {int(k): int(v) for k, v in
                                 (poison_decode_at or {}).items()}
        self.storm_queue = {int(k): list(v) for k, v in
                            (storm_queue or {}).items()}
        self.storm_max_new_tokens = int(storm_max_new_tokens)
        self.preempt_serving_at = (None if preempt_serving_at is None
                                   else int(preempt_serving_at))
        self.drop_devices_at = {int(k): int(v) for k, v in
                                (drop_devices_at or {}).items()}
        self.poisoned_decode_steps: List[int] = []
        self.storms_injected = 0
        self.serving_preempted_at: Optional[int] = None
        self.devices_dropped: List[int] = []
        self._decode_poison_done: set = set()
        self._storm_done: set = set()
        self._drop_done: set = set()
        # strategy-safety injections (resilience/fallback.py, audit.py)
        self.fail_compiles = int(fail_compiles)
        self.compile_failures_injected = 0
        self.wrong_reshard = bool(wrong_reshard)
        self.wrong_reshard_factor = float(wrong_reshard_factor)
        if wrong_reshard_mode not in ("scale", "drop", "duplicate"):
            raise ValueError(
                f"wrong_reshard_mode must be scale|drop|duplicate, got "
                f"{wrong_reshard_mode!r}")
        self.wrong_reshard_mode = wrong_reshard_mode
        self.wrong_reshards_injected = 0
        self.injected_defect = ""  # description of the graph-level defect

    # -- hooks called by Model.fit ------------------------------------------
    def poison_batch(self, step: int, bx):
        """Replace the first floating-point input of step ``step`` with NaN
        (dtype-preserving, so the jitted step does not retrace)."""
        if step not in self.nan_at_steps or \
                (self.once and step in self._nan_done):
            return bx
        import jax.numpy as jnp

        bx = list(bx)
        for i, a in enumerate(bx):
            if jnp.issubdtype(a.dtype, jnp.floating):
                bx[i] = a * jnp.asarray(float("nan"), dtype=a.dtype)
                self._nan_done.add(step)
                self.injected_nan_steps.append(step)
                return bx
        raise ValueError(
            "ChaosPlan.nan_at_steps needs a floating-point model input to "
            f"poison; step {step}'s batch has dtypes "
            f"{[str(a.dtype) for a in bx]}")

    # -- hooks called by the strategy-safety cascade / auditor --------------
    def strategy_chaos_pending(self) -> bool:
        """Any strategy-safety injection still pending? (What arms the
        fallback cascade's pre-fit verification.)"""
        return (self.compile_failures_injected < self.fail_compiles
                or (self.wrong_reshard
                    and (not self.once
                         or self.wrong_reshards_injected == 0)))

    def consume_compile_failure(self) -> bool:
        """True while scripted compile failures remain: the cascade's
        compile check treats it exactly like XLA rejecting the plan. Each
        call consumes one injection, so candidate N+fail_compiles compiles
        clean and the cascade lands on it."""
        if self.compile_failures_injected < self.fail_compiles:
            self.compile_failures_injected += 1
            return True
        return False

    def consume_wrong_reshard(self) -> float:
        """Grad-norm factor the auditor applies to the CANDIDATE probe —
        != 1.0 while a ``"scale"``-mode injection is pending, simulating a
        plan whose miscompiled resharding double-counts the gradient
        allreduce (loss matches the reference, the grad norm is off by
        the factor). Graph-level modes return 1.0: their defect is a real
        node in the graph (``apply_wrong_reshard``), not a reporting
        tweak. With ``once=True`` it fires on a single audit, so the
        cascade's next candidate audits clean."""
        if self.wrong_reshard and self.wrong_reshard_mode == "scale" and \
                (not self.once or self.wrong_reshards_injected == 0):
            self.wrong_reshards_injected += 1
            return self.wrong_reshard_factor
        return 1.0

    def graph_defect_pending(self) -> bool:
        """A graph-level wrong-reshard injection (mode drop/duplicate)
        that has not been applied yet — the cascade applies it to the
        model's live PCG at the top of ``preverify``."""
        return (self.wrong_reshard
                and self.wrong_reshard_mode in ("drop", "duplicate")
                and (not self.once or self.wrong_reshards_injected == 0))

    def apply_wrong_reshard(self, ffmodel) -> str:
        """Mutate the model's live PCG with the scripted reshard defect
        (``inject_wrong_reshard``). A graph with no reduction edge to
        break (e.g. a pure-dp plan) degrades to the legacy ``"scale"``
        simulation with a warning, so the injection never silently does
        nothing. Returns a description of what was injected."""
        try:
            desc = inject_wrong_reshard(ffmodel.pcg, ffmodel.strategy,
                                        mode=self.wrong_reshard_mode,
                                        factor=self.wrong_reshard_factor)
        except ValueError as e:
            import warnings

            warnings.warn(
                f"ChaosPlan wrong_reshard_mode="
                f"{self.wrong_reshard_mode!r}: no injection site ({e}); "
                "falling back to the legacy grad-norm scale simulation")
            self.wrong_reshard_mode = "scale"
            return ""
        self.wrong_reshards_injected += 1
        self.injected_defect = desc
        return desc

    def maybe_preempt(self, step: int) -> None:
        """Deliver the scripted preemption signal before step ``step``
        dispatches. Goes through ``os.kill`` so the REAL installed signal
        handler runs — the fit loop then finishes the in-flight step,
        flushes a final checkpoint and returns, exactly the TPU
        grace-window protocol."""
        if self.preempt_at_step is None or self.preempted_at is not None \
                or step != self.preempt_at_step:
            return
        self.preempted_at = step
        os.kill(os.getpid(), self.preempt_signal)

    # -- hooks called by the serving engine (ISSUE 9) -----------------------
    def maybe_poison_decode(self, step: int, state):
        """NaN one slot's KV-cache rows before decode step ``step``
        dispatches; returns ``(state, slot-or-None)``. Poisoning the cache
        (not the logits post-hoc) means the guarded decode step's fused
        isfinite check judges genuinely non-finite arithmetic — the same
        contract as ``poison_batch`` for the training sentinel. Floating
        leaves only (length cursors stay intact); batch-row independence
        of the decode ops keeps every other slot bitwise-untouched."""
        slot = self.poison_decode_at.get(step)
        if slot is None or (self.once and step in self._decode_poison_done):
            return state, None
        self._decode_poison_done.add(step)
        self.poisoned_decode_steps.append(step)
        return poison_decode_state(state, slot), slot

    def maybe_storm(self, step: int) -> List:
        """Scripted queue storm: the prompt burst to submit through the
        engine's admission control before decode step ``step`` (empty list
        when nothing is scheduled). Determinism: same script + same
        engine state -> same shed/accept pattern."""
        if step not in self.storm_queue or \
                (self.once and step in self._storm_done):
            return []
        self._storm_done.add(step)
        self.storms_injected += 1
        return list(self.storm_queue[step])

    def maybe_preempt_serving(self, step: int) -> None:
        """Deliver the scripted preemption signal before decode step
        ``step`` — through ``os.kill`` so the REAL flag-only handler
        (resilience/session.py) runs; the serve loop then drains
        gracefully exactly as a TPU-pool SIGTERM would make it."""
        if self.preempt_serving_at is None \
                or self.serving_preempted_at is not None \
                or step != self.preempt_serving_at:
            return
        self.serving_preempted_at = step
        os.kill(os.getpid(), self.preempt_signal)

    def maybe_drop_devices(self, step: int) -> Optional[int]:
        """Scripted device loss before decode step ``step``: returns the
        surviving device count (the engine raises ``DeviceLossError`` and
        auto-replans onto it) or None."""
        n = self.drop_devices_at.get(step)
        if n is None or (self.once and step in self._drop_done):
            return None
        self._drop_done.add(step)
        self.devices_dropped.append(step)
        return n


def poison_decode_state(state, slot: int):
    """NaN one slot's KV-cache rows of a serving ``DecodeState`` — the
    shared injection primitive behind ``ChaosPlan.maybe_poison_decode``
    (scripted per-step poison) and ``FleetChaosPlan``'s scripted replica
    degrade (a sustained poison *rate* on one replica, ISSUE 11).
    Floating leaves only; every other slot stays bitwise-untouched.

    Paged layout (ISSUE 12): the victim's rows live in POOL blocks, so
    the poison targets exactly the blocks its block-table row occupies
    (``tables[slot, :ceil(len/bs)]``) — never the shared GARBAGE block,
    whose contents must stay finite (a NaN there would leak into every
    co-batched slot's masked-out ``0 * garbage`` contributions and break
    the quarantine isolation this chaos exists to test)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..serving.kvcache import DecodeState

    if getattr(state, "block_tables", None) is not None:
        tables = np.asarray(state.block_tables)
        length = int(np.asarray(state.lengths)[slot])
        caches = dict(state.caches)
        # block_size from any pool leaf (n_blocks, h, bs, hd); a slot
        # with no occupied block (never prefilled) has nothing to poison
        for name, entry in state.caches.items():
            leaves = jax.tree_util.tree_leaves(entry)
            pool_like = [lf for lf in leaves if lf.ndim == 4]
            if not pool_like:
                # slot-major entries (LSTM carry): the ring rule applies
                caches[name] = jax.tree.map(
                    lambda lf: lf.at[slot].set(
                        jnp.asarray(float("nan"), lf.dtype))
                    if jnp.issubdtype(lf.dtype, jnp.floating) else lf,
                    entry)
                continue
            if length < 1:
                # never-admitted slot: it occupies NO pool block, so
                # there is nothing to poison — indexing by the slot
                # number here would NaN pool block == slot, which may
                # belong to a LIVE request in another slot
                continue
            bs = int(pool_like[0].shape[2])
            used = -(-length // bs)
            row = tables[slot, :used]
            # never the GARBAGE block (index 0): every co-batched slot's
            # masked-out reads touch it, and a freed slot's cleared row
            # points entirely at it
            row = row[row != 0]
            if row.size == 0:
                continue
            blocks = jnp.asarray(row, jnp.int32)

            def nanify(leaf):
                if not jnp.issubdtype(leaf.dtype, jnp.floating):
                    return leaf
                return leaf.at[blocks].set(
                    jnp.asarray(float("nan"), leaf.dtype))

            caches[name] = jax.tree.map(nanify, entry)
        return DecodeState(caches=caches, lengths=state.lengths,
                           block_tables=state.block_tables)

    def nanify(leaf):
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        return leaf.at[slot].set(jnp.asarray(float("nan"), leaf.dtype))

    caches = {name: jax.tree.map(nanify, entry)
              for name, entry in state.caches.items()}
    return DecodeState(caches=caches, lengths=state.lengths)


class FleetChaosPlan(ChaosPlan):
    """Scripted fleet-level fault schedule (ISSUE 11, serving/fleet.py).

    Extends :class:`ChaosPlan` with replica-granular faults, keyed on the
    router's FLEET TICK counter (one tick = every live replica advanced
    one scheduler action) — the fleet analog of the serving extensions'
    decode-step keys. All once-semantics, all runnable on CPU in tier-1:

    * ``kill_replica_at={tick: replica}`` — the replica dies abruptly
      mid-decode (DecodeState lost with its mesh); the router migrates
      its in-flight streams to survivors (re-prefilled from host-side
      committed tokens) and re-routes its queue.
    * ``degrade_replica_at={tick: replica}`` — from that tick on, every
      ``degrade_poison_every``-th decode step on the replica NaNs one
      live slot's KV rows (a sustained decode-poison rate, as a flaky
      HBM bank would produce): the quarantine-rate passive signal should
      open the replica's circuit breaker. Cleared by ``rejoin_at``.
    * ``partition_at={tick: replica}`` — router↔replica dispatches raise
      timeouts for ``partition_ticks`` ticks (the replica itself is
      healthy; the router just cannot reach it).
    * ``drain_replica_at={tick: replica}`` — scripted ``fleet.drain``
      (the rolling zero-downtime restart path).
    * ``rejoin_at={tick: replica}`` — a killed/drained/degraded replica
      re-enters through half-open probation (probe decode gates it).
    * ``traffic_step_at={tick: (per_tick, ticks)}`` — a sustained
      traffic step (ISSUE 19): starting at ``tick``, inject ``per_tick``
      synthetic ``storm_tenant`` requests through the REAL fleet door
      every tick for ``ticks`` ticks — the scripted 4x surge the
      autoscaler must absorb.
    * ``tenant_storm_at={tick: (tenant, n)}`` — a one-shot burst of
      ``n`` requests from one tenant (once-semantics like every other
      fleet fault), for proving WFQ isolation under a misbehaving
      neighbor.
    * ``crash_at={tick: mode}`` — whole-PROCESS death mid-serve
      (ISSUE 20): ``"sigkill"`` delivers a real ``SIGKILL`` to the
      current process (run the fleet in a child process for this mode);
      ``"hard"`` is the tier-1 CPU in-process stand-in — the fleet
      drops its journal group-commit buffer and raises
      :class:`~flexflow_tpu.serving.fleet.FleetCrashed` past every
      drain/finish path, so nothing gets to flush. Recovery goes
      through ``ServingFleet.recover()`` on the journal directory.
    """

    def __init__(self, kill_replica_at: Optional[dict] = None,
                 degrade_replica_at: Optional[dict] = None,
                 partition_at: Optional[dict] = None,
                 drain_replica_at: Optional[dict] = None,
                 rejoin_at: Optional[dict] = None,
                 partition_ticks: int = 8,
                 degrade_poison_every: int = 1,
                 traffic_step_at: Optional[dict] = None,
                 tenant_storm_at: Optional[dict] = None,
                 crash_at: Optional[dict] = None,
                 storm_tenant: str = "batch",
                 fleet_storm_max_new: int = 8,
                 fleet_storm_prompt_tokens: int = 3,
                 **kw):
        super().__init__(**kw)
        self.kill_replica_at = {int(k): int(v) for k, v in
                                (kill_replica_at or {}).items()}
        self.degrade_replica_at = {int(k): int(v) for k, v in
                                   (degrade_replica_at or {}).items()}
        self.partition_at = {int(k): int(v) for k, v in
                             (partition_at or {}).items()}
        self.drain_replica_at = {int(k): int(v) for k, v in
                                 (drain_replica_at or {}).items()}
        self.rejoin_at = {int(k): int(v) for k, v in
                          (rejoin_at or {}).items()}
        self.partition_ticks = int(partition_ticks)
        self.degrade_poison_every = max(int(degrade_poison_every), 1)
        self.traffic_step_at = {
            int(k): (int(v[0]), int(v[1]))
            for k, v in (traffic_step_at or {}).items()}
        self.tenant_storm_at = {
            int(k): (str(v[0]), int(v[1]))
            for k, v in (tenant_storm_at or {}).items()}
        self.crash_at = {int(k): str(v) for k, v in
                         (crash_at or {}).items()}
        self.storm_tenant = str(storm_tenant)
        self.fleet_storm_max_new = int(fleet_storm_max_new)
        self.fleet_storm_prompt_tokens = int(fleet_storm_prompt_tokens)
        self.storm_requests_injected = 0
        self.replicas_killed: List[int] = []
        self.replicas_degraded: List[int] = []
        self.replicas_partitioned: List[int] = []
        self.replicas_drained: List[int] = []
        self.replicas_rejoined: List[int] = []
        self.crashes_fired: List[str] = []
        self._fleet_done: set = set()

    def _fire(self, table: dict, tick: int, kind: str,
              log: List[int]) -> Optional[int]:
        replica = table.get(tick)
        if replica is None or (self.once and (kind, tick) in
                               self._fleet_done):
            return None
        self._fleet_done.add((kind, tick))
        log.append(replica)
        return replica

    def maybe_kill_replica(self, tick: int) -> Optional[int]:
        return self._fire(self.kill_replica_at, tick, "kill",
                          self.replicas_killed)

    def maybe_degrade_replica(self, tick: int) -> Optional[int]:
        return self._fire(self.degrade_replica_at, tick, "degrade",
                          self.replicas_degraded)

    def maybe_partition_replica(self, tick: int) -> Optional[int]:
        return self._fire(self.partition_at, tick, "partition",
                          self.replicas_partitioned)

    def maybe_drain_replica(self, tick: int) -> Optional[int]:
        return self._fire(self.drain_replica_at, tick, "drain",
                          self.replicas_drained)

    def maybe_rejoin_replica(self, tick: int) -> Optional[int]:
        return self._fire(self.rejoin_at, tick, "rejoin",
                          self.replicas_rejoined)

    def maybe_crash(self, tick: int) -> Optional[str]:
        """Process-death mode to fire this tick (``"hard"`` or
        ``"sigkill"``), or None. Same once-semantics as every other
        fleet fault."""
        mode = self.crash_at.get(int(tick))
        if mode is None or (self.once and ("crash", tick) in
                            self._fleet_done):
            return None
        self._fleet_done.add(("crash", tick))
        self.crashes_fired.append(mode)
        return mode

    def maybe_fleet_storm(self, tick: int) -> List[tuple]:
        """``[(tenant, n), ...]`` to inject at the fleet door this tick
        (ISSUE 19). One-shot storms honor the once-semantics key; a
        traffic step fires on every tick inside its window (each window
        tick is its own key, so ``once`` replays stay deterministic)."""
        tick = int(tick)
        out: List[tuple] = []
        burst = self.tenant_storm_at.get(tick)
        if burst is not None and not (self.once and
                                      ("tenant_storm", tick)
                                      in self._fleet_done):
            self._fleet_done.add(("tenant_storm", tick))
            out.append(burst)
        for start, (per_tick, n_ticks) in self.traffic_step_at.items():
            if start <= tick < start + n_ticks and not (
                    self.once and ("traffic_step", tick)
                    in self._fleet_done):
                self._fleet_done.add(("traffic_step", tick))
                out.append((self.storm_tenant, per_tick))
        self.storm_requests_injected += sum(n for _t, n in out)
        return out


class _InjectedReductionOp:
    """A REAL doubled-reduction node (lazy subclass factory below): its
    forward scales the value by ``chaos_factor`` — but only under a
    multi-device mesh, exactly like a double-counted allreduce, whose
    damage exists only in the parallel plan. The single-device audit
    reference therefore computes the TRUE value and the divergence is
    caught dynamically, while the analyzer's FF001 names the node
    statically (it is an OP_REDUCTION whose input is not a partial sum)."""

    def __new__(cls, *args, **kwargs):
        from ..parallel.parallel_op import ReductionOp

        class _Injected(ReductionOp):
            def forward(self, params, inputs, ctx):
                x = inputs[0]
                n_dev = (int(ctx.mesh.devices.size)
                         if ctx.mesh is not None else 1)
                factor = float(self.attrs.get("chaos_factor", 2.0))
                if n_dev > 1 and factor != 1.0:
                    import jax.numpy as jnp

                    x = x * jnp.asarray(factor, dtype=x.dtype)
                return [x]

        return _Injected(*args, **kwargs)


def inject_wrong_reshard(pcg, strategy, mode: str = "duplicate",
                         factor: float = 2.0) -> str:
    """Mutate ``pcg`` IN PLACE with a graph-level wrong-reshard defect.

    ``mode="duplicate"``: insert a :class:`_InjectedReductionOp` on the
    output edge of the first reduction site — an explicit ``OP_REDUCTION``
    node (a searched plan after ``insert_parallel_ops``) or a partial-sum
    producer whose ``output_spec`` performs the reduce (a hand/spec-based
    plan) — modelling a duplicated reduction edge. ``mode="drop"``:
    remove that reduction — splice out the ``OP_REDUCTION`` node, or strip
    the producer's reducing ``output_spec`` — modelling a dropped
    reduction edge (statically FF001-unreduced; numerically invisible
    under XLA SPMD, which is why only the static check can catch it).

    Raises ``ValueError`` when the graph has no reduction site (nothing
    to break — e.g. a pure data-parallel plan). Returns a description
    naming the defect and the node, mirroring the analyzer's diagnostic.
    """
    from ..analysis.interp import _partial_axes_produced
    from ..ffconst import OperatorType

    node_strats = strategy.node_strategies if strategy is not None else {}
    site = None  # (node, kind): kind in ("reduction", "producer")
    for node in pcg.compute_nodes():
        if node.op.op_type == OperatorType.OP_REDUCTION and \
                pcg.consumers(node.guid):
            site = (node, "reduction")
            break
    if site is None:
        for node in pcg.compute_nodes():
            ns = node_strats.get(node.guid)
            if _partial_axes_produced(node, ns) and \
                    ns is not None and ns.output_spec is not None and \
                    pcg.consumers(node.guid):
                site = (node, "producer")
                break
    if site is None:
        raise ValueError(
            "no reduction edge to break: the graph has no OP_REDUCTION "
            "node and no partial-sum producer with consumers")
    node, kind = site

    if mode == "drop":
        if kind == "reduction":
            src = node.inputs[0]
            for c in pcg.consumers(node.guid):
                cn = pcg.nodes[c]
                cn.inputs = [src if g == node.guid else (g, i)
                             for g, i in cn.inputs]
            del pcg.nodes[node.guid]
            pcg._order.remove(node.guid)
            node_strats.pop(node.guid, None)
            return (f"dropped reduction node '{node.name}' (consumers "
                    "splice through to its unreduced input)")
        ns = node_strats[node.guid]
        ns.output_spec = None
        return (f"dropped the reducing output constraint of partial-sum "
                f"producer '{node.name}'")

    if mode != "duplicate":
        raise ValueError(f"unknown graph defect mode {mode!r}")
    if kind == "reduction":
        axes = tuple(node.op.attrs.get("axes") or ())
        degree = int(node.op.attrs.get("degree", 2) or 2)
    else:
        axes = tuple(_partial_axes_produced(node,
                                            node_strats.get(node.guid)))
        axis_size = dict(zip(tuple(strategy.axis_names),
                             (int(s) for s in strategy.mesh_shape)))
        degree = int(axis_size.get(axes[0], 2)) if axes else 2
    op = _InjectedReductionOp(
        f"chaos_dup_reduction_{node.guid}",
        {"dim": 0, "degree": degree, "axes": axes,
         "chaos_factor": float(factor)},
        node.op.data_type, num_inputs=1)
    consumers = pcg.consumers(node.guid)
    first = pcg.nodes[consumers[0]]
    slot = [s for s, (g, _i) in enumerate(first.inputs)
            if g == node.guid][0]
    new = pcg.insert_node_on_edge(consumers[0], slot, op)
    # insert_node_on_edge rewires exactly one slot; a consumer referencing
    # the reduction output in SEVERAL input slots (e.g. add(r, r)) must
    # have all of them routed through the injected node, like the
    # consumers[1:] rewiring below — else one edge bypasses the defect
    first.inputs = [(new.guid, 0) if g == node.guid else (g, i)
                    for g, i in first.inputs]
    for c in consumers[1:]:
        cn = pcg.nodes[c]
        cn.inputs = [(new.guid, 0) if g == node.guid else (g, i)
                     for g, i in cn.inputs]
    return (f"duplicated the reduction after '{node.name}' as "
            f"'{new.op.name}' (x{factor:g} under a multi-device mesh)")


def corrupt_checkpoint(path: str, mode: str = "truncate") -> str:
    """Deterministically damage a committed checkpoint; returns a
    description of what was done.

    * ``truncate`` — cut the largest checksummed payload file in half
      (a killed copy / torn write).
    * ``flip``     — flip one byte in the middle of that file (bit rot).
    * ``uncommit`` — delete the commit marker (a writer that died between
      staging and commit; ``latest_checkpoint`` must skip the dir).
    """
    path = os.path.abspath(path)
    if mode == "uncommit":
        os.remove(os.path.join(path, COMMIT_MARKER))
        return f"removed {COMMIT_MARKER} from {path}"
    sums = read_meta(path).get("checksums", {})
    if not sums:
        raise ValueError(f"{path}: no checksummed payload files")
    # deterministic victim: the largest file, name as tie-break
    rel = max(sorted(sums), key=lambda r: (sums[r][1], r))
    fp = os.path.join(path, rel)
    size = os.path.getsize(fp)
    if mode == "truncate":
        with open(fp, "r+b") as f:
            f.truncate(max(size // 2, 0))
        return f"truncated {rel} from {size} to {max(size // 2, 0)} bytes"
    if mode == "flip":
        with open(fp, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        return f"flipped byte {size // 2} of {rel}"
    raise ValueError(f"unknown corruption mode {mode!r}")
