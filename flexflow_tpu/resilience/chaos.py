"""Deterministic fault injection: the resilience paths must be testable.

Real failures (a TPU preemption SIGTERM, a NaN'd loss, bit rot in a
checkpoint) are rare and non-deterministic; this harness scripts them so
every recovery path runs on CPU in the fast test tier:

* ``ChaosPlan(nan_at_steps={K})`` — poison the batch of step K with NaN
  (the sentinel sees a genuinely non-finite loss/grad, exactly as a real
  divergence would produce one).
* ``ChaosPlan(preempt_at_step=M)`` — deliver a real ``SIGTERM`` to the
  process right before step M dispatches, driving the same signal handler
  a preemptible TPU pool would (``Model.fit`` installs it; the step
  finishes, a final checkpoint is flushed, fit returns).
* ``corrupt_checkpoint(path)`` — truncate / bit-flip / un-commit a written
  checkpoint, for exercising the commit-marker and checksum defenses.
* ``ChaosPlan(fail_compiles=N)`` — the strategy-safety cascade's
  compile check (resilience/fallback.py) raises a scripted XLA-compile
  failure for the first N candidates, driving the ranked-fallback path.
* ``ChaosPlan(wrong_reshard=True)`` — the parallel-correctness auditor's
  candidate probe reports a grad-norm scaled by ``wrong_reshard_factor``
  (default 2.0 — the signature of a double-counted gradient allreduce
  from a miscompiled resharding rule), so the audit-reject path runs on
  CPU without a genuinely miscompiled plan.

Pass a plan to ``Model.fit(..., chaos=plan)``. Injection is once-per-step
by default so a run that rolls back and re-executes step K replays it
*clean* — the transient-fault model under which recovery must reconverge
to the uninterrupted trajectory. The strategy-safety injections follow the
same once model: the NEXT candidate compiles/audits clean, so the cascade
lands on a working fallback.
"""
from __future__ import annotations

import os
import signal
from typing import Iterable, List, Optional

from ..execution.checkpoint import COMMIT_MARKER, read_meta


class ChaosPlan:
    """Scripted fault schedule for one training run.

    Steps are global 0-based step indices (the value ``step_count`` holds
    as the step is about to dispatch). With ``once=True`` (default) each
    scripted fault fires a single time even if the step is re-executed
    after a rollback — the transient-fault model.
    """

    def __init__(self, nan_at_steps: Iterable[int] = (),
                 preempt_at_step: Optional[int] = None,
                 preempt_signal: int = signal.SIGTERM,
                 once: bool = True,
                 fail_compiles: int = 0,
                 wrong_reshard: bool = False,
                 wrong_reshard_factor: float = 2.0):
        self.nan_at_steps = {int(s) for s in nan_at_steps}
        self.preempt_at_step = (None if preempt_at_step is None
                                else int(preempt_at_step))
        self.preempt_signal = preempt_signal
        self.once = once
        self.injected_nan_steps: List[int] = []
        self.preempted_at: Optional[int] = None
        self._nan_done: set = set()
        # strategy-safety injections (resilience/fallback.py, audit.py)
        self.fail_compiles = int(fail_compiles)
        self.compile_failures_injected = 0
        self.wrong_reshard = bool(wrong_reshard)
        self.wrong_reshard_factor = float(wrong_reshard_factor)
        self.wrong_reshards_injected = 0

    # -- hooks called by Model.fit ------------------------------------------
    def poison_batch(self, step: int, bx):
        """Replace the first floating-point input of step ``step`` with NaN
        (dtype-preserving, so the jitted step does not retrace)."""
        if step not in self.nan_at_steps or \
                (self.once and step in self._nan_done):
            return bx
        import jax.numpy as jnp

        bx = list(bx)
        for i, a in enumerate(bx):
            if jnp.issubdtype(a.dtype, jnp.floating):
                bx[i] = a * jnp.asarray(float("nan"), dtype=a.dtype)
                self._nan_done.add(step)
                self.injected_nan_steps.append(step)
                return bx
        raise ValueError(
            "ChaosPlan.nan_at_steps needs a floating-point model input to "
            f"poison; step {step}'s batch has dtypes "
            f"{[str(a.dtype) for a in bx]}")

    # -- hooks called by the strategy-safety cascade / auditor --------------
    def strategy_chaos_pending(self) -> bool:
        """Any strategy-safety injection still pending? (What arms the
        fallback cascade's pre-fit verification.)"""
        return (self.compile_failures_injected < self.fail_compiles
                or (self.wrong_reshard
                    and (not self.once
                         or self.wrong_reshards_injected == 0)))

    def consume_compile_failure(self) -> bool:
        """True while scripted compile failures remain: the cascade's
        compile check treats it exactly like XLA rejecting the plan. Each
        call consumes one injection, so candidate N+fail_compiles compiles
        clean and the cascade lands on it."""
        if self.compile_failures_injected < self.fail_compiles:
            self.compile_failures_injected += 1
            return True
        return False

    def consume_wrong_reshard(self) -> float:
        """Grad-norm factor the auditor applies to the CANDIDATE probe —
        != 1.0 while the injection is pending, simulating a plan whose
        miscompiled resharding double-counts the gradient allreduce (loss
        matches the reference, the grad norm is off by the factor). With
        ``once=True`` it fires on a single audit, so the cascade's next
        candidate audits clean."""
        if self.wrong_reshard and (not self.once
                                   or self.wrong_reshards_injected == 0):
            self.wrong_reshards_injected += 1
            return self.wrong_reshard_factor
        return 1.0

    def maybe_preempt(self, step: int) -> None:
        """Deliver the scripted preemption signal before step ``step``
        dispatches. Goes through ``os.kill`` so the REAL installed signal
        handler runs — the fit loop then finishes the in-flight step,
        flushes a final checkpoint and returns, exactly the TPU
        grace-window protocol."""
        if self.preempt_at_step is None or self.preempted_at is not None \
                or step != self.preempt_at_step:
            return
        self.preempted_at = step
        os.kill(os.getpid(), self.preempt_signal)


def corrupt_checkpoint(path: str, mode: str = "truncate") -> str:
    """Deterministically damage a committed checkpoint; returns a
    description of what was done.

    * ``truncate`` — cut the largest checksummed payload file in half
      (a killed copy / torn write).
    * ``flip``     — flip one byte in the middle of that file (bit rot).
    * ``uncommit`` — delete the commit marker (a writer that died between
      staging and commit; ``latest_checkpoint`` must skip the dir).
    """
    path = os.path.abspath(path)
    if mode == "uncommit":
        os.remove(os.path.join(path, COMMIT_MARKER))
        return f"removed {COMMIT_MARKER} from {path}"
    sums = read_meta(path).get("checksums", {})
    if not sums:
        raise ValueError(f"{path}: no checksummed payload files")
    # deterministic victim: the largest file, name as tie-break
    rel = max(sorted(sums), key=lambda r: (sums[r][1], r))
    fp = os.path.join(path, rel)
    size = os.path.getsize(fp)
    if mode == "truncate":
        with open(fp, "r+b") as f:
            f.truncate(max(size // 2, 0))
        return f"truncated {rel} from {size} to {max(size // 2, 0)} bytes"
    if mode == "flip":
        with open(fp, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        return f"flipped byte {size // 2} of {rel}"
    raise ValueError(f"unknown corruption mode {mode!r}")
