"""ResilienceSession: the fault-tolerance orchestrator for one fit().

One object owns every resilience concern of a training run so the fit loop
stays readable: the async ``CheckpointManager`` (``--checkpoint-dir`` /
``--checkpoint-every`` / ``--keep-checkpoints``), the SIGTERM/SIGINT
preemption handlers (flag-setting only — the loop flushes a final
checkpoint at the next step boundary, inside the TPU grace window), exact
resume (``--resume auto|<path>``: params, opt state, epoch, batch cursor,
rng counter), the divergence sentinel (``--max-bad-steps`` consecutive
non-finite steps trigger an automatic rollback to the last committed
checkpoint), and the scripted chaos hooks.

Rollback semantics: the first rollback replays from the last good
checkpoint unchanged — under the transient-fault model (a bad batch, a
one-off hardware glitch) the replay is clean and the run reconverges to
the uninterrupted trajectory. If divergence *persists* (a second rollback
fires), the reduced-LR escape hatch multiplies the learning rate by
``rollback_lr_factor`` before each further replay; after ``max_rollbacks``
the run aborts rather than loop forever. Every event lands in the obs
layer: ``fault`` instant events, ``recovery`` spans, and counters merged
into ``StepTelemetry``.
"""
from __future__ import annotations

import signal
import time
from typing import Any, Dict, Optional, Tuple

from ..execution.checkpoint import (CheckpointCorruptError,
                                    CheckpointManager, latest_checkpoint,
                                    list_checkpoints, restore_checkpoint,
                                    restore_train_cursor)
from .sentinel import GuardedTrainStep


class ResilienceSession:
    def __init__(self, ffmodel, chaos=None, signals_only: bool = False):
        # signals_only (ISSUE 9): the serving engine reuses ONLY the
        # flag-only preemption handlers for its graceful drain — no
        # checkpoint writer thread, no train-step guard, even when the
        # model's config has training-side resilience armed
        cfg = ffmodel.config
        self.model = ffmodel
        self.chaos = chaos
        self.tracer = ffmodel._obs_tracer()
        self.checkpoint_every = max(int(
            getattr(cfg, "checkpoint_every", 0) or 0), 0)
        self.manager: Optional[CheckpointManager] = None
        if getattr(cfg, "checkpoint_dir", "") and not signals_only:
            self.manager = CheckpointManager(
                ffmodel, cfg.checkpoint_dir,
                keep=getattr(cfg, "keep_checkpoints", 3))
        self.guard: Optional[GuardedTrainStep] = None
        if int(getattr(cfg, "max_bad_steps", 0) or 0) > 0 \
                and not signals_only:
            self.guard = GuardedTrainStep(ffmodel.executor,
                                          cfg.max_bad_steps)
        self.rollback_lr_factor = float(
            getattr(cfg, "rollback_lr_factor", 0.5) or 0.5)
        self.max_rollbacks = max(int(
            getattr(cfg, "max_rollbacks", 3) or 3), 1)
        self.rollbacks = 0
        # telemetry counters (merged into StepTelemetry at close)
        self.fault_events = 0
        self.recovery_events = 0
        self.skipped_steps = 0
        self.last_resume_step: Optional[int] = None
        self.preempted = False
        self.preempt_signum: Optional[int] = None
        self._old_handlers: Dict[int, Any] = {}

    @staticmethod
    def wanted(config, chaos) -> bool:
        """Any resilience feature requested? (The fit loop stays untouched
        — zero per-step overhead — when this is False.)"""
        return bool(getattr(config, "checkpoint_dir", "")
                    or int(getattr(config, "max_bad_steps", 0) or 0) > 0
                    or (getattr(config, "resume", "") or "").strip()
                    or chaos is not None)

    # ------------------------------------------------------------ signals --
    def _on_signal(self, signum, frame) -> None:
        # flags ONLY: the handler runs on the main thread at an arbitrary
        # bytecode boundary — touching the tracer here could deadlock on
        # its non-reentrant lock if the signal lands inside an in-progress
        # emit. The fault event is deferred to the loop's next step
        # boundary (note_preemption)
        self.preempted = True
        self.preempt_signum = signum

    def note_preemption(self, step: int) -> None:
        """Record the preemption the handler flagged — called from the fit
        loop (safe context), right before the final flush."""
        self.fault_events += 1
        self.tracer.event("fault", kind="preemption_signal",
                          signum=self.preempt_signum, step=step)

    def install_signal_handlers(self) -> None:
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._old_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                pass  # not the main thread: preemption flagging unavailable

    def restore_signal_handlers(self) -> None:
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._old_handlers.clear()

    # ------------------------------------------------------------- resume --
    def maybe_resume(self) -> Optional[Tuple[int, int, int]]:
        """Honor ``--resume``; returns (step, epoch, batch_in_epoch) after
        restoring model state, or None for a fresh start. ``auto`` with no
        committed checkpoint is a fresh start; an explicit path that is
        missing or uncommitted raises."""
        mode = (getattr(self.model.config, "resume", "") or "").strip()
        if not mode:
            return None
        if mode == "auto":
            d = getattr(self.model.config, "checkpoint_dir", "")
            path = latest_checkpoint(d, verify=True) if d else None
            if path is None:
                return None
        else:
            path = mode
        t0 = time.perf_counter()
        step = restore_checkpoint(self.model, path)
        ts = restore_train_cursor(self.model, path)
        self.last_resume_step = step
        self.recovery_events += 1
        self.tracer.complete("recovery", time.perf_counter() - t0,
                             kind="resume", path=path, step=step)
        return step, int(ts.get("epoch", 0)), int(ts.get("batch_in_epoch", 0))

    # -------------------------------------------------------- checkpointing --
    def _train_state(self, step: int, epoch: int, batch_in_epoch: int,
                     steps_per_epoch: int) -> Dict[str, Any]:
        if steps_per_epoch and batch_in_epoch >= steps_per_epoch:
            epoch, batch_in_epoch = epoch + 1, 0  # boundary-normalized
        return {"step": int(step), "epoch": int(epoch),
                "batch_in_epoch": int(batch_in_epoch),
                "rng_counter": int(self.model._rng_counter)}

    def on_step(self, step: int, epoch: int, batch_in_epoch: int,
                steps_per_epoch: int) -> None:
        """Periodic async checkpoint trigger (call after the step's update
        landed in ``model.params``)."""
        if self.manager is None or self.checkpoint_every <= 0:
            return
        if step % self.checkpoint_every == 0:
            self.manager.save_async(
                step, self._train_state(step, epoch, batch_in_epoch,
                                        steps_per_epoch))

    def final_checkpoint(self, step: int, epoch: int, batch_in_epoch: int,
                         steps_per_epoch: int) -> Optional[str]:
        """Preemption flush: drain pending saves, then commit the current
        state synchronously — the last thing that must happen inside the
        grace window."""
        if self.manager is None:
            return None
        t0 = time.perf_counter()
        path = self.manager.save_sync(
            step, self._train_state(step, epoch, batch_in_epoch,
                                    steps_per_epoch))
        self.tracer.complete("recovery", time.perf_counter() - t0,
                             kind="preemption_flush", step=step,
                             path=path or "")
        return path

    # ------------------------------------------------------------ sentinel --
    def record_fault(self, step: int, kind: str = "nonfinite_step") -> None:
        self.fault_events += 1
        self.skipped_steps += 1
        self.tracer.event("fault", kind=kind, step=step)

    def rollback(self) -> Tuple[int, int, int]:
        """Restore the last committed checkpoint after the sentinel's
        bad-step budget is exhausted. Returns (step, epoch,
        batch_in_epoch) to re-enter the loop at. First rollback replays
        as-is; repeated rollbacks engage the reduced-LR escape hatch."""
        if self.manager is None:
            raise RuntimeError(
                "--max-bad-steps hit with no --checkpoint-dir: divergence "
                "sentinel has no committed checkpoint to roll back to "
                f"(loss/grads non-finite for {self.guard.consecutive_bad} "
                "consecutive steps)")
        self.manager.flush()
        candidates = [p for _s, p in
                      reversed(list_checkpoints(self.manager.directory))]
        if not candidates:
            raise RuntimeError(
                "divergence sentinel: no committed checkpoint exists yet "
                "(lower --checkpoint-every or raise --max-bad-steps)")
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise RuntimeError(
                f"divergence persists after {self.max_rollbacks} rollbacks "
                "(reduced-LR escape hatch included) — aborting the run")
        t0 = time.perf_counter()
        step = path = None
        for cand in candidates:
            # a bit-rotted newest checkpoint must not kill the run while
            # older checksum-clean ones exist — fall back past it
            try:
                step = restore_checkpoint(self.model, cand)
                path = cand
                break
            except CheckpointCorruptError:
                self.fault_events += 1
                self.tracer.event("fault", kind="corrupt_checkpoint",
                                  path=cand)
        if step is None:
            raise RuntimeError(
                "divergence sentinel: every committed checkpoint in "
                f"{self.manager.directory} failed checksum verification")
        ts = restore_train_cursor(self.model, path)
        new_lr = None
        if self.rollbacks > 1:
            # persistent divergence: shrink the LR before replaying
            opt = self.model.optimizer
            cur = getattr(opt, "lr", None)
            if cur is None:
                cur = getattr(opt, "alpha", 0.0)
            new_lr = float(cur) * self.rollback_lr_factor
            opt.set_learning_rate(new_lr)
            self.model.executor.invalidate_jit_cache()
            if self.guard is not None:
                self.guard.rebuild()
        if self.guard is not None:
            self.guard.reset()
        self.recovery_events += 1
        self.last_resume_step = step
        self.tracer.complete(
            "recovery", time.perf_counter() - t0, kind="rollback",
            step=step, path=path, rollbacks=self.rollbacks,
            **({"reduced_lr": new_lr} if new_lr is not None else {}))
        return step, int(ts.get("epoch", 0)), int(ts.get("batch_in_epoch", 0))

    # --------------------------------------------------------------- close --
    def merge_telemetry(self, telemetry) -> None:
        if telemetry is None:
            return
        telemetry.fault_events += self.fault_events
        telemetry.recovery_events += self.recovery_events
        telemetry.skipped_steps += self.skipped_steps
        if self.manager is not None:
            telemetry.checkpoints_saved += self.manager.saved
        if self.last_resume_step is not None:
            telemetry.last_resume_step = self.last_resume_step

    def close(self, telemetry=None) -> None:
        try:
            if self.manager is not None:
                self.manager.close()
        finally:
            self.restore_signal_handlers()
            self.merge_telemetry(telemetry)
