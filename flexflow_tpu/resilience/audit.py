"""Parallel-correctness auditor: refuse plans that compute wrong gradients.

The worst strategy failure is not a crash — it is a plan that compiles,
runs, and silently trains on *wrong gradients* (a bad substitution rule, a
resharding that drops or double-counts a partial sum). The search's cost
model cannot see this; only execution can. The auditor (ISSUE 5,
``--audit-strategy``) runs ONE probe batch twice over the same graph:

* under the candidate strategy, exactly as the train step would execute it
  (same mixed-precision cast, aux losses, guid-folded dropout rng), via
  ``Executor.make_probe_step``;
* under a single-device data-parallel *reference* executor — the plan with
  no resharding to get wrong.

and compares the loss and the global gradient L2 norm within
``--audit-tol`` relative error. Two scalars are a deliberately small
comparison surface: any dropped/doubled collective anywhere in the
backward pass moves the global grad norm, while per-leaf comparison would
cost a full host gather of both pytrees. A failed audit raises (or, under
the fallback cascade, demotes the plan to the next ranked candidate).

Chaos hook: ``ChaosPlan(wrong_reshard=True)`` scales the candidate's
reported grad norm (default 2.0 — a double-counted gradient allreduce), so
the reject path is CPU-testable without a genuinely miscompiled plan. See
``docs/strategy_safety.md``.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


class AuditError(RuntimeError):
    """The candidate strategy's probe diverged from the single-device
    reference beyond ``--audit-tol`` — the plan is presumed miscompiled."""


@dataclasses.dataclass
class AuditReport:
    passed: bool
    loss_candidate: float
    loss_reference: float
    grad_norm_candidate: float
    grad_norm_reference: float
    loss_rel_err: float
    grad_rel_err: float
    tol: float
    strategy: str = ""

    def detail(self) -> str:
        return (f"loss {self.loss_candidate:.6g} vs reference "
                f"{self.loss_reference:.6g} (rel err "
                f"{self.loss_rel_err:.3g}), grad norm "
                f"{self.grad_norm_candidate:.6g} vs "
                f"{self.grad_norm_reference:.6g} (rel err "
                f"{self.grad_rel_err:.3g}), tol {self.tol:g}")


def _reference_executor(ffmodel):
    """A single-device data-parallel executor over the SAME compiled graph:
    no tensor/sequence/expert sharding, so there is no resharding rule to
    have gotten wrong — the numerical ground truth for the audit."""
    import jax

    from ..execution.executor import Executor
    from ..parallel.mesh import build_mesh
    from ..parallel.strategy import data_parallel_strategy

    strat = data_parallel_strategy(ffmodel.pcg, 1)
    mesh = build_mesh(None, mesh_shape=(1,), axis_names=("data",),
                      devices=jax.devices()[:1])
    ex = ffmodel.executor
    return Executor(ffmodel.pcg, mesh, strat, ffmodel.loss_type,
                    ffmodel.metrics_obj, ffmodel.optimizer, ffmodel.config,
                    ffmodel.final_guid, ex.label_dtype, ex.repl_labels,
                    final_out_idx=ex.final_out_idx)


def audit_strategy(ffmodel, xs, y, tol: float = 0.05,
                   chaos=None, ref_cache: Optional[dict] = None
                   ) -> AuditReport:
    """Run the probe batch under the model's live strategy and under the
    single-device reference; returns an :class:`AuditReport` (never raises
    on a mere mismatch — the caller decides between refuse and fall back).

    ``xs``/``y`` are host arrays of one batch (labels may be raw; they are
    passed through the model's label prep). ``tol`` is the relative-error
    budget for BOTH scalars; non-finite values on either side fail.
    ``ref_cache`` (a dict the caller owns) memoizes the reference scalars:
    the reference is candidate-independent, so a fallback cascade auditing
    several candidates over the same probe pays its compile once."""
    import jax

    xs = [np.asarray(a) for a in ffmodel._as_input_list(xs)]
    y = ffmodel._prep_label(np.asarray(y))
    ex = ffmodel.executor
    rng = jax.random.PRNGKey(0)

    probe = ex.make_probe_step()
    in_sh = [ex.batch_sharding(a.ndim) for a in xs]
    bx = [jax.device_put(a, s) for a, s in zip(xs, in_sh)]
    by = jax.device_put(y, ex.batch_sharding(y.ndim))
    cargs = (ffmodel.params, bx, by, rng)
    if ex.cache_nodes:
        cargs = cargs + (ex.init_cache(),)
    loss_c, gn_c = (float(v) for v in jax.device_get(probe(*cargs)))
    if chaos is not None:
        gn_c *= float(chaos.consume_wrong_reshard())

    if ref_cache is not None and "ref" in ref_cache:
        loss_r, gn_r = ref_cache["ref"]
    else:
        ref = _reference_executor(ffmodel)
        host_params = {ln: {wn: np.asarray(a) for wn, a in ws.items()}
                       for ln, ws in ffmodel.params.items()}
        rargs = (host_params, xs, y, rng)
        if ref.cache_nodes:
            rargs = rargs + (ref.init_cache(),)
        loss_r, gn_r = (float(v) for v in
                        jax.device_get(ref.make_probe_step()(*rargs)))
        if ref_cache is not None:
            ref_cache["ref"] = (loss_r, gn_r)

    def rel(a: float, b: float) -> float:
        return abs(a - b) / max(abs(b), 1e-8)

    loss_err, grad_err = rel(loss_c, loss_r), rel(gn_c, gn_r)
    finite = bool(np.all(np.isfinite([loss_c, gn_c, loss_r, gn_r])))
    passed = finite and loss_err <= tol and grad_err <= tol
    return AuditReport(
        passed=passed, loss_candidate=loss_c, loss_reference=loss_r,
        grad_norm_candidate=gn_c, grad_norm_reference=gn_r,
        loss_rel_err=loss_err, grad_rel_err=grad_err, tol=tol,
        strategy=(ffmodel.strategy.describe()
                  if ffmodel.strategy is not None else "?"))
