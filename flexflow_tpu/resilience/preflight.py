"""Preflight validation: reject doomed plans BEFORE burning compile time.

Production TPU stacks run cheap static checks before committing a job to
hours of compilation and accelerator time (MegaScale-style preflight); the
reference instead discovers a bad MachineView or a mis-shaped batch as a
Legion mapping failure deep inside the run. This module is the TPU-native
preflight (ISSUE 5):

* ``preflight_strategy`` — strategy-vs-machine divisibility: mesh size vs
  visible devices, batch vs data-parallel degree, every PartitionSpec axis
  exists in the mesh, sharded weight/output dims divide their axis size,
  hybrid ICI x DCN factors multiply out, pipeline grid sanity, remat level.
  The per-node PartitionSpec half routes through the ShardLint FF006
  checker (``analysis/rules.check_shapes``, ISSUE 7) — one implementation
  for both validation paths, same historic error texts.
  Run by ``FFModel.compile`` on explicit / imported strategies (the
  untrusted inputs — searched strategies are divisible by construction)
  and by the fallback cascade on every candidate it considers.
* ``preflight_config`` — flag-combination sanity that needs the assembled
  config (``--resume auto`` without a checkpoint dir, non-positive
  ``--audit-tol``, retention that would delete the checkpoint resume
  needs). Parse-time single-flag validation lives in ``config.parse_args``.
* ``validate_batch`` — fit/eval/predict input arrays vs the compiled
  signature: rank, per-axis shape, dtype kind, consistent sample counts —
  a clear ``ValueError`` naming the offending tensor and axis instead of a
  cryptic XLA shape error mid-epoch.

All failures raise :class:`PreflightError` (a ``ValueError``) whose message
says what to change. See ``docs/strategy_safety.md``.
"""
from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import numpy as np


class PreflightError(ValueError):
    """A strategy / flag / batch combination that cannot run; the message
    is actionable (names the offending piece and what to change)."""


# ----------------------------------------------------------------- config
def preflight_config(config) -> None:
    """Flag-combination sanity (ISSUE 5 satellite): everything here would
    otherwise fail mid-run with a far less helpful error."""
    fb = (getattr(config, "strategy_fallback", "on") or "on")
    if fb not in ("on", "off"):
        raise PreflightError(
            f"--strategy-fallback expects on|off, got {fb!r}")
    tol = getattr(config, "audit_tol", 0.05)
    if tol is not None and float(tol) <= 0:
        raise PreflightError(
            f"--audit-tol must be > 0 (got {tol}): the audit compares "
            "relative loss/grad-norm error against it")
    if int(getattr(config, "memory_budget_mb", 0) or 0) < 0:
        raise PreflightError(
            "--memory-budget-mb must be >= 0 (0 disables the compile-time "
            "OOM check)")
    if getattr(config, "checkpoint_dir", "") and \
            int(getattr(config, "keep_checkpoints", 3) or 0) < 1:
        raise PreflightError(
            "--keep-checkpoints must keep at least 1 committed checkpoint; "
            "retention 0 would delete the checkpoint --resume and the "
            "divergence sentinel roll back to")
    if (getattr(config, "resume", "") or "").strip() == "auto" and \
            not getattr(config, "checkpoint_dir", ""):
        raise PreflightError(
            "--resume auto needs --checkpoint-dir to know where committed "
            "checkpoints live; pass --checkpoint-dir DIR or give --resume "
            "an explicit step_N checkpoint path")
    remat = (getattr(config, "remat", "") or "")
    if remat and remat not in ("none", "selective", "full"):
        raise PreflightError(
            f"--remat expects none|selective|full, got {remat!r}")
    sched = (getattr(config, "schedule", "") or "")
    if sched and sched not in ("gpipe", "1f1b", "interleaved"):
        raise PreflightError(
            f"--schedule expects gpipe|1f1b|interleaved, got {sched!r}")
    vstages = int(getattr(config, "pipeline_virtual_stages", 0) or 0)
    if vstages and vstages < 2:
        raise PreflightError(
            f"--virtual-stages must be >= 2 (got {vstages}): v=1 IS the "
            "1f1b schedule — use --schedule 1f1b instead")
    if vstages and sched and sched != "interleaved":
        raise PreflightError(
            "--virtual-stages only applies to the interleaved schedule; "
            "use --schedule interleaved or drop --virtual-stages")
    co = (getattr(config, "collective_overlap", "off") or "off")
    if co not in ("on", "off"):
        raise PreflightError(
            f"--collective-overlap expects on|off, got {co!r}")
    sa = (getattr(config, "static_analysis", "on") or "on")
    if sa not in ("on", "off", "strict"):
        raise PreflightError(
            f"--static-analysis expects on|off|strict, got {sa!r}")
    dt = getattr(config, "drift_tolerance", 0.25)
    if dt is not None and float(dt) <= 0:
        raise PreflightError(
            f"--drift-tolerance must be > 0 (got {dt}): it is the "
            "half-width of the sim-vs-measured band the drift sentinel "
            "alerts on")
    if getattr(config, "auto_recalibrate", False) and \
            not getattr(config, "profile_ops", ""):
        raise PreflightError(
            "--auto-recalibrate needs --profile-ops PATH: the closed loop "
            "repairs calibration from the profiled pass's measurements")
    trace = (getattr(config, "calibrate_from_trace", "") or "")
    if trace and not os.path.isfile(trace):
        raise PreflightError(
            f"--calibrate-from-trace {trace!r}: no such profile file "
            "(produce one with --profile-ops)")
    pods = int(getattr(config, "num_pods", 0) or 0)
    if pods < 0:
        raise PreflightError(
            f"--pods must be >= 0 (got {pods}); 0 keeps the detected "
            "topology, N >= 1 splits the machine into N DCN-connected "
            "pods")
    gbps = float(getattr(config, "dcn_gbps", 0.0) or 0.0)
    if gbps < 0:
        raise PreflightError(
            f"--dcn-gbps must be >= 0 (got {gbps}); 0 keeps the "
            "generation default, > 0 overrides the per-pod DCN "
            "bandwidth in GB/s")
    if gbps > 0 and pods < 2 and \
            not getattr(config, "machine_model_file", ""):
        raise PreflightError(
            "--dcn-gbps needs a multi-pod topology to apply to: set "
            "--pods N >= 2 (or a --machine-model-file with num_pods)")
    hs = (getattr(config, "search_hierarchical", "auto") or "auto")
    if hs not in ("auto", "on", "off"):
        raise PreflightError(
            f"--hierarchical-search expects auto|on|off, got {hs!r}")
    sl = (getattr(config, "serve_loop", "sync") or "sync")
    if sl not in ("sync", "async"):
        raise PreflightError(
            f"--serve-loop expects sync|async, got {sl!r}: sync is the "
            "blocking reference loop, async the double-buffered runtime "
            "(bitwise-identical streams under exact decode)")
    raw_ss = getattr(config, "seq_shards", 1)
    ss = int(raw_ss if raw_ss is not None else 1)
    if ss < 1:
        raise PreflightError(
            f"--seq-shards must be >= 1 (got {ss}): it is the number of "
            "contiguous block-table shards a decode step scores across "
            "(1 = unsharded)")
    if ss > 1 and getattr(config, "kv_cache", "paged") == "ring":
        raise PreflightError(
            "--seq-shards > 1 requires --kv-cache paged: the ring "
            "layout has no block tables to partition into per-shard "
            "contiguous runs")
    cb = getattr(config, "context_buckets", "") or ""
    if cb:
        from ..serving.kvcache import parse_context_buckets

        try:
            parse_context_buckets(cb)
        except ValueError as e:
            raise PreflightError(str(e))
        if getattr(config, "kv_cache", "paged") == "ring":
            raise PreflightError(
                "--context-buckets requires --kv-cache paged: buckets "
                "route requests to sequence-sharded block-table "
                "partitions")
    asc = (getattr(config, "autoscale", "off") or "off")
    if asc not in ("on", "off"):
        raise PreflightError(
            f"--autoscale expects on|off, got {asc!r}")
    mn = int(getattr(config, "min_replicas", 0) or 0)
    mx = int(getattr(config, "max_replicas", 0) or 0)
    if mn < 0 or mx < 0:
        raise PreflightError(
            f"--min-replicas/--max-replicas must be >= 0 (got {mn}/{mx}); "
            "0 defaults to the initial fleet size / twice it")
    if (mn or mx) and asc != "on":
        raise PreflightError(
            "--min-replicas/--max-replicas bound the autoscaler's pool "
            "and are only meaningful with --autoscale on")
    if mn and mx and mx < mn:
        raise PreflightError(
            f"--max-replicas ({mx}) must be >= --min-replicas ({mn})")
    tiers = getattr(config, "tenant_tiers", "") or ""
    if tiers:
        from ..serving.tenancy import parse_tenant_tiers

        try:
            parse_tenant_tiers(tiers)
        except ValueError as e:
            raise PreflightError(str(e))
    jdir = getattr(config, "request_journal", "") or ""
    jsync = float(getattr(config, "journal_sync_ms", 0.0) or 0.0)
    jevery = int(getattr(config, "journal_commit_every", 0) or 0)
    if jsync < 0 or jevery < 0:
        raise PreflightError(
            f"--journal-sync-ms/--journal-commit-every must be >= 0 "
            f"(got {jsync:g}/{jevery})")
    if (jsync or jevery) and not jdir:
        raise PreflightError(
            "--journal-sync-ms/--journal-commit-every tune the "
            "write-ahead request journal and are only meaningful with "
            "--request-journal DIR (docs/durability.md)")
    if jdir:
        import os

        parent = os.path.dirname(os.path.abspath(jdir))
        if not os.path.isdir(parent):
            raise PreflightError(
                f"--request-journal parent directory does not exist: "
                f"{parent} — the journal cannot be made durable on a "
                "path that cannot be created")


# --------------------------------------------------------------- strategy
def preflight_strategy(pcg, strategy, n_dev: int, batch_size: int,
                       spec_checks: bool = True) -> None:
    """Static divisibility audit of a Strategy against the machine it is
    about to compile for. Raises :class:`PreflightError` with the offending
    node / axis named; a passing strategy may still fail XLA (that is what
    the fallback cascade's compile check is for) but cannot fail on any of
    the arithmetic checked here."""
    ms = tuple(int(s) for s in strategy.mesh_shape)
    axes = tuple(strategy.axis_names)
    if len(axes) != len(ms):
        raise PreflightError(
            f"strategy mesh {ms} has {len(ms)} dims but axis_names {axes} "
            f"names {len(axes)}; every mesh dim needs exactly one axis name")
    if len(set(axes)) != len(axes):
        raise PreflightError(f"strategy axis_names {axes} contain "
                             "duplicates; mesh axes must be distinct")
    need = int(np.prod(ms)) if ms else 1
    if need > n_dev:
        raise PreflightError(
            f"strategy needs {need} devices (mesh {ms}) but only {n_dev} "
            "are visible; re-run the search on this machine, pass a "
            "smaller --mesh-shape, or restore a checkpointed run via "
            "resilience.elastic_restore (re-plans for the surviving "
            "devices)")
    if strategy.data_axis not in axes:
        raise PreflightError(
            f"strategy data_axis {strategy.data_axis!r} is not one of the "
            f"mesh axes {axes}")
    dp = ms[axes.index(strategy.data_axis)]
    if dp and batch_size % dp:
        raise PreflightError(
            f"batch size {batch_size} is not divisible by the "
            f"data-parallel degree {dp} of mesh {ms}; use a batch that is "
            f"a multiple of {dp} or a strategy whose dp divides the batch")
    if strategy.hybrid:
        ici, dcn = strategy.hybrid
        if len(ici) != len(ms) or len(dcn) != len(ms) or any(
                int(i) * int(d) != m for i, d, m in zip(ici, dcn, ms)):
            raise PreflightError(
                f"hybrid layout ici={tuple(ici)} x dcn={tuple(dcn)} does "
                f"not factor the mesh {ms}: each axis needs "
                "ici[i] * dcn[i] == mesh_shape[i]")
    if strategy.remat and strategy.remat not in ("none", "selective",
                                                 "full"):
        raise PreflightError(
            f"strategy remat level {strategy.remat!r} is not one of "
            "none|selective|full")
    sched = (getattr(strategy, "schedule", "") or "")
    vstages = int(getattr(strategy, "virtual_stages", 1) or 1)
    if sched and sched not in ("gpipe", "1f1b", "interleaved"):
        raise PreflightError(
            f"strategy schedule {sched!r} is not one of "
            "gpipe|1f1b|interleaved")
    if sched and not strategy.pipeline:
        raise PreflightError(
            f"strategy sets schedule={sched!r} without a pipeline grid: "
            "the schedule knob orders pipeline microbatches — add "
            "pipeline=(pp, dp, n_micro) or drop the schedule")
    if strategy.pipeline:
        pp, pdp, micro = (int(v) for v in strategy.pipeline)
        if pp < 2:
            raise PreflightError(
                f"pipeline grid {strategy.pipeline}: pp must be >= 2 "
                "(pp=1 is plain SPMD — drop the pipeline field)")
        if pp * pdp > n_dev:
            raise PreflightError(
                f"pipeline grid pp={pp} x dp={pdp} needs {pp * pdp} "
                f"devices but only {n_dev} are visible")
        if micro < 1 or batch_size % micro or (batch_size // micro) % \
                max(pdp, 1):
            raise PreflightError(
                f"pipeline grid {strategy.pipeline}: batch {batch_size} "
                f"must split into {micro} microbatches each divisible by "
                f"dp={pdp}")
        # (schedule, pp, n_micro, v) combos (ISSUE 10, docs/pipeline.md):
        # each failure names the knob to change
        if sched == "interleaved":
            if vstages < 2:
                raise PreflightError(
                    f"interleaved schedule needs virtual_stages >= 2 "
                    f"(got {vstages}); virtual_stages=1 IS the 1f1b "
                    "schedule — set schedule='1f1b' or raise "
                    "virtual_stages")
            if micro % pp:
                raise PreflightError(
                    f"interleaved schedule: n_micro={micro} must be a "
                    f"multiple of pp={pp} (microbatches advance in "
                    "rounds of pp through the virtual chunks) — change "
                    "n_micro or use schedule='1f1b'")
        elif vstages != 1:
            raise PreflightError(
                f"virtual_stages={vstages} only applies to the "
                f"interleaved schedule (got schedule="
                f"{sched or 'gpipe'!r}); set virtual_stages=1")
        n_chunks = pp * (vstages if sched == "interleaved" else 1)
        n_nodes = len(pcg.compute_nodes())
        if n_chunks > n_nodes:
            raise PreflightError(
                f"schedule {sched or 'gpipe'!r} needs pp*v = {pp}*"
                f"{vstages if sched == 'interleaved' else 1} = "
                f"{n_chunks} stage chunks but the graph has only "
                f"{n_nodes} compute nodes; lower virtual_stages (v) or "
                "the pipeline depth pp")

    # per-node PartitionSpec dataflow (axis exists, sharded dims divide):
    # routed through the ShardLint FF006 checker (ISSUE 7 — one
    # implementation, two consumers) so preflight and the static analyzer
    # cannot drift; the diagnostic messages ARE the historic preflight
    # error texts, raised here with the same first-failure semantics.
    # ``spec_checks=False`` lets a caller that ALREADY ran the analyzer
    # (the cascade's stage 0 covers FF006) skip the duplicate walk.
    if not spec_checks:
        return
    from ..analysis.rules import check_shapes

    diags = check_shapes(pcg, strategy)
    if diags:
        raise PreflightError(diags[0].message)


# ------------------------------------------------------------------ batch
_KIND_NAMES = {"f": "floating", "i": "integer", "u": "integer",
               "b": "boolean", "c": "complex"}


def _kind(dt: np.dtype) -> str:
    k = np.dtype(dt).kind
    if k in ("f", "V"):  # bfloat16 surfaces as a void-kind numpy dtype
        return "f"
    if k in ("i", "u"):
        return "i"
    return k


def validate_batch(ffmodel, xs: Sequence[Any], y: Optional[Any] = None,
                   phase: str = "fit") -> None:
    """Validate fit/eval/predict arrays against the compiled signature
    (ISSUE 5 satellite): a mis-shaped or mis-typed batch raises a clear
    ``ValueError`` naming the offending tensor and axis here, instead of a
    cryptic XLA shape/dtype error mid-epoch."""
    from ..ffconst import dtype_to_jnp

    input_nodes = ffmodel.pcg.input_nodes()
    if len(xs) != len(input_nodes):
        names = [n.name for n in input_nodes]
        raise ValueError(
            f"{phase}: model has {len(input_nodes)} input tensor(s) "
            f"{names} but got {len(xs)} array(s)")
    n0 = None
    first_name = None
    for node, a in zip(input_nodes, xs):
        a = np.asarray(a)
        want = tuple(node.out_shapes[0])
        got = tuple(a.shape)
        if len(got) != len(want):
            raise ValueError(
                f"{phase}: batch for input '{node.name}' has rank "
                f"{len(got)} (shape {got}) but the compiled signature "
                f"expects rank {len(want)} (declared shape {want}, leading "
                "axis = batch)")
        for ax in range(1, len(want)):
            if got[ax] != int(want[ax]):
                raise ValueError(
                    f"{phase}: batch for input '{node.name}' mismatches "
                    f"the compiled signature on axis {ax}: got {got[ax]} "
                    f"(shape {got}), expected {want[ax]} (declared shape "
                    f"{want})")
        want_dt = np.dtype(dtype_to_jnp(node.out_dtypes[0]))
        if _kind(a.dtype) != _kind(want_dt):
            raise ValueError(
                f"{phase}: batch for input '{node.name}' has "
                f"{_KIND_NAMES.get(_kind(a.dtype), _kind(a.dtype))} dtype "
                f"{a.dtype} but the compiled signature expects a "
                f"{_KIND_NAMES.get(_kind(want_dt), _kind(want_dt))} tensor "
                f"({want_dt.name}); cast the array before {phase}")
        if n0 is None:
            n0, first_name = got[0], node.name
        elif got[0] != n0:
            raise ValueError(
                f"{phase}: input '{node.name}' has {got[0]} samples but "
                f"'{first_name}' has {n0}; all inputs must share the "
                "leading batch axis")
    if y is None:
        return
    y = np.asarray(y)
    if n0 is not None and y.shape[0] != n0:
        raise ValueError(
            f"{phase}: label batch has {y.shape[0]} samples but the "
            f"inputs have {n0}; labels must share the leading batch axis")
    lt = getattr(ffmodel, "label_tensor", None)
    from ..ffconst import LossType

    sparse = (getattr(ffmodel, "loss_type", None) ==
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    if lt is not None and not sparse and \
            not getattr(ffmodel.executor, "repl_labels", False):
        want_tail = tuple(d for d in tuple(lt.dims)[1:] if d != 1)
        got_tail = tuple(d for d in y.shape[1:] if d != 1)
        if got_tail != want_tail:
            raise ValueError(
                f"{phase}: label batch shape {tuple(y.shape)} mismatches "
                f"the compiled label signature {tuple(lt.dims)} (trailing "
                f"dims {got_tail} != {want_tail}); check the loss target "
                "shape")
