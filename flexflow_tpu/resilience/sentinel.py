"""Divergence sentinels: NaN/Inf-guarded training steps.

A poisoned step (NaN loss or gradient, from a bad batch, an overflowed
bf16 path, or flaky hardware) must not be allowed to write NaN into the
weights — once it does, every later step is garbage and the run is lost.
The guarded step (``Executor.make_train_step(guard=True)``) checks
``isfinite(loss) & isfinite(|grad|²)`` *on device* and applies the
optimizer update under ``lax.cond``: a bad step returns params/opt_state
unchanged. The only extra host traffic is ONE boolean scalar per step,
read here.

``GuardedTrainStep`` is the host-side wrapper: it runs the guarded step,
pays the single scalar transfer, tracks consecutive failures, and tells the
fit loop when the ``--max-bad-steps`` budget is exhausted and a rollback to
the last committed checkpoint (with the reduced-LR escape hatch,
``resilience/session.py``) is due.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple


class GuardedTrainStep:
    """Host-side wrapper around the executor's guarded jitted step.

    Call shape matches the plain step (cache-extended models included); the
    return adds nothing — the verdict of the on-device finite check is read
    via :meth:`last_ok` bookkeeping inside ``__call__``:

        outs, ok = guard(params, opt_state, xs, labels, rng[, cache])

    ``outs`` is exactly what the unguarded step would return. ``ok`` is the
    host bool of the device-side check (the one scalar transfer per step).
    """

    def __init__(self, executor, max_bad_steps: int = 3):
        self.executor = executor
        self.max_bad_steps = max(int(max_bad_steps), 1)
        self.consecutive_bad = 0
        self.total_bad = 0
        self._fn = None

    @property
    def fn(self):
        if self._fn is None:
            self._fn = self.executor.make_train_step(guard=True)
        return self._fn

    def rebuild(self) -> None:
        """Drop the cached jitted step (after an LR change the update rule
        baked into the jit is stale; the executor cache must be invalidated
        by the caller first)."""
        self._fn = None

    def reset(self) -> None:
        self.consecutive_bad = 0

    def __call__(self, params, opt_state, xs, labels, rng,
                 cache: Optional[Any] = None) -> Tuple[tuple, bool]:
        if cache is not None:
            *outs, ok_dev = self.fn(params, opt_state, xs, labels, rng,
                                    cache)
        else:
            *outs, ok_dev = self.fn(params, opt_state, xs, labels, rng)
        ok = bool(ok_dev)  # THE one device->host scalar transfer
        if ok:
            self.consecutive_bad = 0
        else:
            self.consecutive_bad += 1
            self.total_bad += 1
        return tuple(outs), ok

    @property
    def should_rollback(self) -> bool:
        return self.consecutive_bad >= self.max_bad_steps
