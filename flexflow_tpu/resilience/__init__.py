"""flexflow_tpu.resilience: fault-tolerant training subsystem (ISSUE 4).

The reference FlexFlow inherits resilience from Legion's task runtime; our
JAX port makes it a first-class subsystem instead — a preemption, a NaN'd
loss, or a lost host must cost at most the work since the last committed
checkpoint, never the run:

* preemption-safe checkpointing: ``execution/checkpoint.py`` (atomic
  commit, background async save with backpressure, checksums, retention,
  exact data-pipeline resume) driven from ``Model.fit`` via
  ``--checkpoint-dir`` / ``--checkpoint-every`` / ``--resume``;
* divergence sentinels: ``sentinel.GuardedTrainStep`` (on-device NaN/Inf
  check, one scalar transfer, skip + rollback via ``--max-bad-steps``);
* elastic restart: ``elastic.elastic_restore`` (re-run the Unity search on
  a degraded mesh, host-staged resharding of the restored pytree);
* deterministic fault injection for testing all of it on CPU:
  ``chaos.ChaosPlan`` / ``chaos.corrupt_checkpoint``;
* strategy safety (ISSUE 5, docs/strategy_safety.md): ``preflight``
  (static strategy/flag/batch validation), ``audit`` (parallel-correctness
  probe vs a single-device reference), ``fallback.StrategyCascade`` (the
  compile-time degrade-through-ranked-candidates cascade).

``session.ResilienceSession`` orchestrates the runtime concerns for one
``fit()``; ``fallback.StrategyCascade`` the compile-time ones. See
``docs/fault_tolerance.md`` and ``docs/strategy_safety.md``.
"""
from .audit import AuditError, AuditReport, audit_strategy  # noqa: F401
from .chaos import (ChaosPlan, FleetChaosPlan,  # noqa: F401
                    corrupt_checkpoint, inject_wrong_reshard,
                    poison_decode_state)
from .elastic import elastic_restore  # noqa: F401
from .fallback import (MemoryBudgetError, StrategyCascade,  # noqa: F401
                       StrategyCompileError, StrategySafetyError)
from .preflight import (PreflightError, preflight_config,  # noqa: F401
                        preflight_strategy, validate_batch)
from .sentinel import GuardedTrainStep  # noqa: F401
from .session import ResilienceSession  # noqa: F401
