"""Strategy fallback cascade: never trust one compilation.

The Unity search hands the executor ONE winning strategy; before this
module that plan was a single point of failure — XLA rejecting it, a
compile-time OOM, or a miscompiled resharding cost the whole run (or,
silently, its correctness). The cascade (ISSUE 5) makes the plan itself
fault-tolerant, the way PR 4 made the step loop fault-tolerant. Verification
runs ONCE before the fit loop (``StrategyCascade.preverify``); for the
active strategy, in order:

0. **static analysis** — ShardLint (``flexflow_tpu.analysis``, ISSUE 7):
   the placement-lattice abstract interpreter plus rules FF001-FF006
   over the live PCG + Strategy. A statically-rejected candidate
   degrades down the ranked chain WITHOUT paying a compile or probe
   step (the ``compile_probes`` counter proves it); ``--static-analysis
   off`` disables the stage;
1. **preflight** — static divisibility audit (``preflight.py``), free;
2. **compile check** — build the exact jitted step the loop will run and
   execute ONE step on throwaway device-side copies: XLA compile errors
   and first-step failures surface here (the jit cache is shared, so the
   loop's real first step pays no second compile). A
   ``ChaosPlan(fail_compiles=N)`` injection fails this stage on script;
3. **memory budget** — ``--memory-budget-mb``: XLA's compiled peak
   (``train_step_memory_analysis``) must fit, the ``-ll:fsize`` analog of
   the reference's per-device memory validation (graph.cc:1984-2032);
4. **audit** — ``--audit-strategy``: the parallel-correctness probe
   (``audit.py``) against a single-device reference within ``--audit-tol``.

On any failure the cascade degrades: next ranked search candidate
(``SearchResult.ranked``, re-mapped by node name onto a fresh PCG) → the
dp+full-remat last resort → abort with a diagnosis listing every rejected
plan and why. Pre-fit weight edits survive each hop (params are re-seeded
host-staged onto the new shardings). Every hop emits a
``strategy_fallback`` obs event and lands in ``StepTelemetry``'s
``strategy_safety`` block; ``--strategy-fallback off`` turns failures into
immediate errors (audit-only refusal mode). See ``docs/strategy_safety.md``.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..analysis.report import StaticAnalysisError
from .audit import AuditError
from .preflight import PreflightError, preflight_strategy


class StrategySafetyError(RuntimeError):
    """The strategy-safety layer rejected the plan (and, with the cascade
    on, every fallback after it)."""


class StrategyCompileError(StrategySafetyError):
    """The candidate failed the compile check (XLA rejection, first-step
    failure, or a scripted chaos injection)."""


class MemoryBudgetError(StrategySafetyError):
    """XLA's compiled peak exceeds ``--memory-budget-mb``."""


_FAILURE_KINDS = (PreflightError, AuditError, StrategySafetyError,
                  StaticAnalysisError)


class StrategyCascade:
    """One fit()'s strategy-safety verification + fallback driver."""

    def __init__(self, ffmodel, chaos=None):
        cfg = ffmodel.config
        self.model = ffmodel
        self.chaos = chaos
        self.tracer = ffmodel._obs_tracer()
        self.fallback_on = (getattr(cfg, "strategy_fallback", "on")
                            or "on") != "off"
        self.audit_on = bool(getattr(cfg, "audit_strategy", False))
        self.tol = float(getattr(cfg, "audit_tol", 0.05) or 0.05)
        self.budget_bytes = int(
            getattr(cfg, "memory_budget_mb", 0) or 0) * 2 ** 20
        # stage 0 (ISSUE 7): ShardLint static analysis — on unless
        # explicitly disabled; pure Python over graph metadata, so it is
        # free relative to any probe the cascade was armed to run anyway
        self.static_on = (getattr(cfg, "static_analysis", "on")
                          or "on") != "off"
        self.static_checks = 0
        self.static_rejects = 0
        self.static_rules: List[str] = []
        # compile/probe executions — the acceptance counter: a statically
        # rejected candidate must never increment this
        self.compile_probes = 0
        self.fallbacks = 0
        self.audits = 0
        self.audit_failures = 0
        self.audit_reports: List = []
        self._audit_ref_cache: dict = {}
        self.failures: List[Tuple[str, str]] = []
        self.final_desc = (ffmodel.strategy.describe()
                           if ffmodel.strategy is not None else "?")
        ranked = list(getattr(ffmodel, "_strategy_candidates", []) or [])
        # rank 0 is the winner the model already compiled; runners-up must
        # be SPMD (the cascade re-enters the SPMD fit loop — the GPipe
        # trainer is out of its scope) and carry a name-re-mappable
        # serialized strategy
        self._pending = [c for c in ranked[1:]
                         if c.strategy_json and not c.pipeline]
        self._dp_tried = False

    @classmethod
    def maybe_create(cls, ffmodel, chaos=None) -> Optional["StrategyCascade"]:
        """The cascade only arms when there is something to verify — the
        audit flag, a memory budget, or pending strategy chaos. A plain fit
        pays zero overhead (no probe step, no extra lowering).
        ``--strategy-fallback off`` does NOT disarm verification — it only
        turns failures into immediate errors (refusal mode)."""
        cfg = ffmodel.config
        audit = bool(getattr(cfg, "audit_strategy", False))
        budget = int(getattr(cfg, "memory_budget_mb", 0) or 0) > 0
        chaos_armed = chaos is not None and getattr(
            chaos, "strategy_chaos_pending", lambda: False)()
        if not (audit or budget or chaos_armed):
            return None
        return cls(ffmodel, chaos)

    # ------------------------------------------------------------- verify --
    def preverify(self, xs, y, batch_size: int) -> None:
        """Run the cascade to a verified strategy (possibly after several
        fallbacks) or raise a :class:`StrategySafetyError` diagnosis."""
        model = self.model
        # probe data is one fit batch; a dataset smaller than the batch
        # yields NO training steps (drop_remainder), so the execution
        # probes are skipped — but preflight still judges the REAL batch
        # size the loop would use, not the clipped probe
        n = min(int(batch_size), int(np.asarray(xs[0]).shape[0]))
        probe_xs = [np.asarray(a[:n]) for a in xs]
        probe_y = np.asarray(y[:n])
        run_probes = n == int(batch_size)
        # graph-level chaos (ISSUE 7 satellite): a scripted drop/duplicate
        # of a real reduction edge lands in the live PCG here, so the
        # static stage and the dynamic audit judge the SAME defect
        if self.chaos is not None and getattr(
                self.chaos, "graph_defect_pending", lambda: False)():
            desc = self.chaos.apply_wrong_reshard(model)
            if desc:
                self.tracer.event("chaos_graph_defect", detail=desc[:300])
        while True:
            desc = (model.strategy.describe()
                    if model.strategy is not None else "?")
            try:
                self._verify_current(desc, probe_xs, probe_y, batch_size,
                                     run_probes)
            except _FAILURE_KINDS as e:
                reason = f"{type(e).__name__}: {e}"
                self.failures.append((desc, reason))
                self.tracer.event("strategy_rejected", strategy=desc,
                                  reason=reason[:300])
                if not self.fallback_on:
                    raise
                self._fall_back(reason, cause=e)
                continue
            self.final_desc = (model.strategy.describe()
                               if model.strategy is not None else "?")
            if self.fallbacks:
                self.tracer.event("strategy_fallback_final",
                                  strategy=self.final_desc,
                                  fallbacks=self.fallbacks)
            return

    def _fall_back(self, reason: str, cause: Exception) -> None:
        """Advance to the next applicable candidate; a candidate that
        fails to APPLY (its own preflight at compile, a bad remap) joins
        the diagnosis and the cascade keeps degrading rather than dying
        with a bare error."""
        while True:
            nxt = self._next_candidate()
            if nxt is None:
                lines = "\n".join(f"  {d}: {r}" for d, r in self.failures)
                raise StrategySafetyError(
                    "strategy-safety cascade exhausted — every candidate "
                    "(ranked search results and the dp+full-remat last "
                    "resort) was rejected:\n" + lines) from cause
            try:
                self._apply(nxt, reason=reason)
                return
            except Exception as e:
                to_desc = (nxt if isinstance(nxt, str) else nxt.describe())
                self.failures.append(
                    (to_desc,
                     f"fallback apply failed: {type(e).__name__}: {e}"))

    def _verify_current(self, desc: str, probe_xs, probe_y,
                        batch_size: int, run_probes: bool = True) -> None:
        import jax

        model = self.model
        if self.static_on:
            self._static_check(desc)
        # stage 0's analyzer already ran FF006 (the per-node spec half of
        # preflight) over this exact (pcg, strategy) — don't walk it twice
        preflight_strategy(model.pcg, model.strategy,
                           n_dev=len(jax.devices()), batch_size=batch_size,
                           spec_checks=not self.static_on)
        if not run_probes:
            return
        self._compile_check(desc, probe_xs, probe_y)
        if self.budget_bytes:
            self._memory_check(desc, probe_xs, probe_y)
        if self.audit_on:
            self._audit_check(desc, probe_xs, probe_y)

    def _static_check(self, desc: str) -> None:
        """Stage 0 (ISSUE 7): run ShardLint over the candidate. An
        erroring report raises :class:`StaticAnalysisError` — rejection is
        free (no compile, no probe step; ``compile_probes`` untouched)."""
        from ..analysis import analyze_model

        self.static_checks += 1
        report = analyze_model(self.model)
        self.tracer.event("strategy_static", strategy=desc,
                          diagnostics=len(report.diagnostics),
                          errors=len(report.errors),
                          rules=",".join(report.rules_fired()))
        if report.errors:
            self.static_rejects += 1
            for d in report.errors:
                if d.rule_id not in self.static_rules:
                    self.static_rules.append(d.rule_id)
            raise StaticAnalysisError(report, context=desc)

    def _compile_check(self, desc: str, probe_xs, probe_y) -> None:
        """Compile the EXACT jitted step the loop will dispatch (guarded
        when the sentinel is on) and execute one step on donation-safe
        device copies — the result is discarded, the jit cache stays warm
        for the loop's real first step."""
        import jax

        model = self.model
        self.compile_probes += 1
        if self.chaos is not None and self.chaos.consume_compile_failure():
            raise StrategyCompileError(
                f"chaos: injected XLA compile failure for {desc}")
        from ..execution.checkpoint import _device_snapshot

        guard = int(getattr(model.config, "max_bad_steps", 0) or 0) > 0
        try:
            step = model.executor.make_train_step(guard=guard)
            ex = model.executor
            in_sh = [ex.batch_sharding(a.ndim) for a in probe_xs]
            bx = [jax.device_put(a, s) for a, s in zip(probe_xs, in_sh)]
            by = jax.device_put(probe_y, ex.batch_sharding(probe_y.ndim))
            args = (_device_snapshot(model.params),
                    _device_snapshot(model.opt_state), bx, by,
                    jax.random.PRNGKey(0))
            if ex.cache_nodes:
                args = args + (ex.init_cache(),)
            out = step(*args)
            jax.block_until_ready(out[2])  # the loss: compile + one step ran
        except _FAILURE_KINDS:
            raise
        except Exception as e:
            raise StrategyCompileError(
                f"{desc}: train-step compile / first-step probe failed: "
                f"{type(e).__name__}: {e}") from e

    def _memory_check(self, desc: str, probe_xs, probe_y) -> None:
        import warnings

        from ..obs.telemetry import peak_memory_bytes

        model = self.model
        try:
            ma = model.executor.train_step_memory_analysis(
                model.params, model.opt_state, probe_xs, probe_y)
        except Exception as e:
            # a backend without compiled memory stats makes the gate moot,
            # but NEVER silently: the user asked for a hard OOM gate
            warnings.warn(
                f"--memory-budget-mb check skipped for {desc}: compiled "
                f"memory analysis unavailable ({type(e).__name__}: {e})")
            return
        peak = peak_memory_bytes(ma)
        if peak is not None and peak > self.budget_bytes:
            raise MemoryBudgetError(
                f"{desc}: XLA compiled peak {peak / 2 ** 20:.1f} MiB "
                f"exceeds --memory-budget-mb "
                f"{self.budget_bytes // 2 ** 20} MiB")

    def _audit_check(self, desc: str, probe_xs, probe_y) -> None:
        from .audit import audit_strategy

        self.audits += 1
        # the single-device reference is candidate-independent (same graph,
        # same host weights, same probe): computed once, reused across
        # every candidate this cascade audits
        report = audit_strategy(self.model, probe_xs, probe_y, tol=self.tol,
                                chaos=self.chaos,
                                ref_cache=self._audit_ref_cache)
        self.audit_reports.append(report)
        self.tracer.event("strategy_audit", strategy=desc,
                          passed=bool(report.passed),
                          loss_rel_err=round(report.loss_rel_err, 6),
                          grad_rel_err=round(report.grad_rel_err, 6))
        if not report.passed:
            self.audit_failures += 1
            raise AuditError(
                f"{desc}: parallel-correctness audit failed — "
                + report.detail())

    # ----------------------------------------------------------- fallback --
    def _next_candidate(self):
        if self._pending:
            return self._pending.pop(0)
        if not self._dp_tried:
            self._dp_tried = True
            return "dp_full_remat"
        return None

    def _apply(self, cand, reason: str = "") -> None:
        """Recompile the model under the fallback candidate, preserving the
        live weights host-staged across the hop (pre-fit weight edits must
        survive; shapes are strategy-independent)."""
        import jax

        model = self.model
        from_desc = (model.strategy.describe()
                     if model.strategy is not None else "?")
        host = {ln: {wn: np.asarray(a) for wn, a in ws.items()}
                for ln, ws in model.params.items()}
        if cand == "dp_full_remat":
            n_dev = len(jax.devices())
            from ..parallel.strategy import data_parallel_strategy

            def strategy_fn(pcg):
                s = data_parallel_strategy(pcg, n_dev)
                s.remat = "full"
                return s

            to_desc = f"mesh=({n_dev},) remat=full"
        else:
            from ..parallel.strategy import Strategy

            text = cand.strategy_json

            def strategy_fn(pcg):
                return Strategy.from_json(text, pcg)

            to_desc = cand.describe()
        model.compile(optimizer=model.optimizer, loss_type=model.loss_type,
                      metrics=(model.metrics_obj.measures
                               if model.metrics_obj else None),
                      strategy_fn=strategy_fn)
        # counted/emitted only once the hop actually took effect — a
        # candidate that fails to compile joins the diagnosis instead
        self.fallbacks += 1
        self.tracer.event("strategy_fallback", from_strategy=from_desc,
                          to_strategy=to_desc, reason=reason[:300],
                          fallback=self.fallbacks)
        for ln, ws in host.items():
            for wn, a in ws.items():
                cur = model.params.get(ln, {}).get(wn)
                if cur is not None and np.asarray(cur).shape == a.shape:
                    model.params[ln][wn] = jax.device_put(
                        a, cur.sharding if hasattr(cur, "sharding")
                        else None)
        model.opt_state = model.optimizer.init_state(model.params)

    # ---------------------------------------------------------- telemetry --
    def merge_telemetry(self, telemetry) -> None:
        if telemetry is None:
            return
        telemetry.strategy_fallbacks += self.fallbacks
        telemetry.audit_runs += self.audits
        telemetry.audit_failures += self.audit_failures
        telemetry.final_strategy = self.final_desc
        telemetry.static_checks += self.static_checks
        telemetry.static_rejects += self.static_rejects
        for r in self.static_rules:
            if r not in telemetry.static_rules:
                telemetry.static_rules.append(r)
