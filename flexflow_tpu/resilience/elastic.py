"""Elastic restart: resume a checkpoint on a degraded (or grown) mesh.

A lost host or a shrunk TPU slice changes the device count; the checkpoint
on disk was sharded for the old topology and its strategy may not even be
expressible on the survivors. The reference has no answer to this (Legion
restarts the whole job); related elastic-training work (Varuna, EuroSys'21)
shows the right shape: re-plan for the surviving machine, reshard, continue.

``elastic_restore`` does exactly that: when the target device count differs
from the checkpoint's recorded topology it re-runs the Unity strategy
search on the surviving devices (``FFModel.compile`` with the device-count
override; pass ``sim=`` to reuse a warm delta-cost Simulator's memoized
cost tables — the PR-2 caches make the re-search a fraction of a cold
one), rebuilds mesh + executor for the winning strategy, and restores the
checkpoint *host-staged*: every leaf is read to host and ``device_put``
onto its new owner shards. Training then continues at the restored step.
"""
from __future__ import annotations

import time
from typing import Optional

from ..execution.checkpoint import (read_meta, restore_checkpoint,
                                    restore_train_cursor)


def elastic_restore(ffmodel, path: str, n_dev: Optional[int] = None,
                    sim=None, verify: bool = True) -> int:
    """Restore ``path`` onto the current (possibly degraded) topology.

    ``n_dev`` defaults to the live ``jax.devices()`` count; pass it
    explicitly to target a sub-mesh (tests use this to simulate a halved
    slice on the virtual CPU mesh). ``sim`` is an optional warm
    ``search.simulator.Simulator`` whose memoized cost tables the
    re-search reuses. Returns the checkpoint's step; the model's
    ``_rng_counter`` is restored from ``train_state.json`` when present so
    a following ``fit(resume=...)``-style continuation is exact.
    """
    import jax
    import numpy as np

    meta = read_meta(path)
    n_dev = int(n_dev if n_dev is not None else len(jax.devices()))
    saved_ndev = int(meta.get("n_devices")
                     or np.prod(meta.get("mesh_shape", [1])))
    cur_shape = (list(ffmodel.strategy.mesh_shape)
                 if ffmodel.strategy is not None else None)
    if n_dev == saved_ndev and cur_shape == list(meta.get("mesh_shape", [])):
        step = restore_checkpoint(ffmodel, path, verify=verify)
        restore_train_cursor(ffmodel, path)
        return step

    # topology changed: re-plan on the surviving devices, then reshard
    tracer = ffmodel._obs_tracer()
    t0 = time.perf_counter()
    ffmodel._search_sim = sim
    ffmodel._elastic_n_dev = n_dev
    try:
        ffmodel.compile(
            optimizer=ffmodel.optimizer, loss_type=ffmodel.loss_type,
            metrics=(ffmodel.metrics_obj.measures
                     if ffmodel.metrics_obj else None))
    finally:
        ffmodel._search_sim = None
        ffmodel._elastic_n_dev = None
    step = restore_checkpoint(ffmodel, path, verify=verify)
    restore_train_cursor(ffmodel, path)
    tracer.complete(
        "recovery", time.perf_counter() - t0, kind="elastic_restart",
        step=step, saved_devices=saved_ndev, new_devices=n_dev,
        new_mesh=list(ffmodel.strategy.mesh_shape))
    return step
