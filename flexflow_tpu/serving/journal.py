"""Crash-durable serving: the fleet-door write-ahead request journal.

Every resilience layer so far (PR 9 drain/evict, PR 11 migration and
circuits, PR 19 tenant ledgers) protects requests only while the host
process lives — a hard crash (OOM-kill, SIGKILL, host reboot) silently
loses the door queue, all in-flight streams, and every ledger, breaking
the north-star "exactly-one-outcome" invariant the moment real
infrastructure misbehaves. :class:`RequestJournal` (ISSUE 20,
docs/durability.md) is the explicit durability layer under the
:class:`~.fleet.ServingFleet` door, built on the same atomic-commit
idioms PR 4 proved for training checkpoints (shared via
``utils/durable_io.py``):

* **Write-ahead**: a ``submit`` record (rid, tenant, prompt ids,
  sampling params, deadline) is journaled BEFORE the request is
  admitted; an ``outcome`` record lands at the exactly-one-outcome
  terminal; an optional ``progress`` record persists each request's
  committed-token deltas every ``--journal-commit-every`` tokens.
* **Segmented, append-only, checksummed**: records are framed as
  ``crc32 <space> json\\n`` lines in ``journal_<seq>.log`` segments.
  On open, the live segment's torn tail — a crash mid-append — is
  truncated back to the longest valid record prefix; corruption in a
  SEALED segment raises :class:`JournalCorruptError` (history that
  later records depend on cannot be silently dropped).
* **Group commit**: appends buffer in-process and are flushed+fsynced
  at most once per ``--journal-sync-ms`` window (0 = every record).
  The un-synced window is the honest durability gap: a crash loses at
  most that window, and a request lost from it was never durably
  accepted.
* **Compaction**: a sealed segment whose every referenced rid has an
  outcome record is dropped, oldest-first (prefix order keeps a
  pending rid's submit/progress chain intact).
* **Exactly-once replay**: ``ServingFleet.recover()`` replays every
  rid with a submit but no outcome through the REAL door — WFQ,
  tenancy, quota and shed policies intact — rid-keyed dedupe against
  client retries, journaled progress resuming via the PR 11
  re-prefill path so recovered continuations are bitwise-identical
  under exact decode.

Journal off (the default) is the PR 16 noop-singleton contract:
:data:`NOOP_JOURNAL` — one shared, slotted, allocation-free no-op the
fleet hot path guards with ``if journal.enabled:``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Set

import numpy as np

from ..utils.durable_io import crc_bytes, fsync_path
from .resilience import OUTCOMES
from .scheduler import Request, now_ms

#: record kinds a journal segment may carry (docs/durability.md schema)
RECORD_KINDS = ("run", "submit", "progress", "outcome")

#: segment file name format: journal_<8-digit seq>.log
SEGMENT_PREFIX = "journal_"
SEGMENT_SUFFIX = ".log"


class JournalCorruptError(RuntimeError):
    """A sealed journal segment failed record-frame validation.

    Only SEALED segments raise: the live segment's torn tail is the
    expected signature of a crash mid-append and is truncated back to
    the longest valid record prefix instead."""


class NoopJournal:
    """The journal-off singleton (the PR 16 noop contract): one shared,
    slotted instance; every method a no-op; ``enabled`` is a class
    attribute so the fleet hot path's ``if journal.enabled:`` guard
    costs one attribute read and allocates nothing."""

    __slots__ = ()
    enabled = False
    commit_every = 0

    def log_run(self, **kw) -> None:
        return None

    def log_submit(self, req) -> bool:
        return True

    def log_progress(self, req) -> None:
        return None

    def log_outcome(self, req, outcome=None) -> bool:
        return False

    def maybe_sync(self) -> None:
        return None

    def sync(self) -> None:
        return None

    def compact(self) -> None:
        return None

    def close(self) -> None:
        return None


#: the shared journal-off instance — ``ServingFleet`` without
#: ``--request-journal`` holds exactly this object
NOOP_JOURNAL = NoopJournal()


def _encode(payload: Dict[str, Any]) -> bytes:
    data = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    return b"%08x " % crc_bytes(data) + data + b"\n"


class RequestJournal:
    """Segmented append-only write-ahead journal at the fleet door
    (module docstring has the full story; docs/durability.md the record
    schema and recovery state machine)."""

    enabled = True

    def __init__(self, root: str, sync_ms: float = 0.0,
                 commit_every: int = 0, segment_bytes: int = 1 << 18,
                 clock=None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.sync_ms = float(sync_ms)
        self.commit_every = int(commit_every)
        self.segment_bytes = max(int(segment_bytes), 1 << 10)
        self.clock = clock if clock is not None else now_ms
        # telemetry counters (StepTelemetry ``serving_journal`` block)
        self.appended = 0
        self.syncs = 0
        self.replayed = 0
        self.dedupe_hits = 0
        self.compacted_segments = 0
        self.truncated_records = 0
        self.recovery_wall_s = 0.0
        # replay state rebuilt by the open scan
        self._pending: Dict[int, Dict[str, Any]] = {}
        self._outcomes: Set[int] = set()
        self._progress_mark: Dict[int, int] = {}
        self._seg_rids: Dict[str, Set[int]] = {}
        self.run_args: Optional[Dict[str, Any]] = None
        # live segment + group-commit buffer: records wait here until
        # the sync window closes — an in-process hard crash drops the
        # buffer, exactly like SIGKILL drops a real process's un-fsynced
        # tail
        self._buf: List[bytes] = []
        self._buf_rids: List[Optional[int]] = []
        self._f = None
        self._seg_path: Optional[str] = None
        self._seg_seq = 0
        self._seg_size = 0
        self._last_sync_ms: Optional[float] = None
        self._crashed = False
        self._closed = False
        self._scan()

    # ----------------------------------------------------------------- scan
    def _segments(self) -> List[str]:
        out = []
        for fn in os.listdir(self.root):
            if fn.startswith(SEGMENT_PREFIX) and \
                    fn.endswith(SEGMENT_SUFFIX):
                out.append(os.path.join(self.root, fn))
        return sorted(out)

    def _scan(self) -> None:
        """Rebuild (pending, outcomes, progress) from every segment on
        disk, truncating the live segment's torn tail; appends then go
        to a FRESH segment (never into a file a dead writer tore)."""
        segs = self._segments()
        for i, seg in enumerate(segs):
            self._scan_segment(seg, last=(i == len(segs) - 1))
        if segs:
            base = os.path.basename(segs[-1])
            self._seg_seq = int(
                base[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]) + 1

    def _scan_segment(self, seg: str, last: bool) -> None:
        name = os.path.basename(seg)
        try:
            with open(seg, "rb") as f:
                data = f.read()
        except OSError as e:
            raise JournalCorruptError(
                f"journal segment {name}: unreadable ({e})")
        rids = self._seg_rids.setdefault(seg, set())
        off = good = 0
        while off < len(data):
            nl = data.find(b"\n", off)
            payload = None
            if nl >= 0:
                line = data[off:nl]
                try:
                    crc_hex, body = line.split(b" ", 1)
                    if int(crc_hex, 16) == crc_bytes(body):
                        payload = json.loads(body.decode("utf-8"))
                        if not isinstance(payload, dict) or \
                                payload.get("k") not in RECORD_KINDS:
                            payload = None
                except (ValueError, UnicodeDecodeError):
                    payload = None
            if payload is None:
                # torn/corrupt record: everything from here on is
                # untrusted — the longest VALID RECORD PREFIX survives
                lost = max(data.count(b"\n", off), 1)
                if not last:
                    raise JournalCorruptError(
                        f"journal segment {name}: corrupt record at "
                        f"byte {off} in a sealed segment ({lost} "
                        "record(s) unrecoverable)")
                self.truncated_records += lost
                break
            self._apply(payload, rids)
            good = off = nl + 1
        if good < len(data):
            with open(seg, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            fsync_path(self.root)

    def _apply(self, p: Dict[str, Any], rids: Set[int]) -> None:
        kind = p["k"]
        if kind == "run":
            self.run_args = {k: v for k, v in p.items() if k != "k"}
            return
        rid = int(p.get("rid", -1))
        rids.add(rid)
        if kind == "submit":
            if rid in self._outcomes or rid in self._pending:
                return  # duplicate submit record: first one wins
            p = dict(p)
            p["gen"] = []
            self._pending[rid] = p
            self._progress_mark[rid] = 0
        elif kind == "progress":
            ent = self._pending.get(rid)
            if ent is not None:
                ent["gen"].extend(int(t) for t in p.get("toks", ()))
                self._progress_mark[rid] = len(ent["gen"])
        elif kind == "outcome":
            self._outcomes.add(rid)
            self._pending.pop(rid, None)
            self._progress_mark.pop(rid, None)

    # --------------------------------------------------------------- append
    def _record(self, payload: Dict[str, Any],
                rid: Optional[int]) -> None:
        if self._crashed or self._closed:
            return
        self._buf.append(_encode(payload))
        self._buf_rids.append(rid)
        self.appended += 1
        self.maybe_sync()

    def _rotate(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        self._seg_path = os.path.join(
            self.root,
            f"{SEGMENT_PREFIX}{self._seg_seq:08d}{SEGMENT_SUFFIX}")
        self._seg_rids.setdefault(self._seg_path, set())
        self._seg_seq += 1
        self._seg_size = 0
        self._f = open(self._seg_path, "ab")
        fsync_path(self.root)

    def maybe_sync(self) -> None:
        """Group commit: flush+fsync when the ``--journal-sync-ms``
        window has closed (0 = every record is its own commit)."""
        if not self._buf:
            return
        now = float(self.clock())
        if self._last_sync_ms is None:
            self._last_sync_ms = now
        if self.sync_ms <= 0 or \
                (now - self._last_sync_ms) >= self.sync_ms:
            self.sync()

    def sync(self) -> None:
        """Make every buffered record durable: one write + one fsync
        for the whole group (the group-commit payoff)."""
        if self._crashed or self._closed or not self._buf:
            return
        if self._f is None or self._seg_size >= self.segment_bytes:
            self._rotate()
        assert self._f is not None and self._seg_path is not None
        blob = b"".join(self._buf)
        self._f.write(blob)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._seg_size += len(blob)
        seg_rids = self._seg_rids.setdefault(self._seg_path, set())
        seg_rids.update(r for r in self._buf_rids if r is not None)
        self._buf.clear()
        self._buf_rids.clear()
        self.syncs += 1
        self._last_sync_ms = float(self.clock())

    # ------------------------------------------------------------ WAL hooks
    def log_run(self, **serve_args) -> None:
        """Journal the serve-loop arguments (temperature, top_k, seed)
        so a recovery can rerun the exact sampling configuration."""
        payload = {"k": "run"}
        payload.update(serve_args)
        if self.run_args != serve_args:
            self.run_args = dict(serve_args)
            self._record(payload, None)

    def log_submit(self, req: Request) -> bool:
        """Write-ahead the door admission. Returns False — and counts a
        dedupe hit — when the rid is already journaled (a client retry
        of a submitted-or-finished request must not double-admit)."""
        rid = int(req.rid)
        if rid in self._outcomes or rid in self._pending:
            self.dedupe_hits += 1
            return False
        payload: Dict[str, Any] = {
            "k": "submit", "rid": rid,
            "p": [int(t) for t in req.prompt],
            "m": int(req.max_new_tokens)}
        if req.tenant:
            payload["t"] = req.tenant
        if req.deadline_ms is not None:
            payload["d"] = float(req.deadline_ms)
        if req.rng_tag is not None:
            payload["g"] = int(req.rng_tag)
        if req.eos_id is not None:
            payload["e"] = int(req.eos_id)
        ent = dict(payload)
        ent["gen"] = []
        self._pending[rid] = ent
        self._progress_mark[rid] = len(req.generated)
        self._record(payload, rid)
        return True

    def log_progress(self, req: Request) -> None:
        """Persist the committed-token delta once it reaches
        ``--journal-commit-every`` tokens — the scheduler's
        ``on_commit`` hook calls this at THE commit point, so a
        journaled prefix is always a prefix of the real stream."""
        if self.commit_every <= 0:
            return
        rid = int(req.rid)
        mark = self._progress_mark.get(rid)
        if mark is None:  # unknown rid (hedge twin) or already terminal
            return
        n = len(req.generated)
        if n - mark < self.commit_every:
            return
        toks = [int(t) for t in req.generated[mark:n]]
        self._progress_mark[rid] = n
        ent = self._pending.get(rid)
        if ent is not None:
            ent["gen"].extend(toks)
        self._record({"k": "progress", "rid": rid, "toks": toks,
                      "n": n}, rid)

    def log_outcome(self, req: Request,
                    outcome: Optional[str] = None) -> bool:
        """The exactly-one-outcome terminal: first call per rid wins,
        repeats and unknown rids (hedge twins) are dropped."""
        rid = int(req.rid)
        if rid in self._outcomes or rid not in self._pending:
            return False
        out = outcome or req.outcome or ("ok" if req.done else
                                         "preempted")
        if out not in OUTCOMES:   # the ledger vocabulary is closed
            raise ValueError(f"unknown outcome {out!r} for rid {rid} "
                             f"(expected one of {OUTCOMES})")
        self._outcomes.add(rid)
        self._pending.pop(rid, None)
        self._progress_mark.pop(rid, None)
        self._record({"k": "outcome", "rid": rid, "o": out,
                      "n": len(req.generated)}, rid)
        return True

    # --------------------------------------------------------------- replay
    def pending_rids(self) -> List[int]:
        return sorted(self._pending)

    def max_rid(self) -> int:
        return max(list(self._pending) + list(self._outcomes),
                   default=0)

    def pending_requests(self) -> List[Request]:
        """Reconstruct every journaled-but-unfinished request, in rid
        order: prompt + sampling params from the submit record, the
        committed-token prefix from its progress records (the PR 11
        re-prefill path resumes it bitwise under exact decode). The
        deadline budget restarts at re-submission — monotonic clocks do
        not survive a process, so the pre-crash wait cannot be
        charged."""
        out = []
        for rid in self.pending_rids():
            p = self._pending[rid]
            out.append(Request(
                prompt=np.asarray(p["p"], dtype=np.int32),
                max_new_tokens=int(p["m"]),
                rid=rid,
                eos_id=p.get("e"),
                generated=list(p.get("gen", [])),
                rng_tag=p.get("g"),
                deadline_ms=p.get("d"),
                tenant=p.get("t")))
        return out

    # ----------------------------------------------------------- compaction
    def compact(self) -> int:
        """Drop sealed segments whose every referenced rid has an
        outcome — oldest first, stopping at the first segment still
        holding a pending rid's history (prefix order keeps every
        pending submit/progress chain intact). Returns segments
        dropped."""
        dropped = 0
        for seg in self._segments():
            if seg == self._seg_path:
                break  # never the live segment
            rids = self._seg_rids.get(seg)
            if rids is None or not rids <= self._outcomes:
                break
            try:
                os.remove(seg)
            except OSError:
                break
            self._seg_rids.pop(seg, None)
            dropped += 1
        if dropped:
            fsync_path(self.root)
            self.compacted_segments += dropped
        return dropped

    # -------------------------------------------------------------- lifecycle
    def crash(self) -> None:
        """In-process hard-stop (``FleetChaosPlan.crash_at`` tier-1
        mode): drop the un-group-committed buffer and abandon the file
        — exactly what SIGKILL does to a real process's un-fsynced
        tail. The journal object is dead afterwards; recovery goes
        through a fresh ``RequestJournal`` on the same directory."""
        self._buf.clear()
        self._buf_rids.clear()
        self._crashed = True
        if self._f is not None:
            try:
                os.close(self._f.fileno())  # bypass buffered flush
            except OSError:
                pass
            self._f = None

    def close(self) -> None:
        """Graceful close: group-commit the tail, compact, release the
        segment handle. Idempotent."""
        if self._crashed or self._closed:
            return
        self.sync()
        self.compact()
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None
        self._closed = True


def journal_from_config(config, clock=None):
    """The one construction point the fleet and ``recover()`` share:
    ``--request-journal DIR`` (+ ``--journal-sync-ms`` /
    ``--journal-commit-every``) -> a live :class:`RequestJournal`;
    unset -> the shared :data:`NOOP_JOURNAL` singleton (allocation-free
    serve hot path)."""
    root = getattr(config, "request_journal", "") or ""
    if not root:
        return NOOP_JOURNAL
    return RequestJournal(
        root,
        sync_ms=float(getattr(config, "journal_sync_ms", 0.0) or 0.0),
        commit_every=int(getattr(config, "journal_commit_every", 0)
                         or 0),
        clock=clock)
