"""Unity serving objective: latency-bounded throughput search (ISSUE 6).

``serving_search`` sits next to the training step-time objective
(search/unity.unity_search, reachable through the same façade as
``search.unity.search_all(objective="serving")``): it sweeps mesh
factorizations (dp replicas x tp within a replica) AND the decode-state
layout (KV cache sharded over heads vs replicated) for the *decode* graph,
and picks the plan maximizing simulated tokens/sec subject to
``simulated p99 <= --slo-p99-ms`` and the per-chip HBM budget.

Cost model (documented, deliberately simple — decode is the
weight-streaming regime):

* the decode graph is the model's graph re-inferred at
  ``(slots_per_replica, 1)`` shapes; each op is priced by the SAME
  memoized ``Simulator.op_cost`` the training search uses (delta-cost
  engine, PR 2 — entries persist across candidates, SLO iterations and
  elastic re-searches), with the Megatron-style kind assignment: linear
  layers alternate col/row (one allreduce per pair), attention shards
  heads, embeddings shard the table. Serving is forward-only, so comm is
  half of op_cost's fwd+bwd pricing and sync/update are dropped.
* the KV ring buffer is priced explicitly — op flops at seq-1 shapes miss
  it entirely: each attention node streams
  ``2 * slots * heads * max_len * head_dim * el`` bytes per decode step
  (divided by tp under the sharded layout), and the same bytes count
  against per-chip HBM. This is the "decode-state layout/sharding is a
  searched axis priced by the simulator's memory accounting" inversion of
  the old CacheOp opt-out.
* p50 = decode step; p99 = decode step + one max-bucket prefill (a newly
  admitted request's prefill stalls the in-flight batch for one
  iteration — the continuous-batching worst case).
* tokens/sec = total slots / decode step (every slot advances one token
  per iteration, replicas run concurrently).

Under ``FLEXFLOW_TPU_SEARCH_SELFCHECK`` every candidate is re-priced on a
fresh Simulator and the winner must be identical — the same equivalence
gate the delta-cost engine runs for training sweeps.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ffconst import OperatorType, size_of_datatype
from ..parallel.pcg import PCG, PCGNode
from .kvcache import is_position_constant


class ServingSearchError(RuntimeError):
    """The graph could not be re-inferred at decode shapes (baked
    shape-carrying ops like reshape); serve such models via explicit
    prefill/decode steps instead of the searched plan."""


@dataclasses.dataclass
class ServingCandidate:
    """One priced (mesh, layout, kv_dtype) point of the serving sweep."""

    mesh_shape: Tuple[int, int]
    layout: str  # "sharded" | "replicated" (KV-cache over the model axis?)
    slots_per_replica: int
    # KV storage dtype (ISSUE 12): "native" or "int8" — int8 streams
    # ~1/el of the KV bytes (+ f32 scales) per decode step, the
    # precision-for-bandwidth trade the latency-bounded objective prices
    kv_dtype: str = "native"
    sim_decode_ms: float = 0.0
    sim_prefill_ms: float = 0.0
    sim_p50_ms: float = 0.0
    sim_p99_ms: float = 0.0
    sim_tokens_per_s: float = 0.0
    sim_memory: int = 0
    feasible: bool = True

    def describe(self) -> str:
        return (f"mesh={tuple(self.mesh_shape)} kv={self.layout} "
                f"kv_dtype={self.kv_dtype} "
                f"slots/replica={self.slots_per_replica}")


@dataclasses.dataclass
class ServingPlan:
    """The serving search's winner plus the ranked runner-up chain (the
    strategy-safety shape of PR 5: an elastic replan degrades through the
    same list)."""

    mesh_shape: Tuple[int, int]
    layout: str
    slots: int
    max_decode_len: int
    slo_p99_ms: float
    sim_decode_ms: float
    sim_prefill_ms: float
    sim_p50_ms: float
    sim_p99_ms: float
    sim_tokens_per_s: float
    sim_memory: int
    feasible: bool
    kv_dtype: str = "native"
    # expected prefill-token reuse fraction the p99 was priced at
    # (ISSUE 14: measured prefix-cache hit rate, or an assumption)
    prefill_reuse: float = 0.0
    # sequence-parallel decode (ISSUE 18): the searched context-length
    # buckets and the seq_shards the ICI closed forms picked for each —
    # admission routes a request to its bucket (``seq_shards_for``)
    context_buckets: Tuple[int, ...] = ()
    seq_shards_by_bucket: Dict[int, int] = dataclasses.field(
        default_factory=dict)
    assignment: Dict[int, object] = dataclasses.field(default_factory=dict)
    ranked: List[ServingCandidate] = dataclasses.field(default_factory=list)
    sim: object = None  # the warm Simulator (elastic re-search reuse)

    def seq_shards_for(self, context_len: int) -> int:
        """Admission routing: the searched seq_shards of the smallest
        bucket covering ``context_len`` (requests beyond every bucket
        take the largest — they must shard hardest); 1 when the search
        ran without buckets."""
        if not self.context_buckets:
            return 1
        for b in self.context_buckets:
            if context_len <= b:
                return self.seq_shards_by_bucket.get(b, 1)
        return self.seq_shards_by_bucket.get(self.context_buckets[-1], 1)

    def describe(self) -> str:
        return (f"mesh={tuple(self.mesh_shape)} kv={self.layout} "
                f"kv_dtype={self.kv_dtype} "
                f"tokens/s={self.sim_tokens_per_s:.1f} "
                f"p99={self.sim_p99_ms:.2f}ms")

    def to_strategy(self, pcg: PCG):
        """Materialize as an executor Strategy (weight shardings by node)
        — same machinery as the training search's winner."""
        from ..parallel.strategy import data_parallel_strategy
        from ..search.unity import assignment_to_strategy

        dp, tp = self.mesh_shape
        if tp <= 1 or not self.assignment:
            return data_parallel_strategy(pcg, dp)
        try:
            return assignment_to_strategy(pcg, self.assignment, {}, dp, tp)
        except Exception:
            return data_parallel_strategy(pcg, dp * tp)


# ------------------------------------------------------------ decode graph
def _rescaled_shape(shape: Tuple[int, ...], batch: int, seq: int
                    ) -> Tuple[int, ...]:
    if len(shape) >= 2:
        return (batch, seq) + tuple(shape[2:])
    return shape


def reshape_graph(pcg: PCG, batch: int, seq: int) -> PCG:
    """The model's graph re-inferred at serving shapes ``(batch, seq)``
    without touching the original (ops are shared between PCG copies, so
    shape-bearing ops — inputs, position constants — are shallow-copied
    with fresh attrs). Raises ServingSearchError when an op's baked shape
    cannot follow (e.g. a hard reshape)."""
    g = PCG()
    g._order = list(pcg._order)
    for guid in pcg._order:
        n = pcg.nodes[guid]
        op = n.op
        try:
            if op.op_type in (OperatorType.OP_INPUT, OperatorType.OP_WEIGHT):
                if op.op_type == OperatorType.OP_INPUT:
                    op = copy.copy(op)
                    op.attrs = dict(op.attrs)
                    op.attrs["shape"] = _rescaled_shape(
                        tuple(n.out_shapes[0]), batch, seq)
                    out_shapes = [op.attrs["shape"]]
                else:
                    out_shapes = list(n.out_shapes)
            elif op.op_type == OperatorType.OP_CONSTANT and \
                    is_position_constant(op.attrs.get("value")):
                v = np.asarray(op.attrs["value"])
                op = copy.copy(op)
                op.attrs = dict(op.attrs)
                op.attrs["value"] = np.broadcast_to(
                    np.arange(seq, dtype=v.dtype), (batch, seq)).copy()
                out_shapes = [(batch, seq)]
            else:
                in_shapes = [g.nodes[pg].out_shapes[pi]
                             for pg, pi in n.inputs]
                out_shapes = op.infer_output_shapes(in_shapes)
        except Exception as e:
            raise ServingSearchError(
                f"{n.name} ({op.op_type.name}) cannot re-infer at serving "
                f"shapes (batch={batch}, seq={seq}): {e}") from e
        g.nodes[guid] = PCGNode(
            guid=guid, op=op, inputs=list(n.inputs),
            out_shapes=[tuple(s) for s in out_shapes],
            out_dtypes=list(n.out_dtypes))
    return g


# ------------------------------------------------------------ cost pricing
_W_SHARD = {
    OperatorType.OP_MULTIHEAD_ATTENTION: "heads",
    OperatorType.OP_EMBEDDING: "table",
    OperatorType.OP_EXPERTS: "expert",
}


def _pick_kind(node: PCGNode, tp: int,
               in_shapes: List[Tuple[int, ...]], flip: List[bool]) -> str:
    """Megatron-style kind assignment for inference: linears alternate
    col -> row (the col half pays no collective, the row half's allreduce
    closes the pair), attention shards heads, embeddings the table. Both
    halves respect divisibility — an unshardable dim keeps the op
    replicated, so every priced kind is realizable by
    ``assignment_to_strategy``."""
    if tp <= 1:
        return "none"
    a = node.op.attrs
    ot = node.op.op_type
    if ot == OperatorType.OP_LINEAR:
        col_ok = a.get("out_dim", 0) % tp == 0
        in_ok = bool(in_shapes) and in_shapes[0][-1] % tp == 0
        if flip[0]:
            if col_ok:
                flip[0] = False
                return "col"
            return "none"
        flip[0] = True  # the pair closes here (or resets on fallback)
        if in_ok:
            return "row"  # row eats the col half's sharded activation
        return "col" if col_ok else "none"
    kind = _W_SHARD.get(ot)
    if kind == "heads" and a.get("num_heads", 0) % tp == 0:
        return "heads"
    if kind == "table" and a.get("num_entries", 0) % tp == 0:
        return "table"
    if kind == "expert" and a.get("n", 0) % tp == 0:
        return "expert"
    return "none"


def _attention_state_bytes(node: PCGNode, slots: int, max_len: int,
                           kv_dtype: str = "native") -> int:
    from .kvcache import kv_token_bytes

    a = node.op.attrs
    heads = int(a.get("num_heads", 1))
    kdim = int(a.get("kdim") or a["embed_dim"] // heads)
    vdim = int(a.get("vdim") or a["embed_dim"] // heads)
    return slots * max_len * kv_token_bytes(
        heads, kdim, vdim, size_of_datatype(node.op.data_type), kv_dtype)


def _graph_cost(sim, g: PCG, tp: int, kv_div: int, slots: int,
                max_len: int, decode: bool, kv_dtype: str = "native",
                kv_fill: float = 1.0):
    """(step_time_s, per_chip_mem_bytes, assignment) for one re-inferred
    serving graph under degree-``tp`` model parallelism. Forward-only:
    comm is half the op_cost fwd+bwd figure, sync/update dropped, no
    optimizer state in the memory model.

    ``kv_dtype`` selects the KV-stream element size (ISSUE 12: int8
    streams ~1/el the bytes plus f32 scales); ``kv_fill`` scales the
    per-step KV READ traffic (1.0 = the ring layout's O(max_len) bill;
    the paged flash-decode path reads only occupied blocks, so a
    measured mean-occupancy fill prices its true traffic). Pool
    CAPACITY is always charged at full extent — feasibility must hold
    at worst case."""
    from ..search.simulator import OpSharding

    t = comm = 0.0
    mem_w = kv_bytes = 0
    transient = 0
    flip = [True]
    assignment: Dict[int, OpSharding] = {}
    m = sim.machine
    for node in g.compute_nodes():
        in_shapes = [g.nodes[pg].out_shapes[pi] for pg, pi in node.inputs]
        kind = _pick_kind(node, tp, in_shapes, flip)
        sh = OpSharding(dp=1, tp=(tp if kind != "none" else 1), kind=kind)
        assignment[node.guid] = sh
        cm = sim.op_cost(node, in_shapes, sh)
        t += cm.forward_time
        comm += cm.comm_time / 2.0
        mem_w += cm.weights_memory
        transient = max(transient, cm.inputs_memory + cm.outputs_memory)
        if decode:
            if node.op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
                kv_bytes += _attention_state_bytes(
                    node, slots, max_len, kv_dtype) // max(kv_div, 1)
            elif node.op.op_type == OperatorType.OP_LSTM:
                h = int(node.op.attrs["hidden_size"])
                kv_bytes += slots * 2 * h * size_of_datatype(
                    node.op.data_type)
    kv_time = kv_bytes * max(min(kv_fill, 1.0), 0.0) / (
        m.hbm_bandwidth * m.hbm_efficiency)
    return t + comm + kv_time, mem_w + kv_bytes + transient, assignment


def _bucket_seq_shards(pcg: PCG, machine, n_dev: int, slots: int,
                       bucket: int, kv_dtype: str,
                       kv_fill: float) -> Tuple[int, float, float, bool]:
    """Searched seq_shards for ONE context bucket (ISSUE 18): sweep the
    power-of-two shard widths dividing the mesh and pick the one
    minimizing the per-decode-step KV stream + ring-combine time from
    the ICI closed forms — the same pricing vocabulary as kv_fill/
    prefill_reuse, next to which this axis sits in the objective.

    Per shard width ``s``:

    * the bucket's KV read splits s ways and streams in parallel —
      ``t_kv = kv_read(bucket) / s / (hbm_bw * hbm_eff)``;
    * the combine pays two allgathers per attention node per step: the
      step's query rows out to every shard, the f32 ``(m, l, acc)``
      partial triples back (kernels/seqpar_decode.py byte helpers);
      widths spanning pods compose via ``hier_allgather_time`` (the
      PR 15 DCN x ICI law);
    * feasibility: one shard chip's share of the bucket's FULL-extent
      KV must fit its HBM (capacity is judged at worst case, like the
      sweep's memory term).

    Returns ``(seq_shards, t_kv_s, t_combine_s, fits)``; when no width
    fits, the widest is returned with ``fits=False`` — the least-bad
    plan, flagged rather than hidden."""
    from ..kernels.seqpar_decode import (combine_bytes_per_step,
                                         query_bytes_per_step)
    from .kvcache import kv_token_bytes

    nodes = [n for n in pcg.compute_nodes()
             if n.op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION]
    if not nodes:
        return 1, 0.0, 0.0, True
    fill = max(min(float(kv_fill), 1.0), 0.0)
    kv_cap = 0
    dims = []
    for node in nodes:
        a = node.op.attrs
        heads = int(a.get("num_heads", 1))
        kdim = int(a.get("kdim") or a["embed_dim"] // heads)
        vdim = int(a.get("vdim") or a["embed_dim"] // heads)
        el = size_of_datatype(node.op.data_type)
        kv_cap += slots * bucket * kv_token_bytes(
            heads, kdim, vdim, el, kv_dtype)
        dims.append((heads, kdim, vdim, el))
    hbm_stream = machine.hbm_bandwidth * machine.hbm_efficiency
    widths = []
    s = 1
    while s <= n_dev:
        if n_dev % s == 0:
            widths.append(s)
        s *= 2
    best = None
    widest = None
    for s in widths:
        t_kv = kv_cap * fill / s / hbm_stream
        t_comb = 0.0
        if s > 1:
            cpp = machine.chips_per_pod
            for heads, kdim, vdim, el in dims:
                qb = query_bytes_per_step(heads, kdim, slots, el)
                pb = combine_bytes_per_step(heads, vdim, slots, s)
                if s > cpp and s % cpp == 0:
                    t_comb += machine.hier_allgather_time(qb, cpp, s // cpp)
                    t_comb += machine.hier_allgather_time(pb, cpp, s // cpp)
                else:
                    t_comb += machine.allgather_time(qb, s)
                    t_comb += machine.allgather_time(pb, s)
        fits = kv_cap // s <= machine.hbm_capacity
        cand = (s, t_kv, t_comb, fits)
        widest = cand
        if fits and (best is None or
                     t_kv + t_comb < best[1] + best[2] - 1e-12):
            best = cand
    return best if best is not None else widest


# --------------------------------------------------------------- top level
def serving_search(pcg: PCG, config, n_dev: int, machine=None,
                   sim=None, max_inflight: Optional[int] = None,
                   max_decode_len: Optional[int] = None,
                   slo_p99_ms: Optional[float] = None,
                   kv_fill: float = 1.0,
                   prefill_reuse: float = 0.0,
                   context_buckets=None) -> ServingPlan:
    """Latency-bounded throughput search over (dp, tp, KV layout,
    kv_dtype) for the decode graph (kv_dtype ∈ {native, int8} is the
    ISSUE 12 precision-for-bandwidth axis; ``--kv-dtype`` pins it
    instead of searching). Returns the winning ServingPlan with the
    ranked runner-up chain; the warm Simulator rides along for elastic
    re-searches (``ServingEngine.elastic_replan``). ``kv_fill`` prices
    the decode KV read at a mean occupancy fraction (paged layout —
    bench's simulated paged-vs-ring ratio). ``prefill_reuse`` (ISSUE
    14) prices the prefix cache the same honest way: the expected
    fraction of prefill tokens served from the radix trie — measured
    (``ServingStats.prefix_reuse_rate``, what ``elastic_replan``
    feeds) or assumed — scales the p99 prefill stall term, so a
    high-hit-rate fleet stops over-providing for a cold-cache worst
    case the SLO never sees.

    ``context_buckets`` (ISSUE 18) makes context-length bucketing a
    searched axis: for each bucket (defaulted from
    ``config.context_buckets``) the objective picks seq_shards from the
    ICI closed forms (``_bucket_seq_shards``) and records it on the
    plan — ``plan.seq_shards_for(context_len)`` is the admission
    router's lookup."""
    import time as _time

    from ..obs import SearchLog, get_tracer
    from ..search.machine_model import TPUMachineModel
    from ..search.simulator import Simulator, selfcheck_enabled

    if machine is None:
        machine = TPUMachineModel.detect(n_dev)
    if sim is None:
        sim = Simulator(machine)
    slots = int(max_inflight or getattr(config, "max_inflight", 8))
    max_len = int(max_decode_len or getattr(config, "max_decode_len", 128))
    slo = slo_p99_ms if slo_p99_ms is not None else \
        float(getattr(config, "slo_p99_ms", 0.0) or 0.0)
    # --kv-dtype pins the axis; the default ("native" config value with
    # a paged cache) searches both storage dtypes
    pinned_dtype = str(getattr(config, "kv_dtype", "native") or "native")
    paged = str(getattr(config, "kv_cache", "paged") or "paged") == "paged"
    kv_dtypes: Tuple[str, ...]
    if not paged:
        kv_dtypes = ("native",)   # int8 is a paged-layout feature
    elif pinned_dtype != "native":
        kv_dtypes = (pinned_dtype,)
    else:
        kv_dtypes = ("native", "int8")

    tracer = get_tracer()
    slog = SearchLog(getattr(config, "search_log_file", "") or None,
                     kind="serving")
    hbm = machine.hbm_capacity
    # expected prefill savings from prefix reuse: a newly-admitted
    # request stalls the batch for only the UNCACHED fraction of its
    # prompt (zero-compute trie mapping covers the rest)
    reuse = max(min(float(prefill_reuse), 1.0), 0.0)
    t0 = _time.perf_counter()

    def sweep(active_sim) -> List[Tuple[ServingCandidate, Dict]]:
        from ..search.unity import factorizations

        out = []
        # the prefill graph is factorization-independent (batch 1, max
        # bucket) and its cost depends only on tp — build once, price per
        # distinct tp
        prefill_g = reshape_graph(pcg, 1, max_len)
        t_pre_by_tp: Dict[int, float] = {}
        for dp, tp in factorizations(n_dev):
            if slots % dp != 0:
                continue
            s_r = slots // dp
            decode_g = reshape_graph(pcg, s_r, 1)
            if tp not in t_pre_by_tp:
                t_pre_by_tp[tp], _pm, _a = _graph_cost(
                    active_sim, prefill_g, tp, 1, 1, max_len, decode=False)
            t_pre = t_pre_by_tp[tp] * (1.0 - reuse)
            layouts = ("sharded", "replicated") if tp > 1 else \
                ("replicated",)
            for layout in layouts:
                kv_div = tp if layout == "sharded" else 1
                for kv_dtype in kv_dtypes:
                    t_dec, mem, assignment = _graph_cost(
                        active_sim, decode_g, tp, kv_div, s_r, max_len,
                        decode=True, kv_dtype=kv_dtype, kv_fill=kv_fill)
                    p50 = t_dec * 1e3
                    p99 = (t_dec + t_pre) * 1e3
                    feas = mem <= hbm and (slo <= 0 or p99 <= slo)
                    out.append((ServingCandidate(
                        mesh_shape=(dp, tp), layout=layout,
                        slots_per_replica=s_r, kv_dtype=kv_dtype,
                        sim_decode_ms=round(t_dec * 1e3, 4),
                        sim_prefill_ms=round(t_pre * 1e3, 4),
                        sim_p50_ms=round(p50, 4), sim_p99_ms=round(p99, 4),
                        sim_tokens_per_s=slots / t_dec,
                        sim_memory=int(mem), feasible=bool(feas)),
                        assignment))
        return out

    with tracer.span("serving_search", n_dev=n_dev):
        cands = sweep(sim)
        if not cands:
            raise ServingSearchError(
                f"no serving candidate for n_dev={n_dev}: max_inflight="
                f"{slots} must be divisible by some dp factor")
        for c, _a in cands:
            slog.log(event="candidate", mesh=list(c.mesh_shape),
                     layout=c.layout, kv_dtype=c.kv_dtype,
                     slots_per_replica=c.slots_per_replica,
                     decode_ms=c.sim_decode_ms, prefill_ms=c.sim_prefill_ms,
                     p99_ms=c.sim_p99_ms,
                     tokens_per_s=round(c.sim_tokens_per_s, 2),
                     mem_mib=round(c.sim_memory / 2 ** 20, 1),
                     feasible=c.feasible, cost_ms=c.sim_decode_ms,
                     accepted=c.feasible)

        def rank_key(pair):
            c = pair[0]
            return (not c.feasible, -c.sim_tokens_per_s, c.sim_p99_ms,
                    repr((c.mesh_shape, c.layout, c.kv_dtype)))

        ordered = sorted(cands, key=rank_key)
        winner, win_assignment = ordered[0]

        if selfcheck_enabled():
            # delta-cost equivalence gate: the memoized sweep must price
            # identically to a cold simulator (same contract as the
            # training search's FLEXFLOW_TPU_SEARCH_SELFCHECK)
            fresh = sweep(Simulator(machine))
            fresh_ordered = sorted(fresh, key=rank_key)
            fw = fresh_ordered[0][0]
            assert (fw.mesh_shape, fw.layout, fw.kv_dtype) == \
                (winner.mesh_shape, winner.layout, winner.kv_dtype), \
                f"serving selfcheck: cached winner {winner.describe()} != " \
                f"fresh winner {fw.describe()}"
            for (a, _), (b, _) in zip(ordered, fresh_ordered):
                assert abs(a.sim_decode_ms - b.sim_decode_ms) <= \
                    1e-9 + 1e-6 * abs(b.sim_decode_ms), \
                    f"serving selfcheck: {a.describe()} cost drifted"

    # context-length bucketing (ISSUE 18): per searched bucket, pick
    # seq_shards from the ICI closed forms under the WINNER's kv_dtype
    # and slot count — the bucket axis rides on top of the chosen mesh
    from .kvcache import parse_context_buckets

    buckets = parse_context_buckets(
        context_buckets if context_buckets is not None
        else getattr(config, "context_buckets", "") or "")
    shards_by_bucket: Dict[int, int] = {}
    for bucket in buckets:
        bs, t_kv, t_comb, fits = _bucket_seq_shards(
            pcg, machine, n_dev, slots, bucket, winner.kv_dtype, kv_fill)
        shards_by_bucket[bucket] = bs
        slog.log(event="bucket", context_bucket=bucket, seq_shards=bs,
                 kv_stream_ms=round(t_kv * 1e3, 4),
                 combine_ms=round(t_comb * 1e3, 4),
                 kv_fits_one_chip=bool(fits),
                 cost_ms=round((t_kv + t_comb) * 1e3, 4), accepted=True)

    wall = _time.perf_counter() - t0
    plan = ServingPlan(
        mesh_shape=winner.mesh_shape, layout=winner.layout, slots=slots,
        max_decode_len=max_len, slo_p99_ms=slo,
        kv_dtype=winner.kv_dtype, prefill_reuse=reuse,
        context_buckets=buckets, seq_shards_by_bucket=shards_by_bucket,
        sim_decode_ms=winner.sim_decode_ms,
        sim_prefill_ms=winner.sim_prefill_ms,
        sim_p50_ms=winner.sim_p50_ms, sim_p99_ms=winner.sim_p99_ms,
        sim_tokens_per_s=winner.sim_tokens_per_s,
        sim_memory=winner.sim_memory, feasible=winner.feasible,
        assignment=win_assignment,
        ranked=[c for c, _a in ordered], sim=sim)
    slog.log(event="result", mesh=list(winner.mesh_shape),
             layout=winner.layout, kv_dtype=winner.kv_dtype,
             prefill_reuse=round(reuse, 4),
             cost_ms=winner.sim_decode_ms, p99_ms=winner.sim_p99_ms,
             tokens_per_s=round(winner.sim_tokens_per_s, 2),
             mem_mib=round(winner.sim_memory / 2 ** 20, 1),
             feasible=winner.feasible, search_wall_s=round(wall, 4),
             **sim.cache_stats())
    slog.close()
    return plan
