"""First-class KV-cache / decode-state pytrees for the serving engine.

The reference snapshot's only inference artifact is an incomplete Triton
prototype (triton/README.md); its training-side ``CacheOp`` (src/ops/
cache.cc) threads one cached tensor per op through the step. This module
generalizes that pattern into the serving engine's decode state (ISSUE 6):

* ``ServingState`` — the per-forward context ops see (``OpContext.serving``):
  mode ("prefill" | "decode"), the static ring-buffer capacity, per-slot
  write positions, and the cache_in/cache_out dicts keyed by op name.
  Stateful ops (causal ``MultiHeadAttentionOp``, ``LSTMOp``) read and
  extend it; everything else is oblivious.

* ``DecodeState`` — the jit-carried pytree between decode steps: one cache
  entry per stateful node plus the per-slot ``lengths`` cursor. Registered
  as a pytree node so it flows through ``jax.jit`` donation like any other
  train-state argument.

Static shapes are the design rule (no per-token recompiles): the KV cache
is a ring buffer of capacity ``max_len`` per slot — prefill writes the
prompt at position 0, each decode step writes ONE token at
``lengths[slot]`` via a per-slot dynamic_update_slice, and attention masks
key positions ``> position``. Pad garbage beyond a prompt's true length is
never read: the write cursor overwrites it before the mask ever exposes it.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np


@dataclasses.dataclass
class ServingState:
    """Per-forward serving context threaded as ``OpContext.serving``.

    mode:      "prefill" (whole padded prompt) or "decode" (one token/slot)
    max_len:   ring-buffer capacity — the static sequence axis of every
               cache entry (``--max-decode-len``)
    positions: (batch,) int32 — the first position this call writes
               (zeros for prefill; ``DecodeState.lengths`` for decode)
    lengths:   (batch,) int32 true prompt lengths (prefill only — the LSTM
               carry must be read at position length-1, not at the padded
               tail; attention needs no lengths, its causal mask + the
               decode-side position mask cover padding)
    cache_in:  {node_name: state pytree} consumed by decode
    cache_out: {node_name: state pytree} every stateful op fills
    exact:     decode-numerics mode: True routes the attention score
               through a full-extent GEMM (the new token's q padded to
               max_len rows) so decode logits are BITWISE-identical to the
               whole-sequence forward — XLA lowers a 1-row score product
               as a matvec whose d-axis accumulation order differs from
               the GEMM's by ~1 ulp otherwise. Default False (the fast
               matvec); the equivalence tests and audits flip it on.
    """

    mode: str
    max_len: int
    positions: Any
    lengths: Any = None
    cache_in: Optional[Dict[str, Any]] = None
    cache_out: Dict[str, Any] = dataclasses.field(default_factory=dict)
    exact: bool = False


@dataclasses.dataclass
class DecodeState:
    """The decode loop's carried state: {node_name: cache pytree} plus the
    per-slot length cursor. A pytree node — ``jax.jit`` donates and returns
    it whole, so the ring buffers update in place on device (the decode
    loop never copies the cache host-side)."""

    caches: Dict[str, Any]
    lengths: Any  # (n_slots,) int32

    @property
    def n_slots(self) -> int:
        return int(self.lengths.shape[0])


def _decode_state_flatten(s: "DecodeState"):
    names = tuple(sorted(s.caches))
    return ([s.caches[k] for k in names] + [s.lengths]), names


def _decode_state_unflatten(names, children):
    return DecodeState(caches=dict(zip(names, children[:-1])),
                       lengths=children[-1])


def _register_pytree() -> None:
    import jax

    jax.tree_util.register_pytree_node(
        DecodeState, _decode_state_flatten, _decode_state_unflatten)


_register_pytree()


# ---------------------------------------------------------------- helpers
def is_position_constant(value) -> bool:
    """Detect the position-id constant pattern the autoregressive builders
    bake in (models/gpt2.py: ``broadcast(arange(seq_len), (b, s))``): an
    integer 2-D constant whose every row is ``arange(seq)``. Serving must
    regenerate it per phase — prefill gets ``arange(bucket_len)``, decode
    gets each slot's current position — because the baked value is shaped
    for the training batch/sequence."""
    v = np.asarray(value)
    if v.ndim != 2 or not np.issubdtype(v.dtype, np.integer):
        return False
    if v.shape[1] < 1:
        return False
    return bool(np.all(v == np.arange(v.shape[1], dtype=v.dtype)[None, :]))


def update_slot_entry(cache_entry, prefill_entry, slot):
    """Insert one prefilled request's cache rows (leading dim 1) into the
    decode batch's entry (leading dim n_slots) at ``slot`` — a traced
    index, so slot choice never recompiles."""
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    def ins(dst, src):
        start = (slot,) + (0,) * (dst.ndim - 1)
        return lax.dynamic_update_slice(dst, src.astype(dst.dtype),
                                        tuple(jnp.asarray(s) for s in start))

    return jax.tree.map(ins, cache_entry, prefill_entry)


def write_token_kv(buf, new, positions):
    """Scatter one token's k or v (b, h, 1, hd) into the ring buffer
    (b, h, max_len, hd) at per-slot ``positions`` — vmapped
    dynamic_update_slice, exact (no arithmetic on the stored values)."""
    import jax
    import jax.lax as lax

    def one(dst, src, p):  # (h, L, hd), (h, 1, hd), scalar
        return lax.dynamic_update_slice(dst, src, (0, p, 0))

    return jax.vmap(one)(buf, new, positions)
