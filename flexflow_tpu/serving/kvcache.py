"""First-class KV-cache / decode-state pytrees for the serving engine.

The reference snapshot's only inference artifact is an incomplete Triton
prototype (triton/README.md); its training-side ``CacheOp`` (src/ops/
cache.cc) threads one cached tensor per op through the step. This module
generalizes that pattern into the serving engine's decode state (ISSUE 6):

* ``ServingState`` — the per-forward context ops see (``OpContext.serving``):
  mode ("prefill" | "decode"), the static ring-buffer capacity, per-slot
  write positions, and the cache_in/cache_out dicts keyed by op name.
  Stateful ops (causal ``MultiHeadAttentionOp``, ``LSTMOp``) read and
  extend it; everything else is oblivious.

* ``DecodeState`` — the jit-carried pytree between decode steps: one cache
  entry per stateful node plus the per-slot ``lengths`` cursor. Registered
  as a pytree node so it flows through ``jax.jit`` donation like any other
  train-state argument.

Static shapes are the design rule (no per-token recompiles): the KV cache
is a ring buffer of capacity ``max_len`` per slot — prefill writes the
prompt at position 0, each decode step writes ONE token at
``lengths[slot]`` via a per-slot dynamic_update_slice, and attention masks
key positions ``> position``. Pad garbage beyond a prompt's true length is
never read: the write cursor overwrites it before the mask ever exposes it.

Paged layout (ISSUE 12, vLLM-style PagedAttention adapted to JAX/TPU):
the per-slot ``max_len`` ring buffers become ONE pool of fixed-size KV
blocks per stateful node — ``(n_blocks, heads, block_size, head_dim)`` —
plus a per-slot **block table** ``(n_slots, max_blocks_per_slot)`` int32
mapping each slot's logical positions onto pool blocks. Slot recycling
and prefix sharing are pointer bookkeeping in the host-side
:class:`~flexflow_tpu.serving.scheduler.BlockAllocator` (prefix sharing
delivered by ISSUE 14's radix-tree cache, serving/prefix.py: shared
blocks are refcounted, divergent writes clone first — copy-on-write);
pool occupancy
decouples from ``max_len`` (a short request holds few blocks); and the
single-compile decode contract survives — block tables are just another
int32 array in the jitted signature. Block index 0 is the reserved
GARBAGE block: every unused table entry points at it, free slots write
their (discarded) tokens into it, and the attention mask guarantees it
is never read — so its contents only ever need to stay FINITE (``0 *
garbage`` must be exactly ``0.0`` for the paged/ring bitwise-equality
contract; the chaos poisoner deliberately never NaNs it).

Quantized layout (``kv_dtype="int8"``): pool blocks store symmetric
per-(token, head) int8 rows with float32 scales in block-paged scale
arrays ``(n_blocks, heads, block_size)`` — scale = amax/127 over the
head_dim row, written once with the row and folded back on read. The
exact-decode bitwise contract applies to fp layouts only; int8 is judged
against a pinned tolerance band (tests/test_decode_paged.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

#: reserved pool block every unused block-table entry points at — written
#: by free slots, never read (masked), must stay finite
GARBAGE_BLOCK = 0

#: supported KV-cache storage dtypes (the searched serving axis)
KV_DTYPES = ("native", "int8")

INT8_QMAX = 127.0


class SeqShardsError(ValueError):
    """A configuration asked for sequence-parallel decode
    (``--seq-shards`` > 1) in a mode that cannot honor it — the ring KV
    layout (no block tables to partition) or speculative decoding (the
    greedy verify contract assumes the single-shard score path). Raised
    loudly at plan/engine construction instead of decoding garbage."""


@dataclasses.dataclass
class ServingState:
    """Per-forward serving context threaded as ``OpContext.serving``.

    mode:      "prefill" (whole padded prompt), "decode" (one token/slot)
               or "chunk" (ISSUE 14: one fixed-width prefill chunk for a
               SINGLE slot — batch 1 — writing its k/v rows into the
               slot's pool blocks and attending over the slot's gathered
               extent; the chunked-prefill and prefix-suffix program)
    max_len:   ring-buffer capacity — the static sequence axis of every
               cache entry (``--max-decode-len``)
    positions: (batch,) int32 — the first position this call writes
               (zeros for prefill; ``DecodeState.lengths`` for decode;
               the chunk's start position for chunk mode)
    lengths:   (batch,) int32 true prompt lengths (prefill only — the LSTM
               carry must be read at position length-1, not at the padded
               tail; attention needs no lengths, its causal mask + the
               decode-side position mask cover padding). Chunk mode reuses
               it for the chunk's REAL token count (rows beyond are pad).
    cache_in:  {node_name: state pytree} consumed by decode
    cache_out: {node_name: state pytree} every stateful op fills
    exact:     decode-numerics mode: True routes the attention score
               through a full-extent GEMM (the new token's q padded to
               max_len rows) so decode logits are BITWISE-identical to the
               whole-sequence forward — XLA lowers a 1-row score product
               as a matvec whose d-axis accumulation order differs from
               the GEMM's by ~1 ulp otherwise. Default False (the fast
               matvec); the equivalence tests and audits flip it on.
    block_tables: (n_slots, max_blocks_per_slot) int32 — the paged-KV
               block tables (None selects the legacy ring layout; the
               branch is static at trace time, so ring and paged decode
               are distinct compiles, each recompile-free)
    block_size: tokens per KV block (paged layout only)
    kv_dtype:  "native" (store k/v at the model dtype) or "int8"
               (symmetric per-(token, head) quantization with f32 scales)
    seq_shards: sequence-parallel decode width (ISSUE 18) — the gathered
               KV extent is partitioned into this many contiguous key
               segments, each scored independently (on a mesh: one chip
               per shard owning that run of pool blocks; on one device:
               an emulated compute-path decomposition of the same
               arrays) and merged by the flash segment combine. 1 is
               the unsharded reference path. Paged decode only; chunk
               prefill writes are layout-identical at any width.
    """

    mode: str
    max_len: int
    positions: Any
    lengths: Any = None
    cache_in: Optional[Dict[str, Any]] = None
    cache_out: Dict[str, Any] = dataclasses.field(default_factory=dict)
    exact: bool = False
    block_tables: Any = None
    block_size: int = 0
    kv_dtype: str = "native"
    seq_shards: int = 1

    @property
    def paged(self) -> bool:
        return self.block_tables is not None


@dataclasses.dataclass
class DecodeState:
    """The decode loop's carried state: {node_name: cache pytree} plus the
    per-slot length cursor. A pytree node — ``jax.jit`` donates and returns
    it whole, so the ring buffers (or the paged pool) update in place on
    device (the decode loop never copies the cache host-side).

    ``block_tables`` is None for the ring layout; for the paged layout it
    is the (n_slots, max_blocks_per_slot) int32 table mapping each slot's
    positions onto pool blocks — it only changes at admission (the slot
    writer sets the row), so decode steps carry it through untouched."""

    caches: Dict[str, Any]
    lengths: Any  # (n_slots,) int32
    block_tables: Any = None  # (n_slots, max_blocks_per_slot) int32 | None

    @property
    def n_slots(self) -> int:
        return int(self.lengths.shape[0])

    @property
    def paged(self) -> bool:
        return self.block_tables is not None


def _decode_state_flatten(s: "DecodeState"):
    names = tuple(sorted(s.caches))
    return ([s.caches[k] for k in names]
            + [s.lengths, s.block_tables]), names


def _decode_state_unflatten(names, children):
    return DecodeState(caches=dict(zip(names, children[:-2])),
                       lengths=children[-2], block_tables=children[-1])


def _register_pytree() -> None:
    import jax

    jax.tree_util.register_pytree_node(
        DecodeState, _decode_state_flatten, _decode_state_unflatten)


_register_pytree()


# ---------------------------------------------------------------- helpers
def parse_context_buckets(spec) -> Tuple[int, ...]:
    """Normalize a ``--context-buckets`` spec — the comma-separated flag
    string ("1024,4096,16384") or an already-parsed int sequence — into
    a validated ascending tuple of context lengths. Each bucket is the
    max context a request routed to it may hold; ``serving_search``
    picks seq_shards per bucket and admission routes a request to the
    smallest bucket covering its context. Empty spec → no bucketing."""
    if not spec:
        return ()
    if isinstance(spec, str):
        vals = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                vals.append(int(part))
            except ValueError:
                raise ValueError(
                    f"--context-buckets: {part!r} is not an integer "
                    "(expected a comma-separated list like "
                    "'1024,4096,16384')")
    else:
        vals = [int(v) for v in spec]
    if any(v < 1 for v in vals):
        raise ValueError(
            f"--context-buckets entries must be >= 1, got {vals}")
    if vals != sorted(set(vals)):
        raise ValueError(
            "--context-buckets must be strictly ascending context "
            f"lengths, got {vals}")
    return tuple(vals)


def is_position_constant(value) -> bool:
    """Detect the position-id constant pattern the autoregressive builders
    bake in (models/gpt2.py: ``broadcast(arange(seq_len), (b, s))``): an
    integer 2-D constant whose every row is ``arange(seq)``. Serving must
    regenerate it per phase — prefill gets ``arange(bucket_len)``, decode
    gets each slot's current position — because the baked value is shaped
    for the training batch/sequence."""
    v = np.asarray(value)
    if v.ndim != 2 or not np.issubdtype(v.dtype, np.integer):
        return False
    if v.shape[1] < 1:
        return False
    return bool(np.all(v == np.arange(v.shape[1], dtype=v.dtype)[None, :]))


def update_slot_entry(cache_entry, prefill_entry, slot):
    """Insert one prefilled request's cache rows (leading dim 1) into the
    decode batch's entry (leading dim n_slots) at ``slot`` — a traced
    index, so slot choice never recompiles."""
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    def ins(dst, src):
        start = (slot,) + (0,) * (dst.ndim - 1)
        return lax.dynamic_update_slice(dst, src.astype(dst.dtype),
                                        tuple(jnp.asarray(s) for s in start))

    return jax.tree.map(ins, cache_entry, prefill_entry)


def write_token_kv(buf, new, positions):
    """Scatter one token's k or v (b, h, 1, hd) into the ring buffer
    (b, h, max_len, hd) at per-slot ``positions`` — vmapped
    dynamic_update_slice, exact (no arithmetic on the stored values)."""
    import jax
    import jax.lax as lax

    def one(dst, src, p):  # (h, L, hd), (h, 1, hd), scalar
        return lax.dynamic_update_slice(dst, src, (0, p, 0))

    return jax.vmap(one)(buf, new, positions)


# ----------------------------------------------------------- paged layout
def blocks_per_slot(max_len: int, block_size: int) -> int:
    """Block-table width: blocks covering ``max_len`` tokens."""
    return -(-int(max_len) // int(block_size))


def kv_token_bytes(heads: int, kdim: int, vdim: int, el: int,
                   kv_dtype: str = "native") -> int:
    """KV bytes ONE token costs across one attention node's heads — THE
    shared pricing formula behind the engine's measured
    ``kv_bytes_read`` accounting AND the serving search's explicit
    KV-stream term (``_attention_state_bytes``): int8 stores 1-byte
    rows plus the two f32 per-(token, head) scales; native stores the
    model dtype. One implementation, two consumers — the bench's
    measured fill ratio is fed back into ``serving_search(kv_fill=)``,
    so the two sides must never price from drifting copies."""
    if kv_dtype == "int8":
        return heads * ((kdim + vdim) * 1 + 8)
    return heads * (kdim + vdim) * el


def quantize_kv(x) -> Tuple[Any, Any]:
    """Symmetric per-(..., token, head)-row int8 quantization over the
    trailing head_dim axis: ``q = round(x / scale)`` with
    ``scale = amax(|x|) / 127`` (scale 1 for all-zero rows — dequant of a
    zero row stays exactly zero). Returns ``(q int8, scale f32)`` with
    ``scale`` shaped like ``x`` minus its last axis."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scale[..., None]),
                 -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype):
    """Fold the per-row scale back: ``q * scale`` in f32, cast to the
    compute dtype — the read half of :func:`quantize_kv`."""
    import jax.numpy as jnp

    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def write_token_kv_paged(pool, new, positions, block_tables, block_size):
    """Scatter one token's k or v (n_slots, h, 1, hd) into the block pool
    (n_blocks, h, block_size, hd) at each slot's current position: block
    ``tables[slot, pos // bs]``, offset ``pos % bs``. Free slots (their
    table rows all GARBAGE_BLOCK, position 0) collide harmlessly in the
    garbage block — it is never read. No arithmetic on stored values."""
    import jax.numpy as jnp

    bi = jnp.take_along_axis(
        block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
    off = positions % block_size
    return pool.at[bi, :, off].set(new[:, :, 0, :].astype(pool.dtype))


def write_token_scale_paged(scales, scale_new, positions, block_tables,
                            block_size):
    """Scale-array twin of :func:`write_token_kv_paged`:
    ``scales (n_blocks, h, block_size)``, ``scale_new (n_slots, h, 1)``."""
    import jax.numpy as jnp

    bi = jnp.take_along_axis(
        block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
    off = positions % block_size
    return scales.at[bi, :, off].set(scale_new[:, :, 0])


def write_chunk_kv_paged(pool, new, positions, valid, table_row,
                         block_size):
    """Scatter one prefill CHUNK's k or v rows ``(1, h, C, hd)`` into
    the block pool at ``positions`` (C,) of the single slot owning
    ``table_row`` (mb,) — the chunked-prefill / prefix-suffix write
    (ISSUE 14). Invalid (pad) rows beyond the chunk's real token count
    are routed to the GARBAGE block (finite garbage, never read); valid
    rows land at (table[pos // bs], pos % bs) like the decode-step
    write. No arithmetic on stored values."""
    import jax.numpy as jnp

    mb = table_row.shape[0]
    blk = jnp.clip(positions // block_size, 0, mb - 1)
    bi = jnp.where(valid, table_row[blk], GARBAGE_BLOCK)
    off = positions % block_size
    rows = jnp.swapaxes(new[0], 0, 1)  # (h, C, hd) -> (C, h, hd)
    return pool.at[bi, :, off].set(rows.astype(pool.dtype))


def write_chunk_scale_paged(scales, scale_new, positions, valid,
                            table_row, block_size):
    """Scale-array twin of :func:`write_chunk_kv_paged`:
    ``scales (n_blocks, h, bs)``, ``scale_new (1, h, C)``."""
    import jax.numpy as jnp

    mb = table_row.shape[0]
    blk = jnp.clip(positions // block_size, 0, mb - 1)
    bi = jnp.where(valid, table_row[blk], GARBAGE_BLOCK)
    off = positions % block_size
    return scales.at[bi, :, off].set(jnp.swapaxes(scale_new[0], 0, 1))


def gather_paged_kv(pool, block_tables):
    """Materialize each slot's logical KV extent from the pool:
    ``(n_blocks, h, bs, hd)`` gathered through ``(n_slots, mb)`` tables →
    ``(n_slots, h, mb * bs, hd)`` in position order. This is the
    CPU/exact fallback read (O(mb * bs) rows like the ring layout — the
    Pallas flash-decode kernel is the O(true_length) path); a pure
    gather, so the materialized rows are bitwise the stored rows."""
    import jax.numpy as jnp

    g = pool[block_tables]                 # (S, mb, h, bs, hd)
    g = jnp.swapaxes(g, 1, 2)              # (S, h, mb, bs, hd)
    return g.reshape(g.shape[0], g.shape[1], -1, g.shape[-1])


def gather_paged_scales(scales, block_tables):
    """(n_blocks, h, bs) through (n_slots, mb) → (n_slots, h, mb * bs)."""
    import jax.numpy as jnp

    g = scales[block_tables]               # (S, mb, h, bs)
    g = jnp.swapaxes(g, 1, 2)              # (S, h, mb, bs)
    return g.reshape(g.shape[0], g.shape[1], -1)


def paged_pool_entry(ring_leaf, n_blocks: int, block_size: int,
                     kv_dtype: str):
    """Zeros-initialized pool (+ scales for int8) for one KV leaf whose
    per-request ring shape is ``(1, h, max_len, hd)``. Returns the pool
    array for "native", ``(pool int8, scales f32)`` for "int8"."""
    import jax.numpy as jnp

    _, h, _L, hd = ring_leaf.shape
    if kv_dtype == "int8":
        return (jnp.zeros((n_blocks, h, block_size, hd), jnp.int8),
                jnp.zeros((n_blocks, h, block_size), jnp.float32))
    return jnp.zeros((n_blocks, h, block_size, hd), ring_leaf.dtype)


def scatter_prefill_paged(pool, ring_leaf, table_row, block_size: int,
                          scales=None):
    """Insert one prefilled request's ring cache ``(1, h, max_len, hd)``
    into its table row's pool blocks: the ring is padded to whole blocks,
    reshaped block-major and scattered at ``table_row`` (mb,) int32.
    Unused table entries point at GARBAGE_BLOCK and receive the ring's
    zero pad — harmless, never read. For int8 pools the rows are
    quantized here (``scales`` must be the matching scale array); fp
    pools store the rows bit-unchanged."""
    import jax.numpy as jnp

    x = ring_leaf[0]                       # (h, L, hd)
    h, L, hd = x.shape
    mb = int(table_row.shape[0])
    pad = mb * block_size - L
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    if scales is not None:
        q, s = quantize_kv(x)              # (h, P, hd), (h, P)
        qb = q.reshape(h, mb, block_size, hd).transpose(1, 0, 2, 3)
        sb = s.reshape(h, mb, block_size).transpose(1, 0, 2)
        return (pool.at[table_row].set(qb),
                scales.at[table_row].set(sb))
    xb = x.reshape(h, mb, block_size, hd).transpose(1, 0, 2, 3)
    return pool.at[table_row].set(xb.astype(pool.dtype)), None
