"""ServingEngine: the inference engine over a compiled FFModel.

This graduates ``model.predict``'s per-batch forward loop into a real
serving path (ISSUE 6; the reference snapshot's only inference artifact is
an *incomplete* Triton prototype, triton/README.md): prefill/decode split
with a first-class KV-cache pytree (serving/kvcache.py), Orca-style
continuous batching over a fixed slot pool (serving/scheduler.py), greedy
and temperature/top-k sampling (the Pallas top-k kernel where eligible),
and obs wiring (prefill/decode/schedule tracer events + the StepTelemetry
``serving`` block).

Static shapes everywhere: ONE decode compile serves every request mix
(asserted via the jit cache size — ``decode_compiles``), and prefill
compiles once per length bucket. The decode-state layout on a real mesh is
a *searched* axis: ``serving.search.serving_search`` prices replica- vs
tensor-parallel decode (KV sharded over heads) with the simulator's memory
accounting, and ``elastic_replan`` re-runs that search mid-serve when the
device pool changes — the in-flight DecodeState survives the hop, so
generation continues bit-identically (PR 4/5 carry-over: re-search and
keep serving).
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence

import numpy as np

from ..ffconst import OperatorType
from .kvcache import DecodeState, update_slot_entry
from .scheduler import (ContinuousBatchScheduler, Request, ServingRejection,
                        bucket_for, default_buckets)

def position_context_bound(executor, max_len: int) -> int:
    """The max supported context of a compiled autoregressive model:
    ``max_len`` bounded by the position-embedding table wherever one
    exists — positions beyond the table would CLAMP under jit
    (``jnp.take``) and silently reuse the last row's embedding. ONE
    implementation for every consumer (the serving engine's admission
    rejection AND the speculative decoder's scoring bound — ISSUE 12
    removed the old warn-and-clamp precisely so nothing aliases rows)."""
    bound = int(max_len)
    pos_guids = set(executor._position_const_guids())
    for node in executor.pcg.compute_nodes():
        if node.op.op_type == OperatorType.OP_EMBEDDING and any(
                g in pos_guids for g, _ in node.inputs):
            entries = int(node.op.attrs.get("num_entries", 0))
            if entries:
                bound = min(bound, entries)
    return bound


# per-token latency reservoir bound (ISSUE 9 satellite): the old unbounded
# list grew one float per token for the life of the serve loop — a
# traffic-serving process leaks. p50/p99 are computed over a sliding
# window of the most recent TOKEN_WALL_WINDOW walls instead (plenty for a
# stable tail estimate; the summary fields are unchanged).
TOKEN_WALL_WINDOW = 8192


@dataclasses.dataclass
class ServingStats:
    """Host-side counters of one serve() run — the bench serving_leg and
    the StepTelemetry ``serving`` block read these."""

    requests_served: int = 0
    tokens_generated: int = 0
    prefills: int = 0
    decode_steps: int = 0
    queue_depth_hwm: int = 0
    wall_s: float = 0.0
    # per-token latency distribution: decode tokens carry their step wall,
    # first tokens their prefill wall. Bounded ring (TOKEN_WALL_WINDOW):
    # percentiles describe the trailing window, not the whole run
    token_walls_s: Deque[float] = dataclasses.field(
        default_factory=lambda: deque(maxlen=TOKEN_WALL_WINDOW))
    # resilience ledger (ISSUE 9): every request leaves the system under
    # exactly one outcome (ok | deadline_exceeded | shed | decode_fault |
    # preempted); the counters mirror serving/resilience.py's events
    outcomes: Dict[str, int] = dataclasses.field(default_factory=dict)
    sheds: int = 0
    deadline_misses: int = 0
    quarantines: int = 0
    decode_retries: int = 0
    drains: int = 0
    replans: int = 0
    drained_returned: int = 0
    # decode HBM traffic accounting (ISSUE 12): analytic KV bytes the
    # decode attention reads, accumulated per step host-side — paged
    # engines charge each live slot's OCCUPIED blocks, ring engines the
    # full n_slots * max_len extent (the O(max_len) bill the paged
    # refactor removes); bench's bytes-read/token column
    kv_bytes_read: int = 0
    # prefix cache + chunked prefill ledger (ISSUE 14,
    # serving/prefix.py): admissions that mapped a cached prefix, the
    # prompt tokens whose prefill compute was skipped vs actually
    # computed, trie evictions this run, and chunk-prefill dispatches —
    # the StepTelemetry ``serving_prefix`` block and the bench
    # shared-prompt sub-leg read these
    prefix_hits: int = 0
    prefix_tokens_reused: int = 0
    prefill_tokens_computed: int = 0
    cache_evictions: int = 0
    chunked_prefills: int = 0
    # speculative decoding (serving/speculative.py): per-round drafter
    # proposal/acceptance ledger; acceptance_rate feeds the bench column
    # and keeps the EWMA admission cost model honest
    spec_rounds: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    # host-overhead accounting (ISSUE 16, ROADMAP item 5): each tick's
    # wall splits into dispatch (tick start -> device call issued: action
    # selection, admission, chaos hooks), device (the blocking
    # prefill/chunk/decode call + result fetch), and bookkeeping (commit
    # loop, stats, trie inserts). host_overhead_fraction() is THE
    # measured baseline the async host runtime must beat — always-on
    # plain-float accumulation, it never touches the token streams
    host_dispatch_s: float = 0.0
    host_device_s: float = 0.0
    host_bookkeep_s: float = 0.0
    host_ticks: int = 0
    # async double-buffered runtime (ISSUE 17): host work performed
    # WHILE a device step was already in flight — off the critical path,
    # so it joins the denominator but never the numerator of
    # host_overhead_fraction (the sync loop leaves it 0, preserving the
    # PR 16 accounting identity). host_syncs counts BLOCKING host
    # transfers through the one decode fetch choke point — the async
    # steady-state contract is <= 1 per committed decode step
    host_overlap_s: float = 0.0
    host_syncs: int = 0
    # sequence-parallel decode (ISSUE 18): mean per-step occupied KV
    # bytes ONE shard chip holds — pool bytes at measured fill divided
    # by seq_shards. This is the recorded number behind the "KV provably
    # exceeds one chip" criterion: the bench asserts the undivided total
    # is above a real chip's HBM budget while this per-chip figure is
    # below it. Set at serve-loop finish; 0 until a decode step ran.
    kv_hbm_per_chip_bytes: int = 0

    def record_token(self, wall_s: float) -> None:
        self.token_walls_s.append(wall_s)

    def kv_bytes_per_token(self) -> Optional[float]:
        if not self.tokens_generated or not self.kv_bytes_read:
            return None
        return self.kv_bytes_read / self.tokens_generated

    def acceptance_rate(self) -> Optional[float]:
        if not self.spec_proposed:
            return None
        return self.spec_accepted / self.spec_proposed

    def prefix_reuse_rate(self) -> Optional[float]:
        """Fraction of prefill tokens served from the prefix cache —
        the measured hit rate ``serving_search(prefill_reuse=)`` prices
        with. None before any prefill ran."""
        total = self.prefix_tokens_reused + self.prefill_tokens_computed
        if not total:
            return None
        return self.prefix_tokens_reused / total

    def count_outcome(self, outcome: str, n: int = 1) -> None:
        if n:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + int(n)

    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.wall_s if self.wall_s > 0 else 0.0

    def batch_occupancy(self, n_slots: int) -> float:
        """Fraction of decode-slot-steps that produced a kept token — the
        continuous-batching utilization headline (1.0 = every slot busy
        every step). First tokens come from prefill, not a decode slot,
        so they stay out of the numerator."""
        denom = self.decode_steps * n_slots
        return max(self.tokens_generated - self.prefills, 0) / denom \
            if denom else 0.0

    def host_overhead_fraction(self) -> Optional[float]:
        """Fraction of the serve loop's tick wall spent on the host
        (dispatch + bookkeeping) rather than waiting on the device —
        ROADMAP item 5's headline number. None before any tick ran.
        Overlapped host work (ISSUE 17: bookkeeping performed while the
        next step was already in flight) extends the wall the loop
        covered without costing the device anything, so it counts in
        the denominator only."""
        total = self.host_dispatch_s + self.host_device_s + \
            self.host_bookkeep_s + self.host_overlap_s
        if total <= 0.0:
            return None
        return (self.host_dispatch_s + self.host_bookkeep_s) / total

    def p50_token_ms(self) -> Optional[float]:
        if not self.token_walls_s:
            return None
        return float(np.percentile(list(self.token_walls_s), 50) * 1e3)

    def p99_token_ms(self) -> Optional[float]:
        if not self.token_walls_s:
            return None
        return float(np.percentile(list(self.token_walls_s), 99) * 1e3)

    def summary(self) -> Dict[str, Any]:
        out = {
            "requests_served": self.requests_served,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "decode_steps": self.decode_steps,
            "queue_depth_hwm": self.queue_depth_hwm,
            "wall_s": round(self.wall_s, 4),
            "tokens_per_s": round(self.tokens_per_s(), 2),
        }
        p50, p99 = self.p50_token_ms(), self.p99_token_ms()
        if p50 is not None:
            out["p50_token_ms"] = round(p50, 3)
            out["p99_token_ms"] = round(p99, 3)
        if self.outcomes:
            out["outcomes"] = dict(self.outcomes)
        for k in ("sheds", "deadline_misses", "quarantines",
                  "decode_retries", "drains", "replans",
                  "drained_returned", "spec_rounds"):
            v = getattr(self, k)
            if v:
                out[k] = v
        kvpt = self.kv_bytes_per_token()
        if kvpt is not None:
            out["kv_bytes_per_token"] = round(kvpt, 1)
        acc = self.acceptance_rate()
        if acc is not None:
            out["spec_acceptance"] = round(acc, 4)
        for k in ("prefix_hits", "prefix_tokens_reused",
                  "prefill_tokens_computed", "cache_evictions",
                  "chunked_prefills"):
            v = getattr(self, k)
            if v:
                out[k] = v
        reuse = self.prefix_reuse_rate()
        if reuse:
            out["prefix_reuse_rate"] = round(reuse, 4)
        hof = self.host_overhead_fraction()
        if hof is not None:
            out["host_overhead_fraction"] = round(hof, 4)
        if self.host_syncs:
            out["host_syncs"] = self.host_syncs
        if self.kv_hbm_per_chip_bytes:
            out["kv_hbm_per_chip_bytes"] = self.kv_hbm_per_chip_bytes
        return out


class ServingEngine:
    """Inference engine over a compiled autoregressive FFModel.

    Requirements on the graph (validated at construction): causal
    self-attention (``multihead_attention(..., causal=True)``) and/or LSTM
    recurrence as the only sequence-stateful ops, a per-token final output
    ``(batch, seq, vocab)``, and — for :meth:`generate` — a single integer
    token input. models/gpt2.py and models/transformer.py's
    ``build_transformer_decoder`` qualify; bidirectional encoders do not
    (incremental decode is undefined for them, and the engine says so).
    """

    def __init__(self, model, n_slots: Optional[int] = None,
                 max_decode_len: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue: int = 64,
                 eos_id: Optional[int] = None,
                 exact_decode: bool = False,
                 kv_cache: Optional[str] = None,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 prefix_cache: Optional[str] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 prefix_cache_blocks: Optional[int] = None,
                 serve_loop: Optional[str] = None,
                 seq_shards: Optional[int] = None,
                 context_buckets: Optional[Sequence[int]] = None):
        assert model.executor is not None, "call model.compile() first"
        self.model = model
        self.executor = model.executor
        cfg = model.config
        self.n_slots = int(n_slots or getattr(cfg, "max_inflight", 8))
        self.max_decode_len = int(max_decode_len or
                                  getattr(cfg, "max_decode_len", 128))
        # the caller-requested value, so FFModel.generate's engine-cache
        # check can compare against what the caller ASKED for
        self.requested_max_decode_len = self.max_decode_len
        self.max_queue = max_queue
        self.eos_id = eos_id
        # bitwise-vs-full-forward decode numerics (ServingState.exact) —
        # the verification mode; default is the fast matvec score path
        self.exact_decode = bool(exact_decode)
        # paged KV cache (ISSUE 12, docs/serving.md "Paged KV cache"):
        # "paged" (default) = block pool + per-slot tables, "ring" = the
        # legacy per-slot max_len buffers (the bitwise reference layout)
        # serve-loop runtime (ISSUE 17, docs/serving.md "Async
        # runtime"): "sync" (default) blocks on each decode step's host
        # transfer before dispatching the next; "async" double-buffers —
        # step k+1 is enqueued on-device while step k's (tokens, ok)
        # transfer is in flight, commits land at transfer ARRIVAL. Both
        # run the same device programs; async must match sync
        # stream-for-stream bitwise under exact decode (tier-1 pins it)
        self.serve_loop = str(serve_loop or
                              getattr(cfg, "serve_loop", "sync") or "sync")
        if self.serve_loop not in ("sync", "async"):
            raise ValueError(
                f"serve_loop must be 'sync' or 'async', got "
                f"{self.serve_loop!r}")
        self.kv_cache = str(kv_cache or getattr(cfg, "kv_cache", "paged"))
        self.kv_block_size = int(kv_block_size or
                                 getattr(cfg, "kv_block_size", 16))
        self.kv_dtype = str(kv_dtype or getattr(cfg, "kv_dtype", "native"))
        kv_pool_blocks = int(kv_pool_blocks if kv_pool_blocks is not None
                             else getattr(cfg, "kv_pool_blocks", 0))
        if self.kv_cache not in ("paged", "ring"):
            raise ValueError(
                f"kv_cache must be 'paged' or 'ring', got "
                f"{self.kv_cache!r}")
        from .kvcache import (KV_DTYPES, SeqShardsError, blocks_per_slot,
                              parse_context_buckets)

        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got "
                f"{self.kv_dtype!r}")
        if self.kv_cache == "ring" and self.kv_dtype != "native":
            raise ValueError(
                "kv_dtype='int8' requires the paged KV layout "
                "(kv_cache='paged')")
        # sequence-parallel decode (ISSUE 18, docs/decode_perf.md
        # "Sequence-parallel decode"): the gathered extent is scored as
        # seq_shards contiguous key segments merged by the flash segment
        # combine — a static trace-time choice that joins the decode jit
        # key. context_buckets routes admitted requests to the searched
        # per-bucket shard width (serving_search picks seq_shards per
        # bucket from the ICI closed forms).
        self.seq_shards = int(seq_shards if seq_shards is not None
                              else getattr(cfg, "seq_shards", 1) or 1)
        if self.seq_shards < 1:
            raise ValueError(
                f"seq_shards must be >= 1, got {self.seq_shards}")
        if self.seq_shards > 1 and self.kv_cache == "ring":
            raise SeqShardsError(
                "--seq-shards > 1 requires the paged KV layout "
                "(kv_cache='paged'): the ring layout has no block tables "
                "to partition into per-shard contiguous runs")
        self.context_buckets = parse_context_buckets(
            context_buckets if context_buckets is not None
            else getattr(cfg, "context_buckets", "") or "")
        if self.context_buckets and self.kv_cache == "ring":
            raise ValueError(
                "--context-buckets requires the paged KV layout "
                "(kv_cache='paged'): buckets route requests to "
                "sequence-sharded block-table partitions")
        # prefix cache + chunked prefill (ISSUE 14, serving/prefix.py,
        # docs/serving.md "Prefix cache & chunked prefill"): the radix
        # trie defaults ON for paged attention-only graphs — its hit
        # path is bitwise the cold path, so enabling it changes no
        # stream; chunking is opt-in via --prefill-chunk-tokens
        self.prefill_chunk_tokens = int(
            prefill_chunk_tokens if prefill_chunk_tokens is not None
            else getattr(cfg, "prefill_chunk_tokens", 0) or 0)
        prefix_mode = str(prefix_cache or
                          getattr(cfg, "prefix_cache", "on") or "on")
        if prefix_mode not in ("on", "off"):
            raise ValueError(
                f"prefix_cache must be 'on' or 'off', got {prefix_mode!r}")
        if self.kv_cache == "ring":
            if prefix_cache == "on":
                raise ValueError(
                    "prefix_cache='on' requires the paged KV layout "
                    "(kv_cache='paged'): the ring layout has no shared "
                    "block pool to map a cached prefix into")
            if self.prefill_chunk_tokens:
                raise ValueError(
                    "prefill_chunk_tokens requires the paged KV layout "
                    "(kv_cache='paged'): chunks write into the block "
                    "pool")
            prefix_mode = "off"
        # max supported context: bounded by the position-embedding table
        # when it is shorter than the ring/pool capacity; admission
        # REJECTS beyond it (the old warn-and-clamp is gone, ISSUE 12
        # satellite)
        self._validate_graph()
        has_lstm = any(
            n.op.op_type == OperatorType.OP_LSTM
            for n in self.executor.pcg.compute_nodes())
        if has_lstm:
            # the LSTM carry is a summary, not per-token pool rows:
            # there is no block to share or chunk (ISSUE 14 scope —
            # attention-only stateful graphs)
            if self.prefill_chunk_tokens:
                raise ValueError(
                    "prefill_chunk_tokens: chunked prefill supports "
                    "attention-only stateful graphs; this model has "
                    "LSTM recurrence")
            if prefix_cache == "on":
                raise ValueError(
                    "prefix_cache='on': prefix caching supports "
                    "attention-only stateful graphs; this model has "
                    "LSTM recurrence")
            prefix_mode = "off"
        self.max_context = position_context_bound(self.executor,
                                                  self.max_decode_len)
        self.block_allocator = None
        self._prefix = None
        if self.kv_cache == "paged":
            from .scheduler import BlockAllocator

            mb = blocks_per_slot(self.max_decode_len, self.kv_block_size)
            self.max_blocks_per_slot = mb
            # auto pool: full capacity (every slot at max_len) + the
            # garbage block — --kv-pool-blocks decouples occupancy from
            # max_len (admission then waits on FREE BLOCKS, not slots).
            # Chunked prefill adds one live chunk's worth of headroom
            # (the FF006 law: one max-context request PLUS one chunk)
            chunk_blocks = (-(-self.prefill_chunk_tokens //
                              self.kv_block_size)
                            if self.prefill_chunk_tokens else 0)
            self.kv_pool_blocks = kv_pool_blocks or (
                self.n_slots * mb + 1 + chunk_blocks)
            # ShardLint FF006 paged shape laws — statically, zero compile
            from ..analysis import (AnalysisReport, StaticAnalysisError,
                                    check_paged_kv)

            import jax

            diags = check_paged_kv(
                self.executor.pcg,
                block_size=self.kv_block_size,
                pool_blocks=self.kv_pool_blocks,
                max_blocks_per_slot=mb,
                max_context=self.max_context,
                prefill_chunk_tokens=self.prefill_chunk_tokens,
                seq_shards=self.seq_shards,
                n_devices=jax.device_count(),
                context_buckets=self.context_buckets)
            if diags:
                raise StaticAnalysisError(
                    AnalysisReport(diagnostics=diags, checked=("FF006",)),
                    context="paged KV configuration")
            self.block_allocator = BlockAllocator(self.kv_pool_blocks,
                                                  self.kv_block_size)
            if prefix_mode == "on":
                from .prefix import PrefixCache

                self._prefix = PrefixCache(
                    self.block_allocator, self.kv_block_size,
                    max_blocks=int(
                        prefix_cache_blocks
                        if prefix_cache_blocks is not None
                        else getattr(cfg, "prefix_cache_blocks", 0) or 0))
        self.buckets = tuple(buckets) if buckets else \
            default_buckets(self.max_decode_len)
        self.state: Optional[DecodeState] = None
        self._last_tokens = None  # (n_slots, 1) device int32
        self._write_slot_fn = None
        self._clear_slot_fn = None
        # filled by _ensure_state: which cache entries live in the block
        # pool (vs slot-major) — the one pagedness classification
        self._paged_entry_names: set = set()
        self._samplers: Dict = {}
        self.stats = ServingStats()
        self.plan = None  # ServingPlan from the last (re)search, if any
        self._search_sim = None  # warm Simulator for elastic re-search
        # resilience (ISSUE 9, serving/resilience.py): the admission
        # controller's EWMA cost model lives on the ENGINE so it warms
        # across serve() runs; resilience_clock (ms) overrides the time
        # base of every deadline/drain decision (deterministic tests);
        # drained_requests holds the queued requests a graceful SIGTERM
        # drain handed back for re-submission
        from .resilience import AdmissionController

        self.admission = AdmissionController()
        self.resilience_clock = None
        self.drained_requests: List[Request] = []
        self._last_guard = False
        # resilience state accumulated by pre-serve admit() calls (shed
        # counts, deadline arming) — consumed by the next serve() so the
        # ledger never loses events to a throwaway policy object
        self._pending_resilience = None

    # ------------------------------------------------------------ validation
    def _validate_graph(self) -> None:
        pcg = self.executor.pcg
        # ShardLint pre-serve pass (ISSUE 7): the FF005 serving-state
        # reachability rule promotes the fused-stateful runtime refusal
        # into a static diagnostic with a rule ID and fix hint. ONE
        # detection implementation either way — with --static-analysis
        # off the same checker still backstops the engine (it must never
        # decode history-free garbage), just phrased as the plain
        # runtime refusal without a rule ID.
        from ..analysis import check_serving_graph

        diags = check_serving_graph(pcg)
        if diags:
            if (getattr(self.model.config, "static_analysis", "on")
                    or "on") != "off":
                raise NotImplementedError(
                    "; ".join(d.format_line() for d in diags))
            d = diags[0]
            raise NotImplementedError(
                f"{d.node}: {d.message}; recompile without --fusion "
                "to serve")
        final = pcg.nodes[self.executor.final_guid]
        out = final.out_shapes[self.executor.final_out_idx]
        if len(out) != 3:
            raise ValueError(
                f"serving needs a per-token final output (batch, seq, "
                f"vocab); {final.name} produces {out} — pooled/classifier "
                "heads cannot be decoded token by token")
        for node in pcg.compute_nodes():
            ot = node.op.op_type
            if ot == OperatorType.OP_SDPA:
                raise NotImplementedError(
                    f"{node.name}: OP_SDPA graphs (torch frontend) have no "
                    "serving decode path yet; build with "
                    "multihead_attention(causal=True)")
            # fused regions hiding stateful/position sub-ops were already
            # refused above via analysis.check_serving_graph (FF005) —
            # the single implementation of that judgement
            if ot == OperatorType.OP_MULTIHEAD_ATTENTION:
                if not node.op.attrs.get("causal", False):
                    raise ValueError(
                        f"{node.name}: serving requires causal=True "
                        "attention (bidirectional attention cannot be "
                        "decoded incrementally)")
                if len({g for g, _ in node.inputs}) != 1:
                    raise ValueError(
                        f"{node.name}: serving decode supports "
                        "self-attention only (q, k, v from one producer)")
            # NOTE: the position-table context bound lives in
            # position_context_bound() — __init__ records it as
            # self.max_context and scheduler.submit rejects any request
            # whose prompt + max_new exceeds it (typed ServingRejection
            # naming the max supported context; ISSUE 12 satellite
            # replacing the old warn-and-clamp)

    def _token_input_check(self) -> None:
        ins = self.executor.pcg.input_nodes()
        from ..ffconst import DataType

        if len(ins) != 1 or ins[0].op.attrs.get("dtype") not in (
                DataType.DT_INT32, DataType.DT_INT64):
            raise ValueError(
                "generate() needs a single integer token input; this graph "
                f"has {len(ins)} input(s) — drive prefill/decode steps "
                "directly (executor.make_prefill_step/make_decode_step) "
                "for custom input schemes")

    # -------------------------------------------------------------- obs hooks
    def _tracer(self):
        return self.model._obs_tracer()

    @property
    def _paged(self) -> bool:
        return self.kv_cache == "paged"

    @property
    def decode_compiles(self) -> Optional[int]:
        """Entries in the decode step's jit cache — the recompile-free
        contract is exactly ``== 1`` after warmup (asserted in tier-1).
        The key includes the guard mode of the last serve (guarded and
        unguarded decode are distinct programs, each with its own
        one-entry contract)."""
        fn = self.executor._serving_jits.get(
            ("decode", self.max_decode_len, self.exact_decode,
             self._last_guard,
             self.kv_block_size if self._paged else 0, self.kv_dtype,
             self.seq_shards))
        if fn is None:
            return None
        try:
            return int(fn._cache_size())
        except Exception:
            return None

    # ------------------------------------------------------------ device fns
    def _decode_fn(self, guard: bool = False):
        return self.executor.make_decode_step(
            self.max_decode_len, exact=self.exact_decode, guard=guard,
            block_size=self.kv_block_size if self._paged else 0,
            kv_dtype=self.kv_dtype, seq_shards=self.seq_shards)

    def _prefill_fn(self, bucket: int):
        return self.executor.make_prefill_step(bucket, self.max_decode_len)

    @staticmethod
    def _is_kv_entry(entry) -> bool:
        """Attention KV entries are (k, v) tuples of 4-D per-request ring
        buffers ``(1, h, max_len, hd)`` — the pageable kind; everything
        else (the LSTM carry ``(1, 2h)``) stays slot-major."""
        import jax

        leaves = jax.tree_util.tree_leaves(entry)
        return bool(leaves) and all(
            getattr(leaf, "ndim", 0) == 4 for leaf in leaves)

    def _write_slot(self, cache, slot: int, length: int, token,
                    table_row=None) -> None:
        """Insert one prefilled request into the decode batch: cache rows,
        length cursor and the pending first token — one jitted scatter,
        slot/length/token traced (no per-slot recompiles). Paged engines
        additionally scatter the request's ring cache into its table
        row's pool blocks (quantizing for int8 layouts) and set the
        slot's block-table row — ``table_row`` is a traced int32 array,
        so block choice never recompiles either."""
        import jax
        import jax.numpy as jnp

        from .kvcache import scatter_prefill_paged

        if self._write_slot_fn is None:
            paged = self._paged
            bs = self.kv_block_size
            int8 = self.kv_dtype == "int8"
            # the ONE pagedness decision: the entry-name set recorded by
            # _ensure_state when it built the pool (a second structural
            # classifier here could silently disagree for a future
            # stateful op's cache shape)
            kv_names = self._paged_entry_names if paged else set()

            def write(state, last, cache, slot, length, token, table_row):
                caches = {}
                for name in state.caches:
                    if paged and name in kv_names:
                        if int8:
                            kq, ks, vq, vs = state.caches[name]
                            kc, vc = cache[name]
                            kq, ks = scatter_prefill_paged(
                                kq, kc, table_row, bs, scales=ks)
                            vq, vs = scatter_prefill_paged(
                                vq, vc, table_row, bs, scales=vs)
                            caches[name] = (kq, ks, vq, vs)
                        else:
                            kp, vp = state.caches[name]
                            kc, vc = cache[name]
                            kp, _ = scatter_prefill_paged(kp, kc,
                                                          table_row, bs)
                            vp, _ = scatter_prefill_paged(vp, vc,
                                                          table_row, bs)
                            caches[name] = (kp, vp)
                    else:
                        caches[name] = update_slot_entry(
                            state.caches[name], cache[name], slot)
                lengths = state.lengths.at[slot].set(length)
                tables = state.block_tables
                if tables is not None:
                    tables = tables.at[slot].set(table_row)
                last = last.at[slot, 0].set(token)
                return DecodeState(caches=caches, lengths=lengths,
                                   block_tables=tables), last

            self._write_slot_fn = jax.jit(write, donate_argnums=(0, 1))
        if table_row is None:
            table_row = np.zeros(
                (getattr(self, "max_blocks_per_slot", 1),), np.int32)
        self.state, self._last_tokens = self._write_slot_fn(
            self.state, self._last_tokens, cache,
            jnp.int32(slot), jnp.int32(length), jnp.int32(token),
            jnp.asarray(table_row, jnp.int32))

    def _clear_slot_tables(self, slot: int) -> None:
        """Reset a freed slot's device-side block-table row (all GARBAGE)
        and length cursor (0). Fired by the scheduler on EVERY
        slot-freeing path: without it the freed slot's stale row keeps
        scattering its discarded per-step tokens into blocks the
        allocator may already have handed to a NEW request in a
        different slot — KV corruption with no error (the garbage-block
        safety argument only covers never-admitted slots). One tiny
        donated jit; slot traced, so recycling never recompiles."""
        import jax
        import jax.numpy as jnp

        from .resilience import state_buffers_lost

        if self.state is None or self.state.block_tables is None or \
                state_buffers_lost(self.state):
            return  # no pool (or a dead one about to be rebuilt)
        if self._clear_slot_fn is None:
            def clear(state, slot):
                return DecodeState(
                    caches=state.caches,
                    lengths=state.lengths.at[slot].set(0),
                    block_tables=state.block_tables.at[slot].set(0))

            self._clear_slot_fn = jax.jit(clear, donate_argnums=(0,))
        self.state = self._clear_slot_fn(self.state, jnp.int32(slot))

    def _table_row_for(self, req) -> np.ndarray:
        """The (max_blocks_per_slot,) int32 block-table row for an
        admitted request: its allocated blocks, GARBAGE_BLOCK beyond."""
        row = np.zeros((self.max_blocks_per_slot,), np.int32)
        if req.kv_blocks:
            row[:len(req.kv_blocks)] = req.kv_blocks
        return row

    # ------------------------------------------------- prefix cache (ISSUE 14)
    def _chunk_fn(self, chunk_shape: int):
        return self.executor.make_chunk_prefill_step(
            int(chunk_shape), self.max_decode_len, self.kv_block_size,
            self.kv_dtype)

    def _cow_clone(self, src: int, dst: int) -> None:
        """Copy-on-write clone: duplicate pool block ``src`` into the
        freshly-allocated ``dst`` across every paged cache entry (int8
        scale arrays included) before the cloner's first divergent
        write. One tiny donated jit with traced block ids — exactly the
        ``_clear_slot_tables`` idiom — so COW never recompiles. The
        sharer's block is read, never written: its rows stay bitwise
        untouched (tests/test_prefix_cache.py pins the isolation)."""
        import jax
        import jax.numpy as jnp

        if self.state is None:
            return  # no pool yet: nothing to clone from
        if getattr(self, "_cow_clone_fn", None) is None:
            paged_names = set(self._paged_entry_names)

            def clone(state, src, dst):
                caches = {}
                for name, entry in state.caches.items():
                    if name in paged_names:
                        caches[name] = tuple(
                            leaf.at[dst].set(leaf[src]) for leaf in entry)
                    else:
                        caches[name] = entry
                return DecodeState(caches=caches, lengths=state.lengths,
                                   block_tables=state.block_tables)

            self._cow_clone_fn = jax.jit(clone, donate_argnums=(0,))
        self.state = self._cow_clone_fn(self.state, jnp.int32(src),
                                        jnp.int32(dst))

    def _set_slot_meta(self, slot: int, length: int, token: int,
                       table_row: np.ndarray) -> None:
        """Arm a chunk-prefilled slot for decode: set its device-side
        length cursor, block-table row and pending first token — the
        pool rows were already written by the chunks, so this is the
        ``_write_slot`` tail without the ring scatter. Traced indices:
        no recompiles."""
        import jax
        import jax.numpy as jnp

        if getattr(self, "_set_slot_meta_fn", None) is None:
            def meta(state, last, slot, length, token, table_row):
                tables = state.block_tables
                if tables is not None:
                    tables = tables.at[slot].set(table_row)
                return (DecodeState(caches=state.caches,
                                    lengths=state.lengths.at[slot].set(
                                        length),
                                    block_tables=tables),
                        last.at[slot, 0].set(token))

            self._set_slot_meta_fn = jax.jit(meta, donate_argnums=(0, 1))
        self.state, self._last_tokens = self._set_slot_meta_fn(
            self.state, self._last_tokens, jnp.int32(slot),
            jnp.int32(length), jnp.int32(token),
            jnp.asarray(table_row, jnp.int32))

    def _ensure_state_bootstrap(self) -> None:
        """A chunk action needs the pool, but the pool structure comes
        from a prefill cache and none has run yet (first-ever admission
        went straight to the chunk path): derive it from one smallest-
        bucket prefill on a dummy token — the same program the health
        probe dispatches, so steady-state this is a warm compile and
        the cache content is discarded (``_ensure_state`` builds
        zeroed pools from its STRUCTURE only)."""
        import jax.numpy as jnp

        if self.state is not None:
            return
        b0 = self.buckets[0]
        ids = np.zeros((1, b0), np.int32)
        _lg, _last, cache = self._prefill_fn(b0)(
            self.model.params, [jnp.asarray(ids)],
            jnp.asarray([1], jnp.int32))
        self._ensure_state(cache)
        # normalize through the classic slot writer — a value-level
        # no-op (dummy cache scattered at an all-garbage row, slot 0,
        # length 0, token 0) whose OUTPUT carries the same committed
        # placement every later step input will: the chunk program then
        # compiles exactly once per shape (an uncommitted first input
        # would key a second fastpath entry)
        self._write_slot(cache, 0, 0, 0,
                         table_row=np.zeros((self.max_blocks_per_slot,),
                                            np.int32))

    def prefix_peek(self, tokens, cap: Optional[int] = None) -> int:
        """Longest cached-prefix length (tokens) the engine's trie holds
        for ``tokens`` — no LRU touch, no counters. The fleet router's
        cache-affinity term (ISSUE 14: route a request to the replica
        whose trie holds its longest prefix); 0 for prefix-less
        engines."""
        if self._prefix is None:
            return 0
        n = len(tokens)
        return self._prefix.peek(tokens, cap=n - 1 if cap is None
                                 else cap)

    def _ensure_state(self, prefill_cache) -> None:
        """Allocate the slot-pool DecodeState lazily from the first
        prefill's cache structure (zeros; every slot's rows are fully
        overwritten by its admission prefill before any read). Paged
        engines build the block POOL per KV entry — ``(kv_pool_blocks,
        h, block_size, hd)`` (+ f32 scale arrays for int8) — instead of
        per-slot rings, plus the all-garbage block tables."""
        import jax
        import jax.numpy as jnp

        from .kvcache import paged_pool_entry

        if self.state is not None:
            return
        if self._prefix is not None and self._prefix.n_blocks:
            # building a FRESH pool (first admission after a device-loss
            # rebuild): every cached block id would dangle into zeroed
            # arrays — drop the trie, returning its references, before
            # anything can match stale pointers
            self._prefix.clear(free=True)
        n = self.n_slots
        tables = None
        if self._paged:
            caches = {}
            self._paged_entry_names = set()
            for name, entry in prefill_cache.items():
                if self._is_kv_entry(entry):
                    self._paged_entry_names.add(name)
                    kc, vc = entry
                    if self.kv_dtype == "int8":
                        kq, ks = paged_pool_entry(
                            kc, self.kv_pool_blocks, self.kv_block_size,
                            "int8")
                        vq, vs = paged_pool_entry(
                            vc, self.kv_pool_blocks, self.kv_block_size,
                            "int8")
                        caches[name] = (kq, ks, vq, vs)
                    else:
                        caches[name] = (
                            paged_pool_entry(kc, self.kv_pool_blocks,
                                             self.kv_block_size, "native"),
                            paged_pool_entry(vc, self.kv_pool_blocks,
                                             self.kv_block_size, "native"))
                else:
                    caches[name] = jax.tree.map(
                        lambda leaf: jnp.zeros((n,) + leaf.shape[1:],
                                               leaf.dtype), entry)
            tables = jnp.zeros((n, self.max_blocks_per_slot), jnp.int32)
        else:
            caches = jax.tree.map(
                lambda leaf: jnp.zeros((n,) + leaf.shape[1:], leaf.dtype),
                prefill_cache)
        self.state = DecodeState(caches=caches,
                                 lengths=jnp.zeros((n,), jnp.int32),
                                 block_tables=tables)
        self._last_tokens = jnp.zeros((n, 1), jnp.int32)

    def _sampler(self, temperature: float, top_k: int):
        """Jitted ``(logits (S, V), base_rng, tag_counts (S, 2) int32) ->
        tokens (S,)`` — one row per slot, each row drawing from its own
        stream ``fold_in(fold_in(base, tag), count)``. The folds happen
        IN-JIT so the decode hot loop dispatches one fused program, not
        2·slots host-side fold_in calls per token. Greedy when
        temperature <= 0; otherwise top-k filtered categorical at
        ``temperature`` — through the Pallas row top-k kernel when the
        shape qualifies (kernels/topk.py), ``lax.top_k`` otherwise."""
        import jax
        import jax.numpy as jnp

        greedy = temperature <= 0.0
        key = ("greedy",) if greedy else ("sample", float(temperature),
                                          int(top_k))
        fn = self._samplers.get(key)
        if fn is not None:
            return fn
        if greedy:
            def sample(logits, base_rng, tag_counts):
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            temp = float(temperature)
            k = int(top_k)

            def row_rng(base_rng, tc):
                return jax.random.fold_in(
                    jax.random.fold_in(base_rng, tc[0]), tc[1])

            def sample(logits, base_rng, tag_counts):
                rngs = jax.vmap(lambda tc: row_rng(base_rng, tc))(
                    tag_counts)
                if k > 0:
                    from ..kernels.topk import (pallas_topk,
                                                should_use_pallas_topk)

                    if should_use_pallas_topk(logits, k, opt_in=True):
                        vals, idx = pallas_topk(logits, k)
                    else:
                        vals, idx = jax.lax.top_k(logits, k)
                    choice = jax.vmap(
                        lambda v, r: jax.random.categorical(r, v / temp))(
                            vals, rngs)
                    return jnp.take_along_axis(
                        idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
                return jax.vmap(
                    lambda lg, r: jax.random.categorical(r, lg / temp))(
                        logits, rngs).astype(jnp.int32)

        fn = jax.jit(sample)
        self._samplers[key] = fn
        return fn

    # ------------------------------------------------------------- main loop
    def _make_resilience(self, chaos):
        from .resilience import ServingResilience

        return ServingResilience(self.model.config, chaos=chaos,
                                 controller=self.admission,
                                 clock=self.resilience_clock)

    def _attach_kv_accounting(self, sched: ContinuousBatchScheduler
                              ) -> None:
        """Bind the engine's paged-KV bookkeeping to a scheduler: the
        block allocator (admission allocates, recycling frees) and the
        max supported context (admission rejects beyond the position
        table, ISSUE 12 satellite). Idempotent; a ring engine only sets
        the context bound when the table is the binding constraint."""
        if self.block_allocator is not None:
            sched.allocator = self.block_allocator
            sched.on_slot_freed = self._clear_slot_tables
            # prefix cache + chunked prefill (ISSUE 14): admission walks
            # the trie and long suffixes/prompts take the chunk path
            sched.prefix = self._prefix
            sched.chunk_tokens = self.prefill_chunk_tokens
        if self.max_context < sched.max_len:
            sched.max_context = self.max_context

    def admit(self, sched: ContinuousBatchScheduler, req: Request,
              resilience=None) -> None:
        """Resilient admission (ISSUE 9): deadline stamp + shed-policy
        gate + scheduler submit. Raises ``OverloadError`` (shed) or
        ``QueueFullError`` (hard queue wall) — both ``ServingRejection``,
        so callers write one except clause. Without an explicit
        ``resilience``, events accumulate on a pending policy object the
        next ``serve()`` consumes — a pre-serve shed or deadline stamp is
        never lost to a throwaway."""
        self._attach_kv_accounting(sched)
        self._stamp_context_bucket(req)
        res = resilience
        if res is None:
            if self._pending_resilience is None:
                self._pending_resilience = self._make_resilience(None)
            res = self._pending_resilience
        res.admit(sched, req)

    def _stamp_context_bucket(self, req: Request) -> None:
        """Admission half of the ISSUE 18 context-length routing: stamp
        the request with the smallest searched bucket covering its max
        context (prompt + decode budget); beyond every bucket it takes
        the largest — mirroring ``ServingPlan.seq_shards_for``, so the
        stamped bucket is the one whose searched seq_shards the request
        decodes under. No-op without buckets (or if already stamped by
        a router upstream)."""
        if not self.context_buckets or req.context_bucket is not None:
            return
        need = int(req.prompt_len + req.max_new_tokens)
        for b in self.context_buckets:
            if need <= b:
                req.context_bucket = b
                return
        req.context_bucket = self.context_buckets[-1]

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, eos_id: Optional[int] = None,
                 seed: int = 0, chaos=None,
                 deadline_ms: Optional[float] = None) -> List[List[int]]:
        """Generate continuations for ``prompts`` (token-id sequences)
        through the continuous-batching loop; returns the generated token
        lists in submission order. Deterministic for a given (prompts,
        sampling params, seed) regardless of slot timing. ``deadline_ms``
        stamps each request with a relative completion budget (defaulted
        from ``--request-timeout-ms``); a request shed at admission or
        evicted/drained mid-serve returns its partial (possibly empty)
        continuation, with ``Request.outcome`` recording why — read
        ``self.stats.outcomes`` / ``self.drained_requests`` for the
        ledger."""
        self._token_input_check()
        res = self._make_resilience(chaos)
        sched = ContinuousBatchScheduler(
            n_slots=self.n_slots, max_queue=max(len(prompts),
                                                self.max_queue),
            buckets=self.buckets, max_len=self.max_decode_len,
            clock=res.clock)
        sched.shed_policy = res.shed_policy
        self._attach_kv_accounting(sched)
        reqs = []
        for i, p in enumerate(prompts):
            r = Request(prompt=np.asarray(p, dtype=np.int32),
                        max_new_tokens=max_new_tokens,
                        eos_id=self.eos_id if eos_id is None else eos_id,
                        rng_tag=i, deadline_ms=deadline_ms)
            self._stamp_context_bucket(r)
            try:
                res.admit(sched, r)
            except ServingRejection:
                pass  # r.outcome == "shed"; ledger picks it up in serve()
            reqs.append(r)
        self.serve(sched, temperature=temperature, top_k=top_k, seed=seed,
                   chaos=chaos, resilience=res)
        return [list(r.generated) for r in reqs]

    def start_serve(self, sched: ContinuousBatchScheduler,
                    temperature: float = 0.0, top_k: int = 0,
                    seed: int = 0, chaos=None, resilience=None,
                    publish_telemetry: bool = True) -> "_ServeLoop":
        """Begin a serve run without driving it to completion: returns
        the :class:`_ServeLoop` whose ``tick()`` advances exactly one
        scheduler action (a prefill or one decode step). This is the
        hook the fleet router (``serving/fleet.py``, ISSUE 11) uses to
        interleave N replicas' progress in one host loop; standalone
        ``serve()`` is exactly ``start_serve`` + ``while tick()`` +
        ``finish()``.

        ISSUE 17: ``--serve-loop async`` returns the double-buffered
        :class:`_AsyncServeLoop` instead — same contract, but one decode
        step's result may be IN FLIGHT between ticks (``settle()``
        forces arrival; ``finish()`` always settles first)."""
        cls = _AsyncServeLoop if self.serve_loop == "async" else _ServeLoop
        return cls(self, sched, temperature=temperature,
                   top_k=top_k, seed=seed, chaos=chaos,
                   resilience=resilience,
                   publish_telemetry=publish_telemetry)

    def serve(self, sched: ContinuousBatchScheduler,
              temperature: float = 0.0, top_k: int = 0,
              seed: int = 0, chaos=None, resilience=None) -> ServingStats:
        """Drive the scheduler until queue and slots drain. One decode
        step advances EVERY live slot one token (iteration-level
        batching); prefills are interleaved the moment a slot frees.

        Resilience (ISSUE 9, serving/resilience.py): the loop installs
        the flag-only SIGTERM/SIGINT handler from ``resilience/session.py``
        — a preemption signal turns into a graceful drain (admission
        stops, in-flight requests finish within ``--drain-grace-s``,
        queued ones are handed back via ``self.drained_requests``). When
        any resilience feature is armed (deadlines, a shed policy, or a
        ``ChaosPlan``) every decode iteration additionally sweeps expired
        deadlines and runs the guarded decode step, whose per-slot
        isfinite verdict quarantines only a poisoned slot (retry on a
        fresh slot per ``--decode-retry-budget``) while co-batched
        streams continue bit-identically. A device-loss error triggers
        the existing ``elastic_replan`` automatically with bounded
        backoff. A plain serve (nothing armed) pays none of the
        per-iteration costs."""
        from ..resilience.session import ResilienceSession

        loop = self.start_serve(sched, temperature=temperature,
                                top_k=top_k, seed=seed, chaos=chaos,
                                resilience=resilience)
        session = ResilienceSession(self.model, signals_only=True)
        session.install_signal_handlers()
        try:
            while True:
                if session.preempted:
                    # flag-only handler fired: graceful drain — stop
                    # admitting, let in-flight requests finish inside the
                    # grace window, hand the queue back
                    loop.request_drain(session=session)
                if not loop.tick():
                    break
        finally:
            session.close()
        return loop.finish()

    # ------------------------------------------------------ resilience hooks
    def health_probe(self, prompt: Sequence[int] = (1, 2, 3)) -> bool:
        """One prefill dispatch + finite-logits verdict, touching neither
        the scheduler nor the slot-pool DecodeState: the fleet router's
        active health check (ISSUE 11). A replica whose compute produces
        non-finite next-token logits for a trivial prompt — or whose
        dispatch raises — fails the probe; the circuit breaker decides
        what that means. The probe reuses the smallest prefill bucket's
        already-compiled program, so a steady-state probe costs one
        dispatch, not a compile."""
        import jax
        import jax.numpy as jnp

        try:
            bucket = self.buckets[0]
            eff = max(1, min(len(prompt), bucket))
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :eff] = np.asarray(prompt[:eff], np.int32)
            _logits, last, _cache = self._prefill_fn(bucket)(
                self.model.params, [jnp.asarray(ids)],
                jnp.asarray([eff], jnp.int32))
            return bool(np.all(np.isfinite(
                np.asarray(jax.device_get(last)))))
        except Exception:
            return False

    def reset_decode_pool(self) -> None:
        """Drop the slot-pool DecodeState (replica kill / rejoin in the
        fleet): the next admission prefill rebuilds it from scratch via
        ``_ensure_state`` — committed tokens live host-side on each
        Request, so nothing user-visible is lost. Paged engines also
        reset the block allocator (no block of the discarded pool is
        live anymore; survivors' re-prefills allocate fresh tables)."""
        self.state = None
        self._last_tokens = None
        if self._prefix is not None:
            # the cached blocks die with the pool arrays; the allocator
            # reset below forgets refcounts wholesale, so the trie just
            # drops its nodes without per-block decrements
            self._prefix.clear(free=False)
        if self.block_allocator is not None:
            self.block_allocator.reset()

    # ------------------------------------------------------ KV accounting
    def _kv_row_bytes(self) -> int:
        """Analytic KV bytes ONE token's row costs across every attention
        node — heads * (kdim + vdim) * element size (int8 layouts add the
        two f32 per-(token, head) scales). The decode bytes-read/token
        bench column and the admission-honesty math both price from
        this."""
        if getattr(self, "_kv_row_bytes_cache", None) is None:
            from ..ffconst import size_of_datatype
            from .kvcache import kv_token_bytes

            total = 0
            for node in self.executor.pcg.compute_nodes():
                if node.op.op_type != OperatorType.OP_MULTIHEAD_ATTENTION:
                    continue
                a = node.op.attrs
                heads = int(a.get("num_heads", 1))
                kd = int(a.get("kdim") or a["embed_dim"] // heads)
                vd = int(a.get("vdim") or a["embed_dim"] // heads)
                total += kv_token_bytes(
                    heads, kd, vd, size_of_datatype(node.op.data_type),
                    self.kv_dtype)
            self._kv_row_bytes_cache = total
        return self._kv_row_bytes_cache

    def _decode_kv_bytes(self, live) -> int:
        """Analytic KV bytes this decode step's attention reads: paged —
        each live slot's OCCUPIED blocks (the flash-decode kernel's
        actual traffic, O(true_length)); ring — every slot's full
        ``max_len`` ring (the O(max_len) bill paged decode removes)."""
        row = self._kv_row_bytes()
        if not self._paged:
            return self.n_slots * self.max_decode_len * row
        bs = self.kv_block_size
        toks = 0
        for _slot, req in live:
            keys = req.effective_len + 1
            toks += -(-keys // bs) * bs
        return toks * row

    def _sweep_deadlines(self, sched, res, tracer) -> None:
        """Deadline enforcement at the iteration boundary: expired queued
        requests are dropped before they cost a prefill; expired in-flight
        requests are evicted and their slot recycled (outcome
        ``deadline_exceeded`` either way)."""
        now = res.clock()
        for req in [r for r in sched.queue if r.expired(now)]:
            res.deadline_misses += 1
            sched.drop_queued(req, "deadline_exceeded")
            if tracer.enabled:
                tracer.event("deadline_exceeded", rid=req.rid, queued=True)
        for slot, req in enumerate(list(sched.slots)):
            if req is not None and req.expired(now):
                res.deadline_misses += 1
                sched.evict(slot, "deadline_exceeded")
                if tracer.enabled:
                    tracer.event("deadline_exceeded", rid=req.rid,
                                 slot=slot,
                                 tokens=len(req.generated))

    def _quarantine(self, sched, res, slot: int, req, tracer) -> None:
        """Decode-health verdict said this slot's logits are non-finite:
        quarantine the slot, retry the request on a fresh slot while its
        retry budget lasts (re-prefilling prompt + committed tokens so the
        stream continues exactly where it stopped), abort it with outcome
        ``decode_fault`` once the budget is spent."""
        res.quarantines += 1
        retryable = req.retries_used < res.decode_retry_budget
        if retryable:
            try:
                bucket_for(req.effective_len, sched.buckets)
            except ValueError:
                retryable = False  # committed stream outgrew the buckets
        if retryable:
            req.retries_used += 1
            res.decode_retries += 1
            sched.quarantine(slot)
            if tracer.enabled:
                tracer.event("decode_quarantine", rid=req.rid, slot=slot,
                             retry=req.retries_used,
                             tokens=len(req.generated))
        else:
            res.decode_faults += 1
            sched.evict(slot, "decode_fault")
            if tracer.enabled:
                tracer.event("decode_fault", rid=req.rid, slot=slot,
                             retries_used=req.retries_used)

    def _dispatch_decode(self, params, res, chaos, k: int, guard: bool,
                         tracer):
        """One decode dispatch with device-loss failover: a scripted
        (``ChaosPlan.drop_devices_at``) or real device-loss error triggers
        ``elastic_replan`` onto the survivors with bounded linear backoff.
        When the DecodeState survives the hop (chaos injection, or an
        error raised before the donated buffers were consumed) generation
        resumes from it bit-identically; when it did NOT (a real loss
        mid-execution — the buffers were donated to the failed dispatch
        or lived on the lost chips) ``DecodeStateLostError`` tells the
        serve loop to rebuild the pool and re-prefill every live stream
        from its host-side committed tokens instead of retrying into an
        'Array has been deleted'. Returns ``(logits, ok_vec-or-None)``."""
        import jax

        from .resilience import (DecodeStateLostError, DeviceLossError,
                                 looks_like_device_loss,
                                 state_buffers_lost)

        attempt = 0
        while True:
            try:
                if chaos is not None:
                    n = chaos.maybe_drop_devices(k)
                    if n is not None:
                        raise DeviceLossError(n)
                decode = self._decode_fn(guard=guard)
                if guard:
                    logits, self.state, ok = decode(
                        params, [self._last_tokens], self.state)
                    return logits, ok
                logits, self.state = decode(params, [self._last_tokens],
                                            self.state)
                return logits, None
            except Exception as e:  # noqa: BLE001 — filtered just below
                if not looks_like_device_loss(e):
                    raise
                surviving = e.n_dev if isinstance(e, DeviceLossError) \
                    else len(jax.devices())
                attempt += 1
                if attempt > res.max_replan_attempts:
                    raise
                if tracer.enabled:
                    tracer.event("serving_device_loss", step=k,
                                 surviving=surviving, attempt=attempt)
                # first retry is immediate; repeats back off linearly
                if attempt > 1 and res.replan_backoff_s > 0:
                    time.sleep(res.replan_backoff_s * (attempt - 1))
                self.elastic_replan(surviving)
                res.replans += 1
                if state_buffers_lost(self.state, self._last_tokens):
                    raise DecodeStateLostError(
                        f"DecodeState lost with the device at step {k} "
                        "(buffers donated to the failed dispatch or "
                        "resident on the lost chips); re-prefilling live "
                        "streams from committed tokens") from e

    def _merge_telemetry(self, sched, stats: ServingStats) -> None:
        """Publish the run into a StepTelemetry ``serving`` block (mirrors
        the resilience / strategy_safety blocks) when a sink wants one."""
        tracer = self._tracer()
        tel = self.model._make_telemetry(tracer, batch_size=self.n_slots,
                                         phase="serving")
        self.model._telemetry = tel or getattr(self.model, "_telemetry",
                                               None)
        if tel is None:
            return
        for w in stats.token_walls_s:
            tel.record_step(w)
        tel.requests_served = stats.requests_served
        tel.tokens_generated = stats.tokens_generated
        tel.queue_depth_hwm = stats.queue_depth_hwm
        tel.serving_p50_token_ms = stats.p50_token_ms()
        tel.serving_p99_token_ms = stats.p99_token_ms()
        tel.serving_tokens_per_s = round(stats.tokens_per_s(), 2)
        # host-overhead accounting (ISSUE 16, ROADMAP item 5)
        tel.serving_host_overhead_fraction = stats.host_overhead_fraction()
        # per-shard-chip KV residency (ISSUE 18) — only once a decode
        # step measured the fill
        tel.serving_kv_hbm_per_chip_bytes = \
            stats.kv_hbm_per_chip_bytes or None
        # serving_resilience block (ISSUE 9): the outcome ledger + event
        # counters, mirroring the resilience/strategy_safety blocks
        tel.serving_outcomes = dict(stats.outcomes)
        tel.serving_sheds = stats.sheds
        tel.serving_deadline_misses = stats.deadline_misses
        tel.serving_quarantines = stats.quarantines
        tel.serving_drains = stats.drains
        tel.serving_replans = stats.replans
        # serving_prefix block (ISSUE 14): the prefix-cache/chunked-
        # prefill ledger, mirroring the serving_resilience block
        tel.serving_prefix_hits = stats.prefix_hits
        tel.serving_prefix_tokens_reused = stats.prefix_tokens_reused
        tel.serving_prefill_tokens_computed = stats.prefill_tokens_computed
        tel.serving_cache_evictions = stats.cache_evictions
        tel.serving_chunked_prefills = stats.chunked_prefills
        tel.finalize()
        if self.model.config.telemetry_file:
            tel.write(self.model.config.telemetry_file)

    # ---------------------------------------------------------------- elastic
    def elastic_replan(self, n_dev: int):
        """Mid-serve re-search (PR 4/5 carry-over): a replica that lost
        chips re-runs the serving-objective search on the surviving device
        count — reusing the warm delta-cost Simulator. The searched plan
        is RECORDED (``self.plan``; ``plan.to_strategy`` materializes
        executor shardings) — applying it to a live multi-chip mesh
        (reshard weights + DecodeState onto the new layout) is the
        follow-on; what this models today is the migration's control path:
        the serving jits are deliberately dropped and recompiled, and the
        in-flight DecodeState must survive that hop untouched, so
        generation resumes exactly where it stopped (tier-1 asserts
        bit-identical continuations across a replan)."""
        from .search import serving_search

        # price prefill with the MEASURED prefix-cache hit rate of the
        # run so far (ISSUE 14: the latency-bounded objective sees the
        # real expected prefill cost, not the cold-cache worst case)
        reuse = self.stats.prefix_reuse_rate() or 0.0
        plan = serving_search(self.executor.pcg, self.model.config, n_dev,
                              sim=self._search_sim, prefill_reuse=reuse)
        self._search_sim = plan.sim
        self.plan = plan
        # drop and rebuild the serving jits — the migration recompile the
        # bit-identity contract is tested against; samplers and the slot
        # writer are state-shape-stable and survive
        self.executor._serving_jits = {}
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("serving_replan", n_dev=n_dev,
                         mesh=list(plan.mesh_shape),
                         tokens_per_s=round(plan.sim_tokens_per_s, 1))
        return plan


def _state_lost(state) -> bool:
    from .resilience import state_buffers_lost

    return state_buffers_lost(state)


class _ServeLoop:
    """One serve() run's loop state, advanced one scheduler action at a
    time (ISSUE 11 refactor: the monolithic serve loop became
    start_serve/tick/finish so the fleet router can interleave N
    replicas' progress in a single host loop while each replica keeps
    the exact PR 9 per-iteration semantics — deadline sweeps, guarded
    decode, quarantine-retry, drain, device-loss failover).

    Contract: ``tick()`` performs exactly one action (one prefill, or
    one decode step advancing every live slot) and returns True;
    returning False means the scheduler has nothing to do *right now* —
    standalone ``serve()`` treats that as completion, the fleet may
    dispatch more work and tick again. ``finish()`` closes the ledger
    exactly once (idempotent)."""

    def __init__(self, engine: ServingEngine,
                 sched: ContinuousBatchScheduler,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 chaos=None, resilience=None,
                 publish_telemetry: bool = True):
        import jax

        eng = self.engine = engine
        self.sched = sched
        self.publish_telemetry = publish_telemetry
        self.tracer = eng._tracer()
        self.params = eng.model.params
        self.sampler = eng._sampler(temperature, top_k)
        self.stats = eng.stats = ServingStats()
        pending = eng._pending_resilience
        res = self.res = resilience or pending or \
            eng._make_resilience(chaos)
        eng._pending_resilience = None  # consumed
        if pending is not None and res is not pending:
            # pre-serve admit() calls ledgered their sheds (and deadline
            # arming) on the pending object; carry them into the object
            # this serve reports from so no rejection goes uncounted
            res.sheds += pending.sheds
            res._saw_deadline = res._saw_deadline or pending._saw_deadline
        if chaos is not None:
            res.chaos = chaos
        self.chaos = res.chaos
        # a caller-built resilience arrives with a cold default
        # controller; carry the engine's warm per-token EWMA across so
        # post-replan/rebuild shedding isn't blind for the first window
        # (no-op when the caller's controller is already warm)
        if res.controller is not eng.admission:
            res.controller.warm_start(eng.admission)
        sched.shed_policy = res.shed_policy
        eng._attach_kv_accounting(sched)
        # ONE time base: submit stamps were taken with the scheduler's
        # clock, so every sweep/drain decision reads the same clock — a
        # mismatched engine.resilience_clock on a caller-built scheduler
        # would otherwise make expired() compare across time bases
        res.clock = sched.clock
        # requests submitted straight to the scheduler (sched.submit, the
        # PR 6 pattern) never passed res.admit: stamp config-default
        # deadlines and arm the sweeps for any caller-set deadline_ms so
        # the documented enforcement does not depend on the entry point
        for r in list(sched.queue) + [s for s in sched.slots
                                      if s is not None]:
            res.stamp_deadline(r)
        self.res_active = res.armed
        self.guard = bool(self.res_active)
        eng._last_guard = self.guard
        eng.drained_requests = []
        self.base_rng = jax.random.PRNGKey(seed)
        self.step_no = 0
        self.storm_seq = 0
        self.draining = False
        self.drain_deadline_ms = None
        self.finished = False
        # prefix cache (ISSUE 14): a trie that outlived its pool (the
        # caller dropped eng.state, or buffers died with a device) must
        # be cleared BEFORE the first admission can match stale block
        # ids into the zeroed rebuild
        if eng._prefix is not None and eng._prefix.n_blocks and (
                eng.state is None or _state_lost(eng.state)):
            eng._prefix.clear(free=True)
        # per-run deltas against persistent counters — the trie (and a
        # caller-reused scheduler) outlive this run, so finish()
        # reports differences, not totals
        self._chunk_walls: Dict[int, float] = {}
        self._prefix_hits0 = sched.prefix_hits
        self._prefix_reused0 = sched.prefix_tokens_reused
        self._evictions0 = (eng._prefix.evictions
                            if eng._prefix is not None else 0)
        self.t0 = time.perf_counter()

    # ---------------------------------------------------------------- drain
    def request_drain(self, session=None) -> None:
        """The graceful-drain transition (SIGTERM in serve(),
        ``fleet.drain`` in the router): admission stops, in-flight
        requests get the grace window, queued ones are handed back at
        ``finish()``. Idempotent — repeat calls are no-ops."""
        if self.draining:
            return
        sched, res = self.sched, self.res
        self.draining = True
        sched.draining = True
        res.drains += 1
        if session is not None:
            session.note_preemption(self.stats.decode_steps)
        self.drain_deadline_ms = res.clock() + res.drain_grace_s * 1e3
        if self.tracer.enabled:
            self.tracer.event("serving_drain",
                              step=self.stats.decode_steps,
                              queued=sched.queued, active=sched.active,
                              grace_s=res.drain_grace_s)

    # -------------------------------------------------- pending transfers
    def settle(self) -> None:
        """Force any in-flight decode result to arrive and commit — the
        async runtime's explicit drain point (ISSUE 17). Every path
        that must observe settled scheduler/ledger state calls it:
        ``finish()``, the drain-grace eviction, the fleet's
        harvest/kill/migration, and the DecodeStateLost rebuild. The
        sync loop never has a pending transfer, so this is a no-op."""
        self._settle_pending()

    def _settle_pending(self) -> None:
        return None

    def _fetch(self, toks, ok_vec):
        """The ONE blocking host-transfer choke point for decode results
        (ISSUE 17 satellite: the formerly separate guarded/unguarded
        ``device_get`` call sites unified). Both the sync loop and the
        async runtime's pending-transfer settle route through here, so
        counting blocking host syncs means counting THIS
        (``stats.host_syncs``; the async steady-state contract is <= 1
        per committed decode step). Returns ``(tokens (n_slots,)
        np.int32, ok (n_slots,) bool-or-None)``."""
        import jax

        self.stats.host_syncs += 1
        if ok_vec is not None:
            # the ONE extra transfer of the guarded step: the per-slot
            # finite verdict rides the same device_get as the tokens —
            # still a single blocking sync
            toks_host, ok_host = jax.device_get((toks, ok_vec))
            return np.asarray(toks_host), np.asarray(ok_host)
        return np.asarray(jax.device_get(toks)), None

    # ----------------------------------------------------------------- tick
    def _acct_tick(self, t_tick: float, t_dev: float,
                   dev_s: float) -> None:
        """Host-overhead accounting (ISSUE 16, ROADMAP item 5): split
        this tick's wall into dispatch (tick entry -> device call
        issued), device (the blocking call + fetch) and bookkeeping
        (device return -> now). Plain float adds — always on, never
        touches the token streams."""
        st = self.stats
        st.host_dispatch_s += max(t_dev - t_tick, 0.0)
        st.host_device_s += dev_s
        st.host_bookkeep_s += max(
            time.perf_counter() - t_dev - dev_s, 0.0)
        st.host_ticks += 1

    def tick(self) -> bool:
        """Perform ONE scheduler action. Returns False when there is
        nothing to do right now (queue empty + no live slot, or the
        drain grace just expired and evicted the stragglers)."""
        t_tick = time.perf_counter()
        import jax
        import jax.numpy as jnp

        eng, sched, res = self.engine, self.sched, self.res
        stats, tracer = self.stats, self.tracer
        if self.draining and sched.active and \
                res.clock() > self.drain_deadline_ms:
            # grace exhausted: stragglers are evicted (outcome
            # preempted), never silently dropped. In-flight tokens land
            # first (async): a token the device already produced inside
            # the grace window belongs to the stream
            self._settle_pending()
            for slot, r in enumerate(list(sched.slots)):
                if r is not None:
                    sched.evict(slot, "preempted")
            return False
        if self.res_active and res.deadlines_armed:
            eng._sweep_deadlines(sched, res, tracer)
        action = sched.next_action()
        if action is None:
            return self._idle()
        if action[0] == "prefill":
            _, req, slot, bucket = action
            if self.res_active and req.expired(res.clock()):
                # expired while queued but swept into a slot in the same
                # iteration: evict before paying prefill
                res.deadline_misses += 1
                sched.evict(slot, "deadline_exceeded")
                return True
            t_p = time.perf_counter()
            # effective prompt = prompt + committed tokens: empty suffix
            # for a fresh request, the full committed stream for a
            # decode-fault retry (or cross-replica migration) re-prefill
            eff = req.effective_len
            cur = req.current_prompt()
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :eff] = cur
            _logits, last, cache = eng._prefill_fn(bucket)(
                self.params, [jnp.asarray(ids)],
                jnp.asarray([eff], jnp.int32))
            eng._ensure_state(cache)
            # per-request rng: deterministic under co-scheduling — the
            # stream depends on (submission tag, tokens emitted), not
            # slot timing or the replica serving it; a retry/migration
            # resumes its stream exactly where it stopped
            tag = req.rng_tag if req.rng_tag is not None else req.rid
            tok = int(jax.device_get(
                self.sampler(last, self.base_rng,
                             np.asarray([[tag, len(req.generated)]],
                                        np.int32))[0]))
            wall = time.perf_counter() - t_p
            stats.prefills += 1
            stats.prefill_tokens_computed += eff
            stats.record_token(wall)
            stats.tokens_generated += 1
            # first_token_ms is stamped at the commit point
            # (ContinuousBatchScheduler.commit_token) — the one stamp
            # site every first-commit path passes through
            if req.first_token_step is None:
                req.first_token_step = self.step_no
            if tracer.enabled:
                tracer.complete("prefill", wall, rid=req.rid,
                                bucket=bucket, slot=slot, prompt_len=eff)
            if not sched.commit_token(slot, tok):
                eng._write_slot(cache, slot, eff, tok,
                                table_row=(eng._table_row_for(req)
                                           if eng._paged else None))
                # mark completion (the pool holds the prompt's KV now)
                # and eagerly cache the FULL prompt blocks so same-batch
                # shared-prefix admissions already hit; the partial tail
                # is adopted later, at release, so the request's own
                # decode writes into it never trigger a self-COW
                req.prefill_pos = req.prefill_target
                if eng._prefix is not None and req.kv_blocks:
                    full = eff // eng.kv_block_size
                    if full:
                        eng._prefix.insert(cur[:full * eng.kv_block_size],
                                           req.kv_blocks[:full])
            self._acct_tick(t_tick, t_p, wall)
            return True
        if action[0] == "prefill_chunk":
            # chunked prefill / prefix-suffix prefill (ISSUE 14): one
            # fixed-width chunk of ONE slot's prompt, co-scheduled with
            # the other slots' decode steps (the scheduler alternates),
            # so a long prompt never head-of-line-blocks the batch and a
            # trie-hit admission computes only its suffix
            _, req, slot, start, n, shape = action
            if self.res_active and req.expired(res.clock()):
                res.deadline_misses += 1
                sched.evict(slot, "deadline_exceeded")
                self._chunk_walls.pop(req.rid, None)
                return True
            t_p = time.perf_counter()
            eng._ensure_state_bootstrap()
            if req.pending_cow is not None:
                # first divergent write into a shared partial tail
                # block: clone it before this chunk touches it
                src, dst = req.pending_cow
                eng._cow_clone(src, dst)
                sched.release_cow(req)
                if tracer.enabled:
                    tracer.event("prefix_cow_clone", rid=req.rid,
                                 slot=slot, src=src, dst=dst)
            cur = req.current_prompt()
            ids = np.zeros((1, shape), np.int32)
            ids[0, :n] = cur[start:start + n]
            row = eng._table_row_for(req)
            last, eng.state = eng._chunk_fn(shape)(
                self.params, [jnp.asarray(ids)], eng.state,
                jnp.asarray(row, jnp.int32), jnp.int32(start),
                jnp.int32(n))
            stats.prefill_tokens_computed += n
            stats.chunked_prefills += 1
            done = sched.chunk_done(slot, n)
            wall = time.perf_counter() - t_p
            self._chunk_walls[req.rid] = \
                self._chunk_walls.get(req.rid, 0.0) + wall
            if tracer.enabled:
                tracer.complete("prefill_chunk", wall, rid=req.rid,
                                slot=slot, start=start, tokens=n,
                                hit=req.prefix_hit_tokens, done=done)
            if sched.rt.enabled:
                sched.rt.note(req.rid, "chunk", float(res.clock()),
                              start=start, tokens=n,
                              replica=sched.replica_idx)
            if not done:
                self._acct_tick(t_tick, t_p, wall)
                return True
            eff = req.prefill_target
            tag = req.rng_tag if req.rng_tag is not None else req.rid
            tok = int(jax.device_get(
                self.sampler(last, self.base_rng,
                             np.asarray([[tag, len(req.generated)]],
                                        np.int32))[0]))
            stats.prefills += 1
            stats.record_token(self._chunk_walls.pop(req.rid, wall))
            stats.tokens_generated += 1
            # first_token_ms lands at the commit point (commit_token)
            if req.first_token_step is None:
                req.first_token_step = self.step_no
            if eng._prefix is not None and req.kv_blocks:
                full = eff // eng.kv_block_size
                if full:
                    eng._prefix.insert(cur[:full * eng.kv_block_size],
                                       req.kv_blocks[:full])
            if not sched.commit_token(slot, tok):
                # arm the slot for decode: the chunks already wrote the
                # pool rows, so only the device-side cursor/table/token
                # remain (the row stayed garbage during chunking — the
                # decode steps running between chunks wrote this slot's
                # discarded tokens into the garbage block, never into
                # its real blocks)
                eng._set_slot_meta(slot, eff, tok, row)
            self._acct_tick(t_tick, t_p, wall)
            return True
        # decode: one token for every live slot — through the sync
        # (reference) or async (double-buffered) _tick_decode variant
        return self._tick_decode(t_tick, action[1])

    def _idle(self) -> bool:
        """No scheduler action is available right now. The async loop
        may still hold an in-flight result whose arrival IS the
        remaining work (an EOS frees a slot, a quarantine requeues);
        the sync loop is simply done."""
        return False

    # ---------------------------------------------------- decode building
    # blocks shared by the sync reference and the async runtime — ONE
    # implementation of chaos injection, device-loss rebuild, sampling
    # and the commit point, so the two loops can only diverge in WHEN
    # the commit happens, never in WHAT it does
    def _chaos_hooks(self, k: int) -> None:
        """Scripted chaos at the decode-step boundary ``k``. The async
        runtime keys ``k`` on its DISPATCH counter: at injection time
        the sync loop's ``stats.decode_steps`` equals its dispatch
        count, so the same script fires at the same logical step in
        both loops."""
        eng, sched, res = self.engine, self.sched, self.res
        chaos, tracer = self.chaos, self.tracer
        if chaos is None:
            return
        chaos.maybe_preempt_serving(k)
        for p in chaos.maybe_storm(k):
            r = Request(prompt=np.asarray(p, np.int32),
                        max_new_tokens=chaos.storm_max_new_tokens,
                        eos_id=eng.eos_id,
                        rng_tag=1_000_000 + self.storm_seq)
            self.storm_seq += 1
            try:
                res.admit(sched, r)
            except ServingRejection:
                pass  # counted by the controller; outcome shed
        if eng.state is not None:
            eng.state, poisoned = chaos.maybe_poison_decode(
                k, eng.state)
            if poisoned is not None and tracer.enabled:
                tracer.event("decode_poison", step=k, slot=poisoned)

    def _rebuild_lost_state(self, k: int) -> None:
        """The slot pool died with the device. Committed tokens are
        host-side on each Request, so recovery is the quarantine-retry
        path applied to EVERY live stream: back to the queue front,
        re-prefilled onto the rebuilt pool (rng streams key on (tag,
        tokens_emitted) — continuations are unchanged). A stream whose
        committed length outgrew the prefill buckets cannot re-enter
        and is evicted (preempted). Drop the dead state FIRST: the
        quarantine path's on_slot_freed hook must see an empty pool,
        not deleted buffers."""
        eng, sched, tracer = self.engine, self.sched, self.tracer
        eng.state = None
        eng._last_tokens = None
        if eng._prefix is not None:
            # the cached blocks died with the pool: drop the trie
            # BEFORE the quarantined requests re-enter admission, or
            # their re-prefills would map stale block ids into the
            # zeroed rebuild
            eng._prefix.clear(free=True)
        # EVERY occupied slot re-enters — mid-chunk prefills included
        # (their partially-written pool rows died with the pool;
        # re-admission restarts the prefill, re-walking the trie,
        # which _ensure_state cleared alongside the pool)
        requeued = 0
        for slot, req in enumerate(list(sched.slots)):
            if req is None:
                continue
            requeued += 1
            try:
                bucket_for(req.effective_len, sched.buckets)
            except ValueError:
                sched.evict(slot, "preempted")
                continue
            sched.quarantine(slot)
        if tracer.enabled:
            tracer.event("serving_state_rebuild", step=k,
                         requeued=requeued)

    def _sample(self, live, logits, pending=None):
        """Sample every slot's next token on device and feed the result
        back as the next step's input (``_last_tokens`` — set from the
        DEVICE array, never a host copy, which is what lets the async
        runtime dispatch k+1 before k's transfer lands). Per-slot rng
        streams depend on (submission tag, tokens emitted), never on
        slot index or batch composition — built as ONE host numpy
        array, folded in-jit. ``pending``: the async runtime's
        in-flight step — a slot whose previous token is still
        uncommitted samples at count+1, the count it will have when
        that token lands (a pending token that ends up discarded —
        EOS, quarantine — discards this draw too, so the +1 can never
        desync a stream)."""
        eng, sched = self.engine, self.sched
        tag_counts = np.zeros((eng.n_slots, 2), np.int32)
        for s, r in live:
            tag_counts[s, 0] = r.rng_tag if r.rng_tag is not None \
                else r.rid
            tag_counts[s, 1] = len(r.generated)
        if pending is not None:
            for (s, r), e in zip(pending.live, pending.epochs):
                if sched.slots[s] is r and sched.slot_epoch[s] == e:
                    tag_counts[s, 1] += 1
        toks = self.sampler(logits, self.base_rng, tag_counts)
        eng._last_tokens = toks[:, None]
        return toks

    def _commit_arrival(self, live, epochs, toks_host, ok_host,
                        wall: float) -> None:
        """THE commit point: one settled decode step's bookkeeping —
        token commits (EOS/length recycling inside ``commit_token``),
        quarantine verdicts, latency/ledger stats, reqtrace stamps. The
        sync loop runs it immediately after its blocking fetch; the
        async runtime runs it at transfer ARRIVAL, one step behind
        dispatch, with ``epochs`` guarding against slots recycled while
        the result was in flight."""
        eng, sched, res = self.engine, self.sched, self.res
        stats, tracer = self.stats, self.tracer
        stats.decode_steps += 1
        self.step_no += 1
        stats.kv_bytes_read += eng._decode_kv_bytes(live)
        if self.res_active:
            res.controller.observe_step(
                wall, len(live),
                tenants=[r.tenant for _s, r in live if r.tenant])
        for i, (slot, req) in enumerate(live):
            if epochs is not None and (
                    sched.slots[slot] is not req
                    or sched.slot_epoch[slot] != epochs[i]):
                # the slot was recycled while this result was in flight
                # (EOS/length/deadline/quarantine at the previous
                # settle): the one-deep pipeline's extra draw is
                # discarded — exactly one terminal outcome per request
                continue
            if ok_host is not None and not bool(ok_host[slot]):
                # poisoned slot: quarantine it alone — the token is NOT
                # committed, neighbors proceed untouched
                eng._quarantine(sched, res, slot, req, tracer)
                continue
            stats.tokens_generated += 1
            stats.record_token(wall)
            sched.commit_token(slot, int(toks_host[slot]))
        if tracer.enabled:
            tracer.complete("decode_step", wall, step=self.step_no,
                            live_slots=len(live))

    def _tick_decode(self, t_tick: float, live) -> bool:
        """One decode step, fully synchronous — the reference
        implementation the async runtime must match stream-for-stream:
        dispatch, BLOCK on the host transfer, commit."""
        from .resilience import DecodeStateLostError

        eng, res = self.engine, self.res
        k = self.stats.decode_steps  # the chaos-script step index
        self._chaos_hooks(k)
        t_d = time.perf_counter()
        try:
            logits, ok_vec = eng._dispatch_decode(
                self.params, res, self.chaos, k, self.guard, self.tracer)
        except DecodeStateLostError:
            self._rebuild_lost_state(k)
            self._acct_tick(t_tick, t_d, 0.0)
            return True
        toks = self._sample(live, logits)
        toks_host, ok_host = self._fetch(toks, ok_vec)
        wall = time.perf_counter() - t_d
        self._commit_arrival(live, None, toks_host, ok_host, wall)
        self._acct_tick(t_tick, t_d, wall)
        return True

    # --------------------------------------------------------------- finish
    def finish(self, ledger_drained: bool = True) -> ServingStats:
        """Close the run exactly once: drain handoff, the outcome ledger
        (every request that entered the system leaves under exactly one
        outcome), telemetry.

        ``ledger_drained`` (ISSUE 20 bugfix): the drain handoff used to
        hand ``engine.drained_requests`` back with only ``outcome``
        stamped — no reqtrace terminal — so a drained rid's timeline
        stayed open forever across a drain followed by a crash. The
        standalone engine path (default True) closes those timelines
        as ``preempted`` here; the requests themselves stay clean for
        re-submission elsewhere. The FLEET passes False: its requeue
        branch clears ``outcome`` and re-admits the request, and
        reqtrace's first-terminal-wins would otherwise pin a premature
        "preempted" on a stream that goes on to finish "ok" — the fleet
        ledgers (and journals) its own drain handoffs at ITS terminal
        instead."""
        eng, sched, res = self.engine, self.sched, self.res
        stats, tracer = self.stats, self.tracer
        if self.finished:
            return stats
        self.finished = True
        if self.draining:
            eng.drained_requests = sched.pop_queued()
            if ledger_drained and sched.rt.enabled:
                for r in eng.drained_requests:
                    sched.rt.finish(r.rid, float(sched.clock()),
                                    "preempted", reason="drain",
                                    new_tokens=len(r.generated),
                                    replica=sched.replica_idx)
            if tracer.enabled:
                tracer.event("serving_drain_done",
                             returned=len(eng.drained_requests),
                             finished=len(sched.finished))
        stats.wall_s = time.perf_counter() - self.t0
        # clean (outcome ok) completions only — evicted/failed requests
        # are accounted in the outcome ledger below, not as "served"
        stats.requests_served = sum(
            1 for r in sched.finished if (r.outcome or "ok") == "ok")
        stats.queue_depth_hwm = sched.queue_depth_hwm
        # outcome ledger: every request that entered the system leaves
        # under exactly one outcome
        for r in sched.finished:
            stats.count_outcome(r.outcome or "ok")
        stats.count_outcome("shed", res.sheds)
        stats.count_outcome("preempted", len(eng.drained_requests))
        stats.sheds = res.sheds
        stats.deadline_misses = res.deadline_misses
        stats.quarantines = res.quarantines
        stats.decode_retries = res.decode_retries
        stats.drains = res.drains
        stats.replans = res.replans
        stats.drained_returned = len(eng.drained_requests)
        # prefix-cache ledger (ISSUE 14): deltas vs the loop-start
        # snapshots — the trie and a caller-reused scheduler persist
        stats.prefix_hits = sched.prefix_hits - self._prefix_hits0
        stats.prefix_tokens_reused = \
            sched.prefix_tokens_reused - self._prefix_reused0
        if eng._prefix is not None:
            stats.cache_evictions = \
                eng._prefix.evictions - self._evictions0
        # per-shard-chip KV residency (ISSUE 18): mean per-step occupied
        # KV bytes / seq_shards — each shard chip holds one contiguous
        # 1/seq_shards run of every slot's blocks, so the measured-fill
        # pool bytes divide evenly across the seq mesh axis
        if stats.decode_steps and stats.kv_bytes_read:
            stats.kv_hbm_per_chip_bytes = int(
                stats.kv_bytes_read / stats.decode_steps
                / max(eng.seq_shards, 1))
        if self.publish_telemetry:
            eng._merge_telemetry(sched, stats)
            if tracer.enabled and eng.model.config.trace_file:
                tracer.write(eng.model.config.trace_file)
        return stats


@dataclasses.dataclass
class _PendingStep:
    """One in-flight decode step of the async runtime (ISSUE 17): the
    device arrays whose host transfer is pending, plus everything the
    commit needs when the result lands. ``epochs`` snapshots the slot
    incarnation counters at DISPATCH time — a slot recycled while the
    result was in flight discards its entry at settle (the one-deep
    pipeline's extra draw), identity checked per (slot, request,
    epoch)."""

    toks: Any
    ok_vec: Any
    live: List
    epochs: List[int]
    t_d: float


class _AsyncServeLoop(_ServeLoop):
    """The double-buffered serve loop behind ``--serve-loop async``
    (ISSUE 17, docs/serving.md "Async runtime"): decode step k+1 is
    dispatched on-device while step k's ``(tokens, ok_vec)`` transfer
    is still in flight, and ALL commit-point bookkeeping — token
    commits, EOS/length recycling, quarantine verdicts, reqtrace
    stamps — fires at transfer ARRIVAL, one step behind dispatch,
    overlapped with step k+1's device execution. The host Python loop
    leaves the decode critical path: the only blocking host sync per
    committed step is the settle's fetch (``stats.host_syncs`` pins
    it).

    What makes the one-deep pipeline safe:

    * the decode feedback token is read from the DEVICE array
      (``_last_tokens = toks[:, None]`` in ``_sample``) — dispatch k+1
      never needs k's host copy;
    * per-slot rng streams key on (tag, tokens_emitted), with pending
      in-flight tokens counted (+1), so sampled streams are bitwise
      the sync loop's regardless of commit lag;
    * the extra in-flight step a finishing/quarantined slot runs
      writes only at positions >= the adopted prefix extent of blocks
      released at settle, and every released block is fully
      re-prefilled (data-dependency ordered through the donated state)
      before any read — the standing overwrite-before-read invariant;
    * slot-epoch guards discard in-flight results for recycled slots
      (``ContinuousBatchScheduler.slot_epoch``).

    Drain points — everything that must observe settled state calls
    ``settle()`` first: ``finish()``, the drain-grace eviction, the
    idle transition, the DecodeStateLost rebuild, and the fleet's
    harvest/kill/migration paths (serving/fleet.py)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pending: Optional[_PendingStep] = None
        # chaos scripts key on DISPATCH order: at injection time the
        # sync loop's stats.decode_steps equals its dispatch count, so
        # a dispatch counter reproduces the exact injection points
        # (stats.decode_steps lags one settle behind here)
        self.dispatch_no = 0

    # ---------------------------------------------------------- settling
    def _settle_step(self, p: _PendingStep) -> float:
        """Block until ``p``'s transfer lands, then run the commit
        point. Returns the seconds actually spent BLOCKED (the only
        part of the settle that is device wait, not host work)."""
        t_s = time.perf_counter()
        toks_host, ok_host = self._fetch(p.toks, p.ok_vec)
        blocked = time.perf_counter() - t_s
        self.stats.host_device_s += blocked
        wall = time.perf_counter() - p.t_d
        self._commit_arrival(p.live, p.epochs, toks_host, ok_host, wall)
        return blocked

    def _settle_pending(self) -> None:
        """The explicit drain point (``settle()``): force the in-flight
        step to arrive and commit. Outside the decode hot path nothing
        overlaps the commit work, so it lands in the bookkeep bucket."""
        p, self._pending = self._pending, None
        if p is None:
            return
        t0 = time.perf_counter()
        blocked = self._settle_step(p)
        self.stats.host_bookkeep_s += max(
            time.perf_counter() - t0 - blocked, 0.0)

    def _idle(self) -> bool:
        if self._pending is None:
            return False
        # the in-flight step IS the remaining work: its arrival commits
        # tokens, frees slots, possibly requeues a quarantined stream —
        # the next tick sees a live scheduler again
        self._settle_pending()
        return True

    # ------------------------------------------------------------- decode
    def _tick_decode(self, t_tick: float, live) -> bool:
        """One double-buffered decode step: dispatch k+1 FIRST (device
        starts immediately), then settle k's pending transfer and do
        its commit bookkeeping while k+1 executes. Steady state: one
        blocking host sync (the settle fetch) per committed step."""
        from .resilience import DecodeStateLostError

        eng, res, stats = self.engine, self.res, self.stats
        # with a step already in flight the device stays busy through
        # this tick's prework — host work only hits the critical path
        # when the pipeline is empty (first step of a burst)
        pipelined = self._pending is not None
        k = self.dispatch_no  # chaos keys on dispatch order
        self._chaos_hooks(k)
        t_d = time.perf_counter()
        try:
            logits, ok_vec = eng._dispatch_decode(
                self.params, res, self.chaos, k, self.guard, self.tracer)
        except DecodeStateLostError:
            # settle FIRST: at this logical point the sync loop had
            # already committed step k-1's tokens — the rebuild's
            # re-prefills must resume from the same committed streams.
            # A scripted loss leaves the pending buffers alive; a real
            # loss that killed them too loses that step's tokens (the
            # requests re-prefill one token earlier — still a valid
            # stream position)
            try:
                self._settle_pending()
            except Exception:
                self._pending = None  # buffers died with the device
            self._rebuild_lost_state(k)
            stats.host_dispatch_s += max(t_d - t_tick, 0.0)
            stats.host_ticks += 1
            return True
        issued = time.perf_counter()
        if pipelined:
            stats.host_overlap_s += max(issued - t_tick, 0.0)
        else:
            stats.host_dispatch_s += max(issued - t_tick, 0.0)
        # the device is busy with step k from here on: the sampler
        # dispatch, the early transfer start and the PREVIOUS step's
        # entire commit bookkeeping all overlap its execution — that is
        # the double buffer. Only the settle's blocking fetch counts as
        # device wait
        toks = self._sample(live, logits, pending=self._pending)
        ok_arr = (ok_vec,) if ok_vec is not None else ()
        for arr in (toks,) + ok_arr:
            try:
                arr.copy_to_host_async()  # start D2H behind the compute
            except (AttributeError, TypeError):
                pass  # backend without async host copies: settle blocks
        prev, self._pending = self._pending, _PendingStep(
            toks=toks, ok_vec=ok_vec, live=list(live),
            epochs=[self.sched.slot_epoch[s] for s, _ in live], t_d=t_d)
        self.dispatch_no += 1
        blocked = self._settle_step(prev) if prev is not None else 0.0
        stats.host_overlap_s += max(
            time.perf_counter() - issued - blocked, 0.0)
        stats.host_ticks += 1
        return True

    # ------------------------------------------------------------- finish
    def finish(self, ledger_drained: bool = True) -> ServingStats:
        self._settle_pending()
        return super().finish(ledger_drained=ledger_drained)
