"""Multi-tenant SLO tiers and the weighted fair queue at the fleet door.

ISSUE 19 (docs/multitenant.md): every request carries a ``tenant`` label
and the fleet door schedules across per-tenant backlogs with virtual
finish times instead of a single FIFO.  Three built-in tiers —
``interactive`` / ``standard`` / ``batch`` — differ in WFQ weight, shed
priority, per-tier deadline default, and token-rate quota.  The spec
string accepted by ``--tenant-tiers`` overrides or extends the registry:

    NAME:WEIGHT[:DEADLINE_MS[:QUOTA_TOKENS_PER_S]][,NAME:...]

Scheduling law: the queue is deterministic in the submission sequence —
virtual clocks advance only on append/popleft, never from wall time — so
replaying the same submissions yields the same service order, and under
exact decode every stream is bitwise-identical whether co-scheduled with
other tenants or run solo (tier-1 pins both properties).
"""
from __future__ import annotations

import bisect
from collections import deque
from dataclasses import dataclass, replace
from typing import Deque, Dict, Iterator, List, Optional, Tuple

from .scheduler import Request, ServingRejection

# canonical tier names; unknown tenants inherit standard's parameters
# (but keep their own WFQ backlog and accounting rows)
TENANT_TIERS = ("interactive", "standard", "batch")


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tier scheduling parameters enforced at the fleet door."""
    name: str
    # WFQ weight: tokens of service per unit of virtual time.  Higher
    # weight -> earlier virtual finish -> served ahead of heavier
    # backlogs from lighter tenants.
    weight: float = 4.0
    # tier deadline default (ms), applied when the request carries none;
    # 0 = no tier default (config.request_timeout_ms still applies)
    deadline_ms: float = 0.0
    # token-rate quota (tokens/s, burst = 1 s worth); 0 = unlimited
    quota_tokens_per_s: float = 0.0
    # who sheds first under queue pressure: 0 = first, higher = later
    shed_priority: int = 1


_DEFAULT_POLICIES: Dict[str, TenantPolicy] = {
    "interactive": TenantPolicy("interactive", weight=8.0, shed_priority=2),
    "standard": TenantPolicy("standard", weight=4.0, shed_priority=1),
    "batch": TenantPolicy("batch", weight=1.0, shed_priority=0),
}


class QuotaExceededError(ServingRejection):
    """Tenant token-rate quota exhausted; ledgered as ``quota_exceeded``."""


def parse_tenant_tiers(spec: str) -> Dict[str, TenantPolicy]:
    """Parse a ``--tenant-tiers`` spec into a policy dict (fail fast)."""
    out: Dict[str, TenantPolicy] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2 or len(parts) > 4:
            raise ValueError(
                "--tenant-tiers entries must be "
                "NAME:WEIGHT[:DEADLINE_MS[:QUOTA_TOKENS_PER_S]], got "
                f"{entry!r}")
        name = parts[0].strip()
        if not name:
            raise ValueError(f"--tenant-tiers entry has empty name: {entry!r}")
        if name in out:
            raise ValueError(f"--tenant-tiers names {name!r} twice")
        try:
            weight = float(parts[1])
            deadline = float(parts[2]) if len(parts) > 2 else 0.0
            quota = float(parts[3]) if len(parts) > 3 else 0.0
        except ValueError:
            raise ValueError(
                f"--tenant-tiers entry {entry!r}: WEIGHT/DEADLINE_MS/"
                "QUOTA_TOKENS_PER_S must be numeric")
        if weight <= 0:
            raise ValueError(
                f"--tenant-tiers entry {entry!r}: WEIGHT must be > 0")
        if deadline < 0 or quota < 0:
            raise ValueError(
                f"--tenant-tiers entry {entry!r}: DEADLINE_MS and "
                "QUOTA_TOKENS_PER_S must be >= 0")
        base = _DEFAULT_POLICIES.get(name)
        out[name] = TenantPolicy(
            name, weight=weight, deadline_ms=deadline,
            quota_tokens_per_s=quota,
            shed_priority=base.shed_priority if base else 1)
    return out


class TenantRegistry:
    """Policy lookup + token-bucket quota accounting per tenant."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None):
        self.policies: Dict[str, TenantPolicy] = dict(_DEFAULT_POLICIES)
        if policies:
            self.policies.update(policies)
        # tenant -> (allowance_tokens, last_refill_ms)
        self._buckets: Dict[str, Tuple[float, float]] = {}

    @classmethod
    def from_config(cls, config) -> "TenantRegistry":
        spec = getattr(config, "tenant_tiers", "") or ""
        return cls(parse_tenant_tiers(spec) if spec else None)

    def policy(self, tenant: Optional[str]) -> TenantPolicy:
        name = tenant or "standard"
        pol = self.policies.get(name)
        if pol is None:
            # unknown tenants get standard's parameters under their own
            # name so WFQ backlogs and ledgers stay per-tenant
            pol = replace(self.policies["standard"], name=name)
        return pol

    def max_shed_priority(self) -> int:
        return max((p.shed_priority for p in self.policies.values()),
                   default=1)

    def charge(self, tenant: Optional[str], tokens: int,
               now_ms: float) -> Tuple[bool, float]:
        """Debit ``tokens`` from the tenant's bucket.

        Returns ``(ok, retry_after_ms)`` — retry_after_ms is how long
        until the bucket refills enough, 0 when the charge succeeded or
        the tenant has no quota.
        """
        pol = self.policy(tenant)
        rate = float(pol.quota_tokens_per_s)
        if rate <= 0:
            return True, 0.0
        burst = rate  # 1 s worth
        allowance, last = self._buckets.get(pol.name, (burst, now_ms))
        allowance = min(burst, allowance + rate * max(now_ms - last, 0.0) / 1e3)
        if allowance >= tokens:
            self._buckets[pol.name] = (allowance - tokens, now_ms)
            return True, 0.0
        self._buckets[pol.name] = (allowance, now_ms)
        return False, (tokens - allowance) / rate * 1e3


class WeightedFairQueue:
    """Virtual-finish-time fair queue over per-tenant backlogs.

    Service order: a request's virtual finish time is
    ``max(vclock, last_vft[tenant]) + max_new_tokens / weight``; the
    queue pops ascending VFT with submission sequence as tie-break, and
    the virtual clock advances to each popped VFT.  Single-tenant
    traffic therefore degenerates to exact FIFO, and a saturating
    low-weight tenant can displace a fresh high-weight request by at
    most one quantum (its own in-progress entry) — the no-starvation
    property tier-1 pins.

    ``appendleft`` feeds a rescue lane served before the fair queue:
    migration re-queues use it so harvested in-flight work stays ahead
    of queued work (PR 11 ordering), bypassing VFT accounting.

    The API is deque-compatible (append/appendleft/extend/popleft/
    len/iter/clear/delitem) so existing fleet code and tests that poke
    ``fleet.queue`` keep working.
    """

    def __init__(self, registry: Optional[TenantRegistry] = None):
        self.registry = registry or TenantRegistry()
        self._rescue: Deque[Request] = deque()
        self._order: List[Tuple[float, int, Request]] = []
        self._seq = 0
        self._vclock = 0.0
        self._last_vft: Dict[str, float] = {}

    def _vft(self, req: Request) -> float:
        pol = self.registry.policy(getattr(req, "tenant", None))
        cost = max(int(req.max_new_tokens), 1) / max(pol.weight, 1e-9)
        return max(self._vclock, self._last_vft.get(pol.name, 0.0)) + cost

    def append(self, req: Request) -> None:
        pol = self.registry.policy(getattr(req, "tenant", None))
        vft = self._vft(req)
        self._last_vft[pol.name] = vft
        bisect.insort(self._order, (vft, self._seq, req))
        self._seq += 1

    def appendleft(self, req: Request) -> None:
        self._rescue.appendleft(req)

    def extend(self, reqs) -> None:
        for r in reqs:
            self.append(r)

    def popleft(self) -> Request:
        if self._rescue:
            return self._rescue.popleft()
        if not self._order:
            raise IndexError("pop from an empty WeightedFairQueue")
        vft, _seq, req = self._order.pop(0)
        self._vclock = max(self._vclock, vft)
        return req

    def clear(self) -> None:
        self._rescue.clear()
        self._order.clear()

    def __len__(self) -> int:
        return len(self._rescue) + len(self._order)

    def __bool__(self) -> bool:
        return bool(self._rescue) or bool(self._order)

    def __iter__(self) -> Iterator[Request]:
        # iteration order == service order (rescue lane first), so
        # remove_by_identity() indexes line up with __delitem__
        yield from self._rescue
        for _vft, _seq, req in self._order:
            yield req

    def __delitem__(self, i: int) -> None:
        if i < len(self._rescue):
            del self._rescue[i]
        else:
            del self._order[i - len(self._rescue)]

    def queued_by_tenant(self) -> Dict[str, int]:
        """Door depth per explicit tenant (untenanted requests omitted)."""
        out: Dict[str, int] = {}
        for req in self:
            t = getattr(req, "tenant", None)
            if t:
                out[t] = out.get(t, 0) + 1
        return out

    def backlog_tokens_ahead(self, tenant: Optional[str]) -> int:
        """Tokens scheduled before a hypothetical new ``tenant`` request.

        Prices the rejected tenant's own virtual queue position: the
        rescue lane plus every queued entry whose VFT sorts at or before
        the virtual start a new request of this tenant would receive.
        """
        pol = self.registry.policy(tenant)
        start = max(self._vclock, self._last_vft.get(pol.name, 0.0))
        # a one-token probe request of this tenant would finish at:
        probe_vft = start + 1.0 / max(pol.weight, 1e-9)
        ahead = sum(max(int(r.max_new_tokens), 1) for r in self._rescue)
        for vft, _seq, req in self._order:
            if vft <= probe_vft:
                ahead += max(int(req.max_new_tokens), 1)
        return ahead
