"""Serving under fire: deadlines, load shedding, decode-health quarantine,
graceful drain and device-loss failover for the serving engine (ISSUE 9).

The PR 6 engine was happy-path only: its one overload behavior was the
bounded-queue ``QueueFullError``, a non-finite logit poisoned every
co-batched stream, and SIGTERM mid-serve dropped all in-flight requests —
while the *training* loop already had atomic checkpoints, divergence
sentinels and chaos coverage (PRs 4–5). This module is the serving-side
counterpart, reusing that machinery at the Orca-style iteration-level
scheduler's natural enforcement point (every admission and every decode
iteration is a decision):

* **deadlines** — ``Request.deadline_ms`` (default from
  ``--request-timeout-ms``), enforced at admission and at every decode
  iteration; expired requests are evicted with outcome
  ``deadline_exceeded`` and their slot recycled.
* **admission control / load shedding** — :class:`AdmissionController`
  keeps an EWMA of per-token decode cost; queue depth times that cost
  yields an estimated completion time, and :meth:`ServingResilience.admit`
  sheds (typed :class:`OverloadError` with a ``retry_after_ms`` hint) per
  ``--shed-policy``:

  - ``off``      — never shed (the bounded queue remains the only wall);
  - ``deadline`` — shed when the completion estimate blows the request's
    deadline (a request that cannot meet its SLO wastes capacity better
    spent on ones that can);
  - ``queue``    — shed once queue depth reaches the high-water mark
    ``max_queue // 2`` (early backpressure before the hard
    ``QueueFullError`` wall), regardless of deadlines.

* **decode-health quarantine** — the guarded decode step
  (``Executor.make_decode_step(guard=True)``, mirroring PR 4's guarded
  train step) returns a per-slot ``isfinite`` verdict on the decode
  logits for ONE extra bool-vector transfer; a poisoned slot is
  quarantined alone (co-batched streams continue bit-identically), its
  request retried once per ``--decode-retry-budget`` on a fresh slot by
  re-prefilling prompt + committed tokens, and repeated poisoning aborts
  the request with outcome ``decode_fault``.
* **graceful drain** — ``ServingEngine.serve`` installs the flag-only
  SIGTERM/SIGINT handler from ``resilience/session.py``; on preemption
  admission stops, in-flight requests finish within ``--drain-grace-s``
  (stragglers are evicted as ``preempted``), and still-queued requests
  are handed back for re-submission to another replica.
* **device-loss failover** — a decode dispatch that dies with a
  device-loss-shaped error (or a scripted ``ChaosPlan.drop_devices_at``)
  triggers the existing ``elastic_replan`` automatically, with bounded
  backoff, and the in-flight ``DecodeState`` survives the hop.

Every path is exercised deterministically in tier-1 via the ``ChaosPlan``
serving extensions (``poison_decode_at`` / ``storm_queue`` /
``preempt_serving_at`` / ``drop_devices_at``) —
tests/test_serving_resilience.py.
"""
from __future__ import annotations

from typing import Callable, Optional

from .scheduler import (ContinuousBatchScheduler, Request,
                        ServingRejection, now_ms)

#: terminal request dispositions — every request that enters the system
#: leaves it under exactly one of these (asserted end-to-end in tier-1).
#: The write-ahead request journal (serving/journal.py) persists exactly
#: these strings in its ``outcome`` records; recovery replay relies on
#: any journaled ``o`` field being a member of this tuple.
OUTCOMES = ("ok", "deadline_exceeded", "shed", "quota_exceeded",
            "decode_fault", "preempted")

SHED_POLICIES = ("off", "deadline", "queue")


class OverloadError(ServingRejection):
    """Admission shed by the load controller (``--shed-policy``): the
    estimated completion time blows the request's deadline, or the queue
    crossed its high-water mark. Carries the same ``queued``/``active``/
    ``retry_after_ms`` fields as ``QueueFullError`` via the shared
    ``ServingRejection`` base — one except clause handles both."""


class DeviceLossError(RuntimeError):
    """A decode dispatch lost (some of) its devices. Raised by the chaos
    hook (``ChaosPlan.drop_devices_at``) and synthesized from real
    device-loss-shaped runtime errors; the engine answers with an
    automatic ``elastic_replan`` onto the survivors."""

    def __init__(self, n_dev: int, message: str = ""):
        super().__init__(message or f"device loss: {n_dev} device(s) "
                         "surviving")
        self.n_dev = int(n_dev)


# substrings (lowercased) that mark a runtime error as device loss rather
# than a program bug — the conservative detector behind the auto-replan
_DEVICE_LOSS_MARKERS = ("device_unavailable", "device unavailable",
                       "failed_precondition: device",
                       "tpu is unhealthy", "device is lost",
                       "chip unreachable", "slice has been terminated")


def looks_like_device_loss(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return isinstance(exc, DeviceLossError) or \
        any(m in msg for m in _DEVICE_LOSS_MARKERS)


class DecodeStateLostError(RuntimeError):
    """The in-flight DecodeState did not survive a device-loss error —
    its buffers were donated to the failed dispatch or resident on the
    lost chips. The serve loop answers by rebuilding the slot pool and
    re-prefilling every live stream from its host-side committed tokens
    (``Request.current_prompt``), so generation still resumes exactly
    where it stopped."""


def state_buffers_lost(*trees) -> bool:
    """True when any jax array leaf in ``trees`` has been invalidated
    (deleted by donation to a dispatch that failed, or lost with its
    device) — retrying a decode with such a leaf raises an opaque
    'Array has been deleted' instead of resuming."""
    import jax

    for tree in trees:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            deleted = getattr(leaf, "is_deleted", None)
            if callable(deleted) and deleted():
                return True
    return False


class AdmissionController:
    """EWMA cost model behind load shedding.

    ``observe_step`` feeds each decode iteration's wall time and the
    number of live slots it advanced; the controller keeps an
    exponentially-weighted moving average of the per-token decode cost
    (ms). The completion estimate for a new request is then

        est_ms = token_cost_ms * (backlog_tokens / n_slots
                                  + max_new_tokens)

    where ``backlog_tokens`` counts the remaining tokens of every
    IN-FLIGHT slot as well as every queued request — a saturated slot
    pool delays a new request's first token exactly like a deep queue
    does. The backlog drains at ``n_slots`` tokens per step while the
    request itself needs ``max_new_tokens`` more steps once admitted
    (iteration-level batching: a step costs one token-time regardless of
    occupancy).
    ``retry_after_ms`` is the backlog-drain half of that estimate, the
    hint a shed caller should wait before resubmitting.

    The controller lives on the ENGINE (not per serve() run) so the cost
    model warms across runs; ``force_token_cost_ms`` pins the cost for
    deterministic tests and scripted capacity planning.
    """

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self._ewma_token_ms: Optional[float] = None
        self.observed_steps = 0
        self.force_token_cost_ms: Optional[float] = None
        # speculative decoding (ISSUE 12, serving/speculative.py): the
        # per-token cost EWMA already absorbs speculation honestly —
        # verification rounds report (wall, tokens COMMITTED) through
        # observe_step — this additionally tracks the acceptance-rate
        # EWMA for introspection/telemetry (None until speculation runs)
        self.spec_acceptance: Optional[float] = None
        # per-tenant token-cost EWMAs (ISSUE 19): same alpha, fed only
        # on steps where the tenant held a live slot — a tenant's cost
        # diverges from the aggregate through WHICH steps it rides
        self._tenant_ewma_ms = {}

    def observe_speculation(self, accepted: int, proposed: int) -> None:
        """Feed one verification round's (accepted, proposed) draft
        counts; keeps a same-alpha EWMA of the acceptance rate. The COST
        side of speculation needs no special casing — callers report
        committed tokens per round wall via :meth:`observe_step`, so the
        per-token EWMA reprices itself."""
        if proposed <= 0:
            return
        rate = accepted / proposed
        if self.spec_acceptance is None:
            self.spec_acceptance = rate
        else:
            self.spec_acceptance += self.alpha * (rate -
                                                  self.spec_acceptance)

    @property
    def token_cost_ms(self) -> float:
        if self.force_token_cost_ms is not None:
            return float(self.force_token_cost_ms)
        return self._ewma_token_ms or 0.0

    def token_cost_ms_for(self, tenant: Optional[str]) -> float:
        """Per-tenant cost when that tenant's EWMA has warmed, else the
        aggregate — untenanted callers get exactly :attr:`token_cost_ms`."""
        if self.force_token_cost_ms is not None:
            return float(self.force_token_cost_ms)
        if tenant is not None:
            v = self._tenant_ewma_ms.get(tenant)
            if v is not None:
                return v
        return self._ewma_token_ms or 0.0

    def observe_step(self, wall_s: float, tokens: int,
                     tenants=None) -> None:
        cost = wall_s * 1e3 / max(int(tokens), 1)
        if self._ewma_token_ms is None:
            self._ewma_token_ms = cost
        else:
            self._ewma_token_ms += self.alpha * (cost - self._ewma_token_ms)
        self.observed_steps += 1
        for t in set(tenants or ()):
            prev = self._tenant_ewma_ms.get(t)
            self._tenant_ewma_ms[t] = cost if prev is None else \
                prev + self.alpha * (cost - prev)

    def warm_start(self, other: "AdmissionController") -> None:
        """Adopt ``other``'s warm cost model iff this controller is cold.

        Replans, pool rebuilds, and autoscale scale-ups hand traffic to
        a fresh controller; without the carry the first post-recovery
        shedding window prices everything at cost 0 (admit-everything)
        until the EWMA re-warms. Never copies ``force_token_cost_ms`` —
        a test pin stays local to the controller it was set on.
        """
        if other is self or other is None:
            return
        if self.observed_steps > 0 or self._ewma_token_ms is not None:
            return  # already warm: keep the fresher local estimate
        self._ewma_token_ms = other._ewma_token_ms
        self.observed_steps = other.observed_steps
        if self.spec_acceptance is None:
            self.spec_acceptance = other.spec_acceptance
        self._tenant_ewma_ms.update(other._tenant_ewma_ms)

    # ------------------------------------------------------------ estimates
    @staticmethod
    def _backlog_tokens(sched: ContinuousBatchScheduler) -> int:
        """Remaining tokens ahead of a NEW request: queued requests plus
        the in-flight slots' unfinished work — omitting the latter would
        under-shed exactly when the slot pool is saturated."""
        queued = sum(r.max_new_tokens - len(r.generated)
                     for r in sched.queue)
        inflight = sum(r.max_new_tokens - len(r.generated)
                       for r in sched.slots if r is not None)
        return queued + inflight

    def estimate_completion_ms(self, req: Request,
                               sched: ContinuousBatchScheduler) -> float:
        backlog = self._backlog_tokens(sched)
        return self.token_cost_ms * (backlog / max(sched.n_slots, 1)
                                     + req.max_new_tokens)

    def retry_after_ms(self, sched: ContinuousBatchScheduler) -> float:
        return self.token_cost_ms * (self._backlog_tokens(sched)
                                     / max(sched.n_slots, 1))


class ServingResilience:
    """Per-serve()-run resilience policy + counters.

    Owns the knobs (``--request-timeout-ms`` / ``--shed-policy`` /
    ``--drain-grace-s`` / ``--decode-retry-budget``), the shared
    :class:`AdmissionController`, the clock every deadline decision reads
    (injectable for deterministic tests — one time base for submit stamps,
    sweeps and drain grace), and the event counters the engine merges into
    ``ServingStats`` / the ``StepTelemetry`` ``serving_resilience`` block.
    """

    def __init__(self, config, chaos=None,
                 controller: Optional[AdmissionController] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.chaos = chaos
        self.request_timeout_ms = float(
            getattr(config, "request_timeout_ms", 0.0) or 0.0)
        self.shed_policy = (getattr(config, "shed_policy", "off")
                            or "off")
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy must be one of {SHED_POLICIES}, got "
                f"{self.shed_policy!r}")
        self.drain_grace_s = float(
            getattr(config, "drain_grace_s", 5.0))
        self.decode_retry_budget = int(
            getattr(config, "decode_retry_budget", 1))
        self.controller = controller or AdmissionController()
        self.clock = clock if clock is not None else now_ms
        # counters (merged into ServingStats / telemetry by the engine)
        self.sheds = 0
        self.deadline_misses = 0
        self.quarantines = 0
        self.decode_retries = 0
        self.decode_faults = 0
        self.drains = 0
        self.replans = 0
        # failover bounds: a replan that keeps failing must not loop
        # forever — bounded linear backoff, then the error propagates
        self.max_replan_attempts = 3
        self.replan_backoff_s = 0.5
        self._saw_deadline = False
        # fleet hook (ISSUE 11): the router arms the guarded decode on
        # every replica it health-checks — a scripted degrade poisons a
        # replica's DecodeState directly (no per-replica ChaosPlan), so
        # the quarantine verdict must be live even when nothing else is
        self.force_armed = False

    @property
    def armed(self) -> bool:
        """Any serving-resilience feature active? The plain serve loop
        pays zero extra cost (no guarded decode, no per-iteration sweeps)
        when this is False — mirroring ``ResilienceSession.wanted``. A
        caller-set ``Request.deadline_ms`` arms it even with every config
        knob at its default (``deadlines_armed`` tracks the stamps)."""
        return bool(self.chaos is not None or self.shed_policy != "off"
                    or self.deadlines_armed or self.force_armed)

    # -------------------------------------------------------------- deadline
    @property
    def deadlines_armed(self) -> bool:
        return self.request_timeout_ms > 0 or self._saw_deadline

    def stamp_deadline(self, req: Request) -> None:
        """Default a request's deadline from --request-timeout-ms; a
        caller-set ``deadline_ms`` wins."""
        if req.deadline_ms is None and self.request_timeout_ms > 0:
            req.deadline_ms = self.request_timeout_ms
        if req.deadline_ms is not None:
            self._saw_deadline = True

    # ------------------------------------------------------------- admission
    def admit(self, sched: ContinuousBatchScheduler, req: Request) -> None:
        """Deadline stamp + shed-policy gate + scheduler submit. Raises
        :class:`OverloadError` (shed) or ``QueueFullError`` (hard wall);
        both are ``ServingRejection`` and both are counted here as
        outcome ``shed`` — a rejected request never enters the queue but
        still leaves the system under exactly one outcome."""
        self.stamp_deadline(req)
        policy = self.shed_policy
        if policy == "queue":
            highwater = max(sched.max_queue // 2, 1)
            if sched.queued >= highwater:
                self.sheds += 1
                req.outcome = "shed"
                if sched.rt.enabled:
                    # record the decision WITH what priced it: queue
                    # depth vs the high-water mark (ISSUE 16)
                    sched.rt.finish(req.rid, float(self.clock()),
                                    "shed", policy="queue",
                                    queued=sched.queued,
                                    highwater=highwater,
                                    replica=sched.replica_idx)
                raise OverloadError(
                    f"request {req.rid} shed (policy 'queue'): queue depth "
                    f"{sched.queued} >= high-water {highwater} "
                    f"(max_queue {sched.max_queue})",
                    queued=sched.queued, active=sched.active,
                    retry_after_ms=self.controller.retry_after_ms(sched))
        elif policy == "deadline" and req.deadline_ms is not None \
                and req.deadline_ms > 0:
            est = self.controller.estimate_completion_ms(req, sched)
            if est > req.deadline_ms:
                self.sheds += 1
                req.outcome = "shed"
                if sched.rt.enabled:
                    # the priced estimate that MADE the decision rides
                    # on the terminal record (ISSUE 16)
                    sched.rt.finish(req.rid, float(self.clock()),
                                    "shed", policy="deadline",
                                    est_ms=round(est, 3),
                                    deadline_ms=req.deadline_ms,
                                    replica=sched.replica_idx)
                raise OverloadError(
                    f"request {req.rid} shed (policy 'deadline'): "
                    f"estimated completion {est:.1f} ms exceeds deadline "
                    f"{req.deadline_ms:.1f} ms",
                    queued=sched.queued, active=sched.active,
                    retry_after_ms=self.controller.retry_after_ms(sched))
        try:
            sched.submit(req)
        except ServingRejection:
            # the hard walls shed too (policy 'off' has no earlier gate;
            # ISSUE 12 adds the max-context ContextOverflowError): the
            # rejection still lands in the ledger under exactly one
            # outcome instead of vanishing from the accounting
            self.sheds += 1
            req.outcome = "shed"
            if sched.rt.enabled:
                sched.rt.finish(req.rid, float(self.clock()), "shed",
                                policy="hard_wall",
                                queued=sched.queued,
                                replica=sched.replica_idx)
            raise
