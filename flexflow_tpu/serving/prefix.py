"""Radix-tree prefix cache over the paged KV pool (ISSUE 14).

The PR 12 paged refactor left this as a promissory note — "slot
recycling and (future) prefix sharing are pointer bookkeeping" — and
this module cashes it: the SGLang RadixAttention idea (a token trie
whose nodes own cache state) married to the vLLM PagedAttention sharing
unit (fixed-size pool blocks with refcounts).

Design
------
* **Nodes are blocks.** Each :class:`PrefixNode` owns exactly one pool
  block id and the ``<= block_size`` token ids whose KV rows that block
  holds. Children hang only under FULL nodes (``block_size`` tokens) —
  a partial node is by construction a leaf (its block still has empty
  row slots, so nothing can continue "after" it in the pool layout).
* **Matching is block-greedy with a partial tail.** The walk descends
  fully-matched full nodes and takes longest-common-prefix credit on
  the last (possibly partial, possibly divergent) node. A match shorter
  than one full block returns a miss: sub-block sharing cannot beat the
  copy-on-write clone it would force, and the floor keeps short-prompt
  workloads byte-for-byte on the classic path.
* **Refcounts, not copies.** A matched block is mapped straight into
  the admitted slot's block table; the
  :class:`~.scheduler.BlockAllocator` refcount grows by one per mapper
  (the trie itself holds one reference per node). Sharers never write a
  shared block — a hit whose boundary falls inside a block schedules a
  **copy-on-write clone** (``Request.pending_cow``; the engine's tiny
  donated jit, exactly like ``_clear_slot_tables``) before the first
  divergent write.
* **Insertion at the release choke point.** A fully-prefilled request's
  prompt blocks are adopted on its way out through
  ``ContinuousBatchScheduler._release_blocks`` (full blocks also
  eagerly at prefill completion, so same-batch admissions already hit);
  quarantine/decode-fault releases skip adoption — poison-suspect KV
  must never enter the cache.
* **LRU eviction under pressure.** When an admission cannot get fresh
  blocks, leaf nodes no live request references (allocator refcount 1 —
  just the trie's) are evicted least-recently-used until the allocation
  fits; ``--prefix-cache-blocks`` additionally caps steady-state
  retention. Eviction frees through the allocator's one decrement path,
  so the refcount laws hold under churn (pinned in
  tests/test_prefix_cache.py).

The trie lives on the ENGINE (beside the allocator) and survives across
serve() runs — that persistence is the point: requests sharing a system
prompt pay its prefill once per engine lifetime, not once per batch. It
is dropped whenever the pool arrays are rebuilt (``reset_decode_pool``,
a device-loss pool rebuild): block ids would otherwise dangle into a
zeroed pool.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .scheduler import BlockAllocator


class PrefixNode:
    """One trie node = one pool block + the tokens its rows hold."""

    __slots__ = ("tokens", "block", "children", "parent", "last_used")

    def __init__(self, tokens: Tuple[int, ...], block: Optional[int],
                 parent: Optional["PrefixNode"] = None):
        self.tokens = tokens
        self.block = block
        self.children: List["PrefixNode"] = []
        self.parent = parent
        self.last_used = 0


def _lcp(a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class PrefixCache:
    """Host-side radix tree mapping token prefixes onto refcounted pool
    blocks (module docstring has the design). Pure deterministic host
    bookkeeping — children keep insertion order, ties resolve first-won
    — so the serving schedule stays a function of the submission
    sequence."""

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 max_blocks: int = 0):
        self.allocator = allocator
        self.block_size = int(block_size)
        # steady-state retention cap in blocks (0 = unbounded; pressure
        # eviction runs either way)
        self.max_blocks = int(max_blocks or 0)
        self.root = PrefixNode((), None)
        self.n_blocks = 0
        self._tick = 0
        # counters (the engine folds these into ServingStats /
        # the StepTelemetry ``serving_prefix`` block)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.inserts = 0

    # ----------------------------------------------------------- matching
    def _touch(self, node: PrefixNode) -> None:
        self._tick += 1
        node.last_used = self._tick

    def _walk(self, tokens, cap: int, touch: bool
              ) -> Tuple[List[int], int]:
        bs = self.block_size
        cap = max(int(cap), 0)
        node = self.root
        matched = 0
        blocks: List[int] = []
        toks = tuple(int(t) for t in tokens[:cap])
        while matched < cap:
            best: Optional[PrefixNode] = None
            best_lcp = 0
            for child in node.children:
                m = _lcp(child.tokens, toks[matched:matched
                                            + len(child.tokens)])
                if m > best_lcp:
                    best, best_lcp = child, m
            if best is None or best_lcp == 0:
                break
            blocks.append(best.block)  # type: ignore[arg-type]
            matched += best_lcp
            if touch:
                self._touch(best)
            if best_lcp < len(best.tokens) or len(best.tokens) < bs:
                break  # partial credit or a partial (leaf) node: stop
            node = best
        return blocks, matched

    def match(self, tokens, cap: int) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens[:cap]`` in (block ids,
        matched token count); a match below one full block is a miss —
        the returned ids are NOT yet pinned (the admission path takes
        its shares via ``BlockAllocator.share`` before anything can
        evict them)."""
        blocks, matched = self._walk(tokens, cap, touch=True)
        if matched < self.block_size:
            self.misses += 1
            return [], 0
        self.hits += 1
        return blocks, matched

    def peek(self, tokens, cap: int) -> int:
        """Matched-token count only, no LRU touch, no counters — the
        fleet router's cache-affinity probe."""
        _blocks, matched = self._walk(tokens, cap, touch=False)
        return matched if matched >= self.block_size else 0

    # ---------------------------------------------------------- insertion
    def insert(self, tokens, blocks: List[int]) -> int:
        """Adopt a request's prefilled blocks for ``tokens`` (block ``i``
        holds ``tokens[i*bs:(i+1)*bs]``); returns how many blocks the
        trie newly retained (each retained block gains one allocator
        reference). Exact duplicates dedup against existing nodes; a
        partial node whose tokens are a prefix of the incoming (longer)
        segment is UPGRADED to the longer block — live sharers of the
        old block keep their own references, so nothing they map
        changes."""
        if self.n_blocks == 0 and not blocks:
            return 0
        bs = self.block_size
        toks = tuple(int(t) for t in tokens)
        # only cache whole-block-or-better prompts: a sub-block prefix
        # can never be matched (the match floor) so retaining it would
        # only pin pool capacity
        if len(toks) < bs:
            return 0
        node = self.root
        adopted = 0
        for i, blk in enumerate(blocks):
            seg = toks[i * bs:(i + 1) * bs]
            if not seg:
                break
            existing = None
            upgrade = None
            covered = None
            for child in node.children:
                if child.tokens == seg:
                    existing = child
                    break
                if len(child.tokens) < len(seg) and \
                        seg[:len(child.tokens)] == child.tokens:
                    upgrade = upgrade or child
                elif len(child.tokens) >= len(seg) and \
                        child.tokens[:len(seg)] == seg:
                    covered = covered or child
            if existing is not None:
                self._touch(existing)
                if len(seg) < bs:
                    break  # duplicate partial tail: nothing below it
                node = existing
                continue
            if covered is not None:
                # an existing node already covers this (shorter) partial
                # segment with more tokens — keep the richer one
                self._touch(covered)
                break
            if upgrade is not None:
                # longer evidence for a partial node: adopt the new
                # block, release the old one's trie reference
                self.allocator.share([blk])
                old = upgrade.block
                upgrade.block = blk
                upgrade.tokens = seg
                self._touch(upgrade)
                if old is not None:
                    self.allocator.free([old])
                adopted += 1
                self.inserts += 1
                if len(seg) < bs:
                    break
                node = upgrade
                continue
            self.allocator.share([blk])
            child = PrefixNode(seg, blk, parent=node)
            self._touch(child)
            node.children.append(child)
            self.n_blocks += 1
            adopted += 1
            self.inserts += 1
            if len(seg) < bs:
                break
            node = child
        if self.max_blocks and self.n_blocks > self.max_blocks:
            self.evict(self.n_blocks - self.max_blocks)
        return adopted

    # ----------------------------------------------------------- eviction
    def _evictable(self) -> List[PrefixNode]:
        out: List[PrefixNode] = []

        def rec(node: PrefixNode) -> None:
            for child in node.children:
                rec(child)
                if not child.children and child.block is not None and \
                        self.allocator.refcount(child.block) == 1:
                    out.append(child)

        rec(self.root)
        return out

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by removing least-
        recently-used leaf nodes no live request references (allocator
        refcount 1 = the trie's own). Removing a leaf may expose its
        parent; the sweep loops until satisfied or nothing is
        evictable. Frees go through ``BlockAllocator.free`` — the one
        decrement path — so the refcount laws hold."""
        freed = 0
        while freed < n_blocks:
            cands = self._evictable()
            if not cands:
                break
            victim = min(cands, key=lambda nd: nd.last_used)
            assert victim.parent is not None
            victim.parent.children.remove(victim)
            self.allocator.free([victim.block])  # type: ignore[list-item]
            self.n_blocks -= 1
            freed += 1
            self.evictions += 1
        return freed

    def invalidate(self, blocks: List[int]) -> int:
        """Remove every node whose block is in ``blocks`` — WITH its
        whole subtree (children are only reachable through the parent,
        and a poisoned parent means the path to them is poison too) —
        returning each removed node's trie reference. The quarantine /
        decode-fault release path calls this with the suspect request's
        block table: eager insertion at prefill completion may have
        cached prompt blocks that a later decode poisoning NaN'd
        in-place, and a poisoned prefix must neither be re-matched by
        the victim's own retry nor served to anyone else."""
        bad = {int(b) for b in blocks}
        removed: List[int] = []

        def rec(node: PrefixNode) -> None:
            keep = []
            for child in node.children:
                if child.block is not None and child.block in bad:
                    reap(child)
                else:
                    rec(child)
                    keep.append(child)
            node.children = keep

        def reap(node: PrefixNode) -> None:
            if node.block is not None:
                removed.append(node.block)
            for child in node.children:
                reap(child)

        rec(self.root)
        if removed:
            self.n_blocks -= len(removed)
            self.allocator.free(removed)
        return len(removed)

    def clear(self, free: bool = True) -> None:
        """Drop every node. ``free=True`` returns the trie's references
        through the allocator (pool rebuild with a live allocator);
        ``free=False`` when the allocator itself is being reset
        (``reset_decode_pool`` — wholesale forgetting supersedes
        per-block decrements)."""
        if free:
            blocks: List[int] = []

            def rec(node: PrefixNode) -> None:
                for child in node.children:
                    rec(child)
                    if child.block is not None:
                        blocks.append(child.block)

            rec(self.root)
            if blocks:
                self.allocator.free(blocks)
        self.root = PrefixNode((), None)
        self.n_blocks = 0
