"""Speculative decoding: a small drafter proposes, the target verifies.

Leviathan et al.'s speculative sampling adapted to the serving engine's
JAX prefill/decode machinery (ISSUE 12): a cheap DRAFTER model from the
zoo proposes ``gamma`` greedy tokens per round, and the TARGET scores the
whole proposal in ONE batched pass through its existing ``exact``-numerics
prefill program — the same whole-sequence forward the engine's
``exact_decode`` contract is pinned against, so every ACCEPTED token is
provably identical to what the baseline greedy decode would have emitted
(bitwise-equal logits ⇒ equal argmax), and a rejected position falls back
to the target's own argmax at no extra forward. Each verification round
therefore commits between 1 (drafter useless) and ``gamma + 1`` (all
accepted + the free bonus token) tokens for one target forward.

Known cost model: drafter proposals re-score the growing stream through
the drafter's bucketed prefill program (no drafter-side KV reuse yet) —
``gamma`` small-model prefills per round next to the one target
verification prefill. For a drafter several times smaller than the
target this still wins on rounds, but a KV-cached one-token drafter
decode (the engine's own decode step pointed at the drafter) is the
obvious next cut and the measured acceptance/round ledger below is what
will price it.

Greedy-only by design: under greedy sampling "distribution-identical"
degenerates to token-identity, which is exactly testable
(tests/test_decode_paged.py pins speculative output == baseline output).
Temperature sampling would need the rejection-sampling correction from
the paper; the decoder refuses it loudly rather than approximating.

Honest accounting: acceptance rates ride ``ServingStats``
(``spec_rounds/spec_proposed/spec_accepted``) and each round's wall and
committed-token count feed the engine's EWMA
:class:`~flexflow_tpu.serving.resilience.AdmissionController` — when
speculation changes the per-token cost, admission shedding sees the REAL
cost, not the non-speculative estimate (the controller additionally
tracks an acceptance EWMA via ``observe_speculation``).
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from .engine import ServingStats
from .scheduler import default_buckets


class SpeculativeDecoder:
    """Greedy speculative decoding over two compiled FFModels.

    ``target`` and ``drafter`` must both be autoregressive (single
    integer token input, per-token (batch, seq, vocab) head) and share a
    vocabulary; the drafter is typically a narrower/shallower zoo build.
    ``controller`` (optionally the serving engine's ``admission``) keeps
    the EWMA admission cost model honest under speculation.
    """

    def __init__(self, target, drafter, gamma: int = 4,
                 max_context: Optional[int] = None,
                 controller=None):
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        for which, m in (("target", target), ("drafter", drafter)):
            if m.executor is None:
                raise ValueError(f"{which} model: call compile() first")
        # ISSUE 18 guard rail: greedy speculative verification scores
        # draft windows through the single-shard exact path; a sequence-
        # sharded target (or drafter) would verify against a different
        # score decomposition than it decodes with. Refuse loudly at
        # construction instead of accepting garbage token streams.
        from .kvcache import SeqShardsError

        for which, m in (("target", target), ("drafter", drafter)):
            if int(getattr(m.config, "seq_shards", 1) or 1) > 1:
                raise SeqShardsError(
                    f"speculative decoding does not support --seq-shards "
                    f"> 1 (the {which} model requests "
                    f"{int(m.config.seq_shards)} sequence shards); run "
                    "the sharded engine without a drafter, or set "
                    "--seq-shards 1")
        t_vocab = self._vocab(target)
        d_vocab = self._vocab(drafter)
        if t_vocab != d_vocab:
            raise ValueError(
                f"target vocab {t_vocab} != drafter vocab {d_vocab}: "
                "speculative verification compares token ids, the two "
                "models must share a vocabulary")
        self.target = target
        self.drafter = drafter
        self.gamma = int(gamma)
        # same bound as the serving engine's admission rejection: the
        # position table caps scorable length on BOTH models (a longer
        # stream would silently alias position rows in the verification
        # forward and break the token-identity contract)
        from .engine import position_context_bound

        requested = int(
            max_context or getattr(target.config, "max_decode_len", 128))
        self.max_context = min(
            position_context_bound(target.executor, requested),
            position_context_bound(drafter.executor, requested))
        self.controller = controller
        self.stats = ServingStats()
        self._buckets = default_buckets(self.max_context)
        # device-side argmax for _score, jitted lazily (retraces per
        # logits bucket shape; one executable per bucket)
        self._argmax = None

    @staticmethod
    def _vocab(model) -> int:
        ex = model.executor
        final = ex.pcg.nodes[ex.final_guid]
        out = final.out_shapes[ex.final_out_idx]
        if len(out) != 3:
            raise ValueError(
                f"speculative decoding needs a per-token (batch, seq, "
                f"vocab) head; {final.name} produces {out}")
        return int(out[-1])

    # ------------------------------------------------------------- scoring
    def _score(self, model, tokens: np.ndarray) -> np.ndarray:
        """Greedy next-token ids for every position of ``tokens`` via the
        model's prefill program (ONE whole-sequence forward — the exact
        numerics the engine's bitwise decode contract is pinned to).
        Returns (len,) int32: entry i is argmax of the distribution for
        position i + 1."""
        import jax
        import jax.numpy as jnp

        L = int(tokens.shape[0])
        bucket = None
        for b in self._buckets:
            if L <= b:
                bucket = b
                break
        if bucket is None:
            raise ValueError(
                f"stream length {L} exceeds the speculative max context "
                f"{self.max_context}")
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = tokens
        logits, _last, _cache = model.executor.make_prefill_step(
            bucket, bucket)(model.params, [jnp.asarray(ids)],
                            jnp.asarray([L], np.int32))
        # reduce on device BEFORE the transfer (ISSUE 17 satellite):
        # only the argmax ids are consumed, so ship (bucket,) int32
        # instead of the full padded (1, bucket, vocab) float matrix —
        # vocab x 4 bytes fewer per scored position, every round
        if self._argmax is None:
            self._argmax = jax.jit(
                lambda lg: jnp.argmax(lg[0], axis=-1).astype(jnp.int32))
        ids_out = self._argmax(logits)
        return np.asarray(jax.device_get(ids_out))[:L]

    # ------------------------------------------------------------ generate
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 eos_id: Optional[int] = None) -> List[List[int]]:
        """Generate greedy continuations; token-identical to the
        baseline engine's greedy ``exact_decode`` output (tested), at
        ~``(accepted + 1)`` tokens per target forward."""
        if temperature > 0.0:
            raise NotImplementedError(
                "speculative decoding is greedy-only: temperature "
                "sampling needs the rejection-sampling correction to "
                "stay distribution-identical; decode through "
                "ServingEngine.generate instead")
        out: List[List[int]] = []
        for p in prompts:
            out.append(self._generate_one(
                np.asarray(p, np.int32), int(max_new_tokens), eos_id))
        return out

    def _generate_one(self, prompt: np.ndarray, max_new: int,
                      eos_id: Optional[int]) -> List[int]:
        stats = self.stats
        stream = list(int(t) for t in prompt)
        generated: List[int] = []
        while len(generated) < max_new:
            t0 = time.perf_counter()
            room = min(max_new - len(generated),
                       self.max_context - len(stream))
            if room <= 0:
                break
            # propose: up to gamma greedy drafter tokens (gamma+draft
            # must still fit the context for the verification pass)
            g = min(self.gamma, room - 1) if room > 1 else 0
            draft: List[int] = []
            ds = list(stream)
            for _ in range(g):
                nxt = int(self._score(self.drafter,
                                      np.asarray(ds, np.int32))[-1])
                draft.append(nxt)
                ds.append(nxt)
                if eos_id is not None and nxt == int(eos_id):
                    break
            # verify: ONE target pass over stream + draft scores every
            # draft position AND the bonus position
            preds = self._score(self.target,
                                np.asarray(stream + draft, np.int32))
            L = len(stream)
            accepted = 0
            commits: List[int] = []
            for i, d in enumerate(draft):
                t_pred = int(preds[L - 1 + i])
                if t_pred == d:
                    accepted += 1
                    commits.append(d)
                else:
                    commits.append(t_pred)  # the correction token
                    break
            else:
                # every draft token accepted: the verification pass
                # already scored position L + len(draft) — a free token
                commits.append(int(preds[L - 1 + len(draft)]))
            wall = time.perf_counter() - t0
            stats.wall_s += wall
            stats.spec_rounds += 1
            stats.spec_proposed += len(draft)
            stats.spec_accepted += accepted
            committed_now = 0
            for tok in commits:
                if len(generated) >= max_new:
                    break
                generated.append(tok)
                stream.append(tok)
                committed_now += 1
                stats.tokens_generated += 1
                stats.record_token(wall / max(len(commits), 1))
                if eos_id is not None and tok == int(eos_id):
                    break
            if self.controller is not None and committed_now:
                self.controller.observe_step(wall, committed_now)
                self.controller.observe_speculation(accepted, len(draft))
            if eos_id is not None and generated and \
                    generated[-1] == int(eos_id):
                break
            if committed_now == 0:
                break  # context exhausted mid-round
        stats.requests_served += 1
        return generated
