"""flexflow_tpu.serving: the inference engine (ISSUE 6, docs/serving.md).

Prefill/decode split with a first-class KV-cache pytree, Orca-style
continuous batching over a fixed decode-slot pool, and a Unity serving
objective (latency-bounded throughput) next to the training step-time
search. The reference snapshot shipped only an incomplete Triton serving
prototype; this subsystem is that story finished in JAX.
"""
from .kvcache import DecodeState, ServingState  # noqa: F401
from .scheduler import (ContinuousBatchScheduler, QueueFullError,  # noqa: F401
                        Request, bucket_for, default_buckets)
from .engine import ServingEngine, ServingStats  # noqa: F401
from .search import (ServingCandidate, ServingPlan,  # noqa: F401
                     ServingSearchError, serving_search)
