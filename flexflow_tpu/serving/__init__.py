"""flexflow_tpu.serving: the inference engine (ISSUE 6, docs/serving.md).

Prefill/decode split with a first-class KV-cache pytree, Orca-style
continuous batching over a fixed decode-slot pool, and a Unity serving
objective (latency-bounded throughput) next to the training step-time
search. The reference snapshot shipped only an incomplete Triton serving
prototype; this subsystem is that story finished in JAX.
"""
from .kvcache import (DecodeState, GARBAGE_BLOCK,  # noqa: F401
                      KV_DTYPES, ServingState)
from .scheduler import (BlockAccountingError,  # noqa: F401
                        BlockAllocator,
                        ContextOverflowError, ContinuousBatchScheduler,
                        QueueFullError, Request, ServingRejection,
                        bucket_for, default_buckets)
from .prefix import PrefixCache, PrefixNode  # noqa: F401
from .engine import ServingEngine, ServingStats  # noqa: F401
from .speculative import SpeculativeDecoder  # noqa: F401
from .resilience import (AdmissionController,  # noqa: F401
                         DecodeStateLostError, DeviceLossError,
                         OUTCOMES, OverloadError, ServingResilience)
from .search import (ServingCandidate, ServingPlan,  # noqa: F401
                     ServingSearchError, serving_search)
from .tenancy import (QuotaExceededError, TENANT_TIERS,  # noqa: F401
                      TenantPolicy, TenantRegistry, WeightedFairQueue,
                      parse_tenant_tiers)
from .journal import (JournalCorruptError, NOOP_JOURNAL,  # noqa: F401
                      NoopJournal, RequestJournal, journal_from_config)
from .fleet import (CircuitBreaker, FLEET_HEALTH,  # noqa: F401
                    FLEET_MIN_RETRY_AFTER_MS, FleetCrashed, FleetReplica,
                    FleetStats, ServingFleet, lint_replica_plans,
                    plan_replicas)
