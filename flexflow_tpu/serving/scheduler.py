"""Continuous (iteration-level) batching scheduler for the serving engine.

Orca-style (OSDI'22) iteration-level scheduling over a fixed pool of decode
slots: new requests are admitted into the in-flight decode batch the moment
a slot frees up (no wait for the whole batch to drain), prompts are
length-bucketed so prefill compiles once per bucket instead of once per
prompt length (padding-free in the compile-cache sense: a handful of
static shapes cover every length), finished slots are recycled on
EOS/max-tokens, and admission backpressure is a bounded queue — ``submit``
refuses instead of letting an unbounded backlog eat host memory.

The scheduler is PURE host-side bookkeeping — deterministic by
construction (same submission order + same engine -> same token streams),
which is what the cross-request isolation tests key on. Device work
(prefill/decode/slot writes) lives in serving/engine.py.
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Deque, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.reqtrace import get_reqtrace

_req_counter = itertools.count(1)


def reserve_rids(past: int) -> None:
    """Advance the process-wide rid counter past ``past`` (ISSUE 20):
    journal recovery replays requests under their ORIGINAL rids, so the
    counter must skip every rid the dead process ever issued or a fresh
    submit would collide with a replayed one. Monotone — never moves
    the counter backwards."""
    global _req_counter
    cur = next(_req_counter)  # consumed value is re-issued by count()
    _req_counter = itertools.count(max(cur, int(past) + 1))


def now_ms() -> float:
    """Default monotonic time base (ms) for deadline/drain decisions —
    ONE definition shared by the scheduler and the resilience policy so
    the two clocks cannot drift apart in units."""
    return time.monotonic() * 1e3


def remove_by_identity(queue, req: "Request") -> bool:
    """Remove ``req`` from a queue by IDENTITY (``is``), returning
    whether it was found. The one implementation behind every queue
    removal here and in the fleet router: Request is a dataclass holding
    ndarrays, so ``list.remove`` / ``in`` (``==`` comparison) raise
    ambiguous-truth mid-sweep."""
    for i, q in enumerate(queue):
        if q is req:
            del queue[i]
            return True
    return False


class ServingRejection(RuntimeError):
    """Common base of every admission refusal (ISSUE 9): the bounded-queue
    ``QueueFullError`` and the load shedder's ``OverloadError``
    (serving/resilience.py) both carry the same retry context, so a caller
    writes ONE except clause:

        try:
            engine.admit(sched, req)
        except ServingRejection as e:
            backoff(e.retry_after_ms); resubmit later

    ``queued``/``active`` snapshot the scheduler at refusal time;
    ``retry_after_ms`` is the admission controller's drain-time hint (0.0
    when no cost estimate exists yet)."""

    def __init__(self, message: str, queued: int = 0, active: int = 0,
                 retry_after_ms: float = 0.0):
        super().__init__(message)
        self.queued = int(queued)
        self.active = int(active)
        self.retry_after_ms = float(retry_after_ms)


class QueueFullError(ServingRejection):
    """Admission refused: the bounded submit queue is at capacity
    (``max_queue``). Callers should retry later or shed load — this is the
    backpressure signal, not an internal failure."""


class ContextOverflowError(ServingRejection):
    """Admission refused: the request's worst case (prompt + max new
    tokens) exceeds the engine's max supported context — the position
    embedding table bounds decodable length below the decode ring/pool
    capacity (ISSUE 12 satellite: previously the engine warned and
    clamped the ring at construction; rejecting AT ADMISSION, naming the
    limit, is what guarantees a too-long request can never silently alias
    position rows)."""


class BlockAccountingError(RuntimeError):
    """A paged-KV block operation violated the allocator's refcount laws
    (ISSUE 14 satellite): double-free (freeing a block whose refcount is
    already 0), sharing a free block, or touching the reserved garbage
    block. Before refcounts these corrupted the FIFO free list SILENTLY
    — the same block handed to two live requests, KV cross-talk with no
    error at the scene of the crime — so the laws are now typed and
    loud."""


class BlockAllocator:
    """Host-side refcounted free-list allocator over the paged KV pool
    (ISSUE 12; refcounts + copy-on-write support ISSUE 14).

    The pool is ``n_blocks`` fixed-size blocks of ``block_size`` tokens;
    block ``GARBAGE_BLOCK`` (0) is reserved — unused table entries point
    at it — so ``n_blocks - 1`` blocks are allocatable. Allocation is
    whole-request up front (``blocks_needed(prompt + max_new)``) at the
    moment a request is admitted into a slot, so the decode hot loop
    never allocates; recycling (EOS/length/eviction/quarantine/
    cancellation) returns the blocks through the scheduler's one
    ``_release_blocks`` choke point. Pure host bookkeeping — deterministic
    FIFO free list, so the schedule stays a function of the submission
    sequence.

    Prefix sharing (ISSUE 14, serving/prefix.py): a block may be mapped
    by several requests' block tables at once — the radix-tree prefix
    cache plus every request currently reusing that prefix. ``share``
    grows the refcount, ``free`` decrements it, and the block returns to
    the FIFO free list only at refcount 0; sharers never write into a
    shared block (a divergent write clones it first — the COW path), so
    refcounts are pure bookkeeping, not synchronization. The refcount
    laws (alloc/share/free round-trips, zero leaks under churn) are
    pinned property-style in tests/test_prefix_cache.py."""

    def __init__(self, n_blocks: int, block_size: int):
        assert n_blocks >= 2, "paged pool needs >= 1 usable block " \
                              "+ the garbage block"
        assert block_size >= 1
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.free_blocks: Deque[int] = deque(range(1, self.n_blocks))
        # refcounts[b] > 0 <=> b is live (mapped by >= 1 request table
        # and/or retained by the prefix trie); the garbage block is
        # never allocated and keeps refcount 0
        self.refcounts: List[int] = [0] * self.n_blocks
        self.blocks_hwm = 0

    @property
    def n_usable(self) -> int:
        return self.n_blocks - 1

    @property
    def in_use(self) -> int:
        return self.n_usable - len(self.free_blocks)

    def blocks_needed(self, tokens: int) -> int:
        return -(-max(int(tokens), 1) // self.block_size)

    def refcount(self, block: int) -> int:
        return self.refcounts[int(block)]

    def _check(self, block: int) -> int:
        b = int(block)
        if b <= 0 or b >= self.n_blocks:
            raise BlockAccountingError(
                f"block {b} is outside the pool (usable ids 1.."
                f"{self.n_blocks - 1}; 0 is the reserved garbage block)")
        return b

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids at refcount 1 each, or None when the pool
        cannot satisfy the request right now (the scheduler keeps it
        queued and decodes; prefix-cache eviction may free some)."""
        if n > len(self.free_blocks):
            return None
        out = []
        for _ in range(int(n)):
            b = self.free_blocks.popleft()
            if self.refcounts[b] != 0:
                raise BlockAccountingError(
                    f"free list corrupt: block {b} popped with refcount "
                    f"{self.refcounts[b]} (double-listed)")
            self.refcounts[b] = 1
            out.append(b)
        self.blocks_hwm = max(self.blocks_hwm, self.in_use)
        return out

    def share(self, blocks: List[int]) -> None:
        """Add one reference to each block — a new request mapping a
        cached prefix, or the trie adopting a request's block."""
        for b in blocks:
            b = self._check(b)
            if self.refcounts[b] == 0:
                raise BlockAccountingError(
                    f"cannot share block {b}: it is free (refcount 0) — "
                    "a stale block id outlived its release")
            self.refcounts[b] += 1

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block; a block returns to the FIFO
        free list only when its last reference is gone. Freeing an
        already-free block raises (the double-free that used to corrupt
        the list silently)."""
        for b in blocks:
            b = self._check(b)
            if self.refcounts[b] == 0:
                raise BlockAccountingError(
                    f"double free of block {b}: refcount is already 0")
            self.refcounts[b] -= 1
            if self.refcounts[b] == 0:
                self.free_blocks.append(b)

    def leaked(self) -> List[int]:
        """Blocks still referenced — the zero-leak churn tests assert
        this is empty (or exactly the trie's retained set)."""
        return [b for b in range(1, self.n_blocks) if self.refcounts[b]]

    def reset(self) -> None:
        """Forget every allocation (replica kill/rejoin: the pool arrays
        are rebuilt from zeros, so no block is live anymore)."""
        self.free_blocks = deque(range(1, self.n_blocks))
        self.refcounts = [0] * self.n_blocks


@dataclasses.dataclass
class Request:
    """One generation request. ``prompt`` is a 1-D int token array;
    ``generated`` fills as decode steps commit tokens."""

    prompt: np.ndarray
    max_new_tokens: int
    rid: int = dataclasses.field(default_factory=lambda: next(_req_counter))
    eos_id: Optional[int] = None
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    finish_reason: Optional[str] = None  # "eos" | "length"
    # serving telemetry (per-request): set by the engine
    submit_step: int = 0
    first_token_step: Optional[int] = None
    # first-token wall stamp (scheduler clock, ms): TTFT = first_token_ms
    # - submit_ms — THE head-of-line-blocking metric the chunked-prefill
    # bench sub-leg reports (a short request behind a monolithic long
    # prefill pays the whole prefill wall here)
    first_token_ms: float = 0.0
    # sampling-stream tag: the engine keys each request's rng fold on this
    # (submission order) rather than the process-global ``rid`` counter, so
    # the same (prompts, seed) reproduces the same draws run after run
    rng_tag: Optional[int] = None
    # resilience (ISSUE 9, docs/serving.md "Serving under failure"):
    # deadline_ms is the relative completion budget from submission (None =
    # no deadline; the engine defaults it from --request-timeout-ms);
    # submit_ms is stamped by the scheduler's clock at submit; outcome is
    # the terminal disposition, exactly one of
    # ok | deadline_exceeded | shed | decode_fault | preempted;
    # retries_used counts decode-fault re-prefills against the
    # --decode-retry-budget
    deadline_ms: Optional[float] = None
    submit_ms: float = 0.0
    outcome: Optional[str] = None
    retries_used: int = 0
    # paged KV (ISSUE 12): pool block ids this request holds while it
    # occupies a slot (allocated at admission, freed on recycle) — empty
    # for ring-layout engines and while queued
    kv_blocks: List[int] = dataclasses.field(default_factory=list)
    # prefix cache + chunked prefill (ISSUE 14, serving/prefix.py /
    # docs/serving.md "Prefix cache & chunked prefill"):
    # prefix_hit_tokens — tokens mapped from the radix trie at admission
    # (their prefill compute is skipped); prefill_pos — tokens of the
    # effective prompt whose KV is in the pool so far (starts at the
    # hit, advances per chunk); prefill_target — the effective prompt
    # length this admission must prefill; chunk_shape — the compiled
    # chunk program's token width; pending_cow — (src, dst) block pair
    # when the shared partial tail block must be cloned before the
    # first suffix write (the copy-on-write path); finish_ms — terminal
    # clock stamp (request-completion latency = finish_ms - submit_ms)
    prefix_hit_tokens: int = 0
    prefill_pos: int = 0
    prefill_target: int = 0
    chunk_shape: int = 0
    pending_cow: Optional[Tuple[int, int]] = None
    finish_ms: float = 0.0
    # sequence-parallel decode (ISSUE 18): the searched context-length
    # bucket this request was routed to at admission (None = engine has
    # no --context-buckets) — the bucket whose seq_shards the plan's
    # ``seq_shards_for`` picked; the fleet router and trace digest read
    # it back
    context_bucket: Optional[int] = None
    # multi-tenant SLO tiers (ISSUE 19, docs/multitenant.md): the tier
    # label the fleet door's weighted fair queue and per-tenant ledgers
    # key on. None = untenanted — scheduled under the standard tier's
    # parameters, aggregate-only accounting (pre-tenant behavior)
    tenant: Optional[str] = None

    @property
    def prefilling(self) -> bool:
        """True while this request occupies a slot whose prompt KV is
        not fully in the pool yet — the decode batch excludes it (its
        length cursor is unset; decode would read garbage)."""
        return self.prefill_target > 0 and \
            self.prefill_pos < self.prefill_target

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def effective_len(self) -> int:
        """Prompt length the NEXT prefill of this request needs: the
        original prompt plus everything already generated — a decode-fault
        retry re-prefills the full committed stream onto a fresh slot so
        generation continues exactly where the quarantine cut it."""
        return self.prompt_len + len(self.generated)

    def current_prompt(self) -> np.ndarray:
        """Token ids the next prefill feeds: ``prompt`` for a fresh
        request, ``prompt + generated`` for a quarantine retry."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def expired(self, now_ms: float) -> bool:
        return (self.deadline_ms is not None and self.deadline_ms > 0
                and now_ms - self.submit_ms > self.deadline_ms)


def default_buckets(max_prompt_len: int, min_bucket: int = 16
                    ) -> Tuple[int, ...]:
    """Geometric prefill buckets: powers of two from ``min_bucket``,
    capped by ``max_prompt_len`` itself as the last bucket (a bucket wider
    than the decode ring would overflow the KV buffers) — each prompt pads
    to the smallest covering bucket, so the prefill jit cache holds at
    most log2(max/min)+1 entries."""
    buckets = []
    b = min(max(int(min_bucket), 1), max_prompt_len)
    while b < max_prompt_len:
        buckets.append(b)
        b *= 2
    buckets.append(min(b, max_prompt_len))
    return tuple(buckets)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        f"prompt length {length} exceeds the largest prefill bucket "
        f"{buckets[-1]} (raise --max-decode-len / the engine's buckets)")


class ContinuousBatchScheduler:
    """Slot allocator + admission queue for iteration-level batching.

    The engine drives it in a loop:

        while scheduler.active or scheduler.queued:
            action = scheduler.next_action()
            if action[0] == "prefill": ...engine prefills into a slot...
            else:                      ...engine runs one decode step...

    Invariants (tested): a slot serves exactly one request at a time; a
    freed slot's cache rows are fully overwritten by the next prefill
    before any decode reads them (no cross-request leakage); admission
    order is FIFO; the whole schedule is a deterministic function of the
    submission sequence.
    """

    def __init__(self, n_slots: int, max_queue: int = 64,
                 buckets: Optional[Sequence[int]] = None,
                 max_len: int = 128, clock=None):
        assert n_slots >= 1, "need at least one decode slot"
        self.n_slots = n_slots
        self.max_queue = max_queue
        self.max_len = max_len
        self.buckets = tuple(buckets) if buckets else \
            default_buckets(max_len)
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * n_slots
        self._free: Deque[int] = deque(range(n_slots))
        self.finished: List[Request] = []
        # counters for the obs serving block / bench occupancy
        self.queue_depth_hwm = 0
        self.admitted = 0
        self.recycled = 0
        # resilience (ISSUE 9): submit stamps each request with this clock
        # (ms) so deadline math shares one time base with the engine's
        # sweeps; injectable for deterministic tests. The shed policy in
        # effect is recorded here so the backpressure refusal can NAME it;
        # draining=True stops admission (next_action only decodes) during a
        # graceful SIGTERM drain.
        self.clock = clock if clock is not None else now_ms
        self.shed_policy = "off"
        self.draining = False
        # request-level tracing (ISSUE 16, obs/reqtrace.py): captured at
        # construction like the engine's tracer; every lifecycle edge
        # below notes the singleton behind an ``enabled`` guard (one
        # attribute load + truth test when tracing is off). The fleet
        # stamps its replica index here so cross-replica hops carry it.
        self.rt = get_reqtrace()
        self.replica_idx: Optional[int] = None
        self.quarantined = 0
        self.evicted = 0
        # paged KV (ISSUE 12): the engine attaches its BlockAllocator and
        # max supported context (position-table bound) before driving the
        # loop; None = ring layout / no context bound below max_len.
        # on_slot_freed fires on EVERY slot-freeing path (finish, evict,
        # quarantine, hedge cancel) — the paged engine resets the freed
        # slot's device-side block-table row and length cursor there: a
        # stale row would keep scattering the freed slot's discarded
        # tokens into blocks the allocator may have already handed to a
        # NEW request in another slot
        self.allocator: Optional[BlockAllocator] = None
        self.max_context: Optional[int] = None
        self.on_slot_freed = None
        # on_commit fires once per committed token, at THE commit point
        # (ISSUE 20): the fleet points it at the request journal's
        # progress writer when --journal-commit-every is on, so a
        # journaled token prefix is always a prefix of the real stream.
        # None (the default) keeps the journal-off hot path branch-only.
        self.on_commit = None
        # prefix cache + chunked prefill (ISSUE 14): the paged engine
        # attaches its radix-tree PrefixCache and --prefill-chunk-tokens
        # here; admission walks the trie, maps the hit into the slot's
        # block table and only the suffix is prefilled (in chunks when
        # the suffix exceeds chunk_tokens). _chunk_turn alternates chunk
        # and decode actions so a long prompt's chunks interleave with
        # other slots' decode steps instead of stalling them.
        self.prefix = None
        self.chunk_tokens = 0
        self._chunk_turn = False
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        # hedge-loss cancellations (ISSUE 11): slots/queue entries freed
        # WITHOUT a terminal outcome — the winning twin owns the ledger
        self.cancelled = 0
        # slot incarnation counters (ISSUE 17, the async serve loop):
        # bumped on EVERY slot-freeing path. A commit that was dispatched
        # against incarnation e of a slot must be discarded if the slot
        # was recycled (finish/evict/quarantine/hedge-cancel) while its
        # result was in flight — identity of the Request object alone is
        # not enough, a quarantined request can re-enter the SAME slot
        self.slot_epoch: List[int] = [0] * n_slots

    # ------------------------------------------------------------ admission
    @property
    def queued(self) -> int:
        return len(self.queue)

    @property
    def active(self) -> int:
        return self.n_slots - len(self._free)

    def submit(self, req: Request) -> None:
        """FIFO admission with bounded-queue backpressure."""
        if len(self.queue) >= self.max_queue:
            raise QueueFullError(
                f"serving queue full ({self.max_queue} waiting, shed "
                f"policy '{self.shed_policy}'); retry later or raise "
                "--max-inflight/max_queue",
                queued=len(self.queue), active=self.active)
        if req.prompt_len + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {req.prompt_len} + "
                f"max_new_tokens {req.max_new_tokens} exceeds the decode "
                f"ring capacity {self.max_len} (--max-decode-len)")
        # max supported context (ISSUE 12 satellite): the position table
        # bounds decodable length below the ring/pool capacity — reject
        # at admission, naming the limit, instead of the old
        # warn-and-clamp at engine construction
        if self.max_context is not None and \
                req.prompt_len + req.max_new_tokens > self.max_context:
            raise ContextOverflowError(
                f"request {req.rid}: prompt {req.prompt_len} + "
                f"max_new_tokens {req.max_new_tokens} exceeds the max "
                f"supported context {self.max_context} (position "
                "embedding table limit; build the model with a longer "
                "seq_len or lower max_new_tokens)",
                queued=len(self.queue), active=self.active)
        # a request the whole pool cannot hold would deadlock admission —
        # refuse it at submit, like the ring-capacity wall above
        if self.allocator is not None:
            need = self.allocator.blocks_needed(
                req.prompt_len + req.max_new_tokens)
            if need > self.allocator.n_usable:
                raise ValueError(
                    f"request {req.rid}: needs {need} KV blocks but the "
                    f"pool has {self.allocator.n_usable} (raise "
                    "--kv-pool-blocks or --kv-block-size)")
        # fail HERE, not after next_action() already claimed a slot: a
        # prompt no bucket covers must never corrupt the slot pool.
        # effective_len (prompt + committed tokens) is what the prefill
        # actually feeds — a drained quarantine-retry resubmitted to a
        # narrower scheduler must be refused at submit too
        bucket_for(req.effective_len, self.buckets)
        req.submit_ms = float(self.clock())
        self.queue.append(req)
        self.queue_depth_hwm = max(self.queue_depth_hwm, len(self.queue))
        if self.rt.enabled:
            self.rt.note(req.rid, "submit", req.submit_ms,
                         prompt_len=req.prompt_len,
                         max_new=req.max_new_tokens,
                         deadline_ms=req.deadline_ms,
                         replica=self.replica_idx)

    # ------------------------------------------------------------ scheduling
    def _admit_head(self):
        """Admit the head-of-queue request into a free slot with
        prefix-aware block accounting (ISSUE 14). Returns the classic
        ``("prefill", ...)`` action, the string ``"chunked"`` when the
        request entered the chunk-prefill path (admission bookkeeping
        only — action selection continues), or None when the pool cannot
        hold it yet (admission waits; decode continues).

        The trie walk maps the longest cached prefix (>= one full block)
        into the new slot's block table with zero prefill compute; only
        the suffix is prefilled. A hit whose boundary falls inside a
        shared block schedules a copy-on-write clone (``pending_cow``):
        the tail block is cloned into a freshly-allocated block before
        the first divergent write, so the sharer's rows are never
        perturbed."""
        req = self.queue[0]
        eff = req.effective_len
        match_blocks: List[int] = []
        match_t = 0
        if self.allocator is not None:
            alc = self.allocator
            if self.prefix is not None:
                # never match the full prompt: the final token's forward
                # pass is what produces the next-token logits admission
                # needs, so >= 1 token always prefills
                match_blocks, match_t = self.prefix.match(
                    req.current_prompt(), cap=eff - 1)
            # worst-case extent: the ORIGINAL prompt + the total token
            # cap (generated tokens count toward max_new_tokens, so a
            # quarantine retry's committed tokens are already inside it)
            need_total = alc.blocks_needed(
                req.prompt_len + req.max_new_tokens)
            partial = match_t % alc.block_size != 0
            fresh_needed = need_total - len(match_blocks) + (1 if partial
                                                            else 0)
            if match_blocks:
                # pin the matched nodes before any eviction can run
                alc.share(match_blocks)
            fresh = alc.alloc(fresh_needed)
            if fresh is None and self.prefix is not None:
                # pool pressure: evict LRU unreferenced trie nodes and
                # retry — cached prefixes are a performance loan, never
                # a reason to starve admission
                if self.prefix.evict(fresh_needed - len(alc.free_blocks)):
                    fresh = alc.alloc(fresh_needed)
            if fresh is None:
                if match_blocks:
                    alc.free(match_blocks)  # drop the pins; stay queued
                return None
            if partial:
                # the shared tail block will be cloned into fresh[0]
                # before the first suffix write (engine-side donated
                # jit); the share on src is held until the clone lands
                req.pending_cow = (match_blocks[-1], fresh[0])
                req.kv_blocks = match_blocks[:-1] + [fresh[0]] + fresh[1:]
            else:
                req.pending_cow = None
                req.kv_blocks = match_blocks + fresh
        req.prefix_hit_tokens = match_t
        req.prefill_pos = match_t
        req.prefill_target = eff
        req.chunk_shape = 0
        self.queue.popleft()
        slot = self._free.popleft()
        self.slots[slot] = req
        self.admitted += 1
        if self.rt.enabled:
            self.rt.note(req.rid, "admit", float(self.clock()),
                         slot=slot, hit=match_t,
                         cow=req.pending_cow is not None,
                         replica=self.replica_idx)
        if match_t:
            self.prefix_hits += 1
            self.prefix_tokens_reused += match_t
        suffix = eff - match_t
        if match_t > 0 or (self.chunk_tokens and
                           suffix > self.chunk_tokens):
            # chunk path: the suffix runs through the chunk-prefill
            # program — chunk_tokens-wide steps when chunking is on, one
            # bucket-shaped chunk otherwise. Compiled shape floor 2: a
            # 1-row projection lowers as a matvec whose accumulation
            # differs from the GEMM's by ~1 ulp (the same lowering fact
            # behind ServingState.exact), breaking the cached-vs-cold
            # bitwise contract.
            req.chunk_shape = max(
                2, self.chunk_tokens or bucket_for(suffix, self.buckets))
            self._chunk_turn = True
            return "chunked"
        req.prefill_pos = 0  # classic one-shot: the engine marks
        # completion (prefill_pos = target) only after the slot write
        return ("prefill", req, slot, bucket_for(eff, self.buckets))

    def next_action(self):
        """("prefill", request, slot, bucket_len) when a request can be
        admitted into a free slot — prefill takes priority so freed
        capacity never idles while work queues; ("prefill_chunk",
        request, slot, start, n_tokens, chunk_shape) for one chunk of an
        in-progress chunked/suffix prefill, alternating with ("decode",
        [(slot, request), ...]) over the decodable in-flight slots so a
        long prompt never head-of-line-blocks the continuous batch; else
        None. While ``draining`` (graceful SIGTERM shutdown) admission
        stops: in-progress prefills and decodes still run so in-flight
        requests finish, and the queue is left intact for the engine to
        hand back."""
        while self.queue and self._free and not self.draining:
            act = self._admit_head()
            if act is None:
                break  # pool pressure: decode on, recycling frees blocks
            if act != "chunked":
                return act
        chunking = [(i, r) for i, r in enumerate(self.slots)
                    if r is not None and r.prefilling]
        live = [(i, r) for i, r in enumerate(self.slots)
                if r is not None and not r.prefilling]
        if chunking and (self._chunk_turn or not live):
            slot, req = chunking[0]  # lowest slot — deterministic
            self._chunk_turn = False  # a decode turn comes next
            n = min(req.chunk_shape, req.prefill_target - req.prefill_pos)
            return ("prefill_chunk", req, slot, req.prefill_pos, n,
                    req.chunk_shape)
        if live:
            self._chunk_turn = True
            return ("decode", live)
        return None

    def chunk_done(self, slot: int, n_tokens: int) -> bool:
        """Record one completed prefill chunk for the request in
        ``slot``; returns True when its whole effective prompt is now in
        the pool (the engine then samples the first token and arms the
        slot for decode)."""
        req = self.slots[slot]
        assert req is not None, f"chunk for empty slot {slot}"
        req.prefill_pos += int(n_tokens)
        return req.prefill_pos >= req.prefill_target

    def release_cow(self, req: Request) -> None:
        """The engine's COW clone landed: drop the admission-held share
        on the source block (the clone in the request's table owns the
        divergent continuation now)."""
        if req.pending_cow is not None and self.allocator is not None:
            self.allocator.free([req.pending_cow[0]])
        req.pending_cow = None

    def commit_token(self, slot: int, token: int) -> bool:
        """Record one generated token for the request in ``slot``; returns
        True when the request finished (EOS or length) and the slot was
        recycled."""
        req = self.slots[slot]
        assert req is not None, f"decode token for empty slot {slot}"
        req.generated.append(int(token))
        # the first-token (TTFT) stamp lands HERE, at the commit point —
        # not in the engine's prefill branches. Any admission path that
        # commits its first token without a classic prefill step (a
        # zero-prefill full-prefix hit, a hedge twin resuming a copied
        # stream, a decode-path first commit) still gets stamped; a
        # migrated request keeps the stamp from its original commit.
        if not req.first_token_ms:
            req.first_token_ms = float(self.clock())
        if self.rt.enabled:
            self.rt.note(req.rid, "token", float(self.clock()),
                         occ=self.n_slots - len(self._free),
                         replica=self.replica_idx)
        if self.on_commit is not None:
            self.on_commit(req)
        if req.eos_id is not None and int(token) == int(req.eos_id):
            return self._finish(slot, "eos")
        if len(req.generated) >= req.max_new_tokens:
            return self._finish(slot, "length")
        return False

    def _release_blocks(self, req: Request, adopt: bool = True) -> None:
        """The ONE choke point returning a request's pool blocks to the
        allocator — every slot-freeing path (finish, evict, quarantine,
        hedge cancellation) funnels through it so a block can never leak
        or double-free. ISSUE 14: prefix-trie retention ALSO happens
        here — a fully-prefilled request's prompt blocks (including the
        partial tail, the copy-on-write sharing site) are adopted into
        the radix tree before the request's own references drop, so the
        cached KV outlives the request and the next shared-prefix
        admission pays no prefill. ``adopt=False`` on quarantine /
        decode-fault paths: suspected-poisoned KV must never enter the
        cache."""
        if self.allocator is not None:
            if req.pending_cow is not None:
                # the COW clone never ran (released before the first
                # suffix chunk): drop the admission-held source share
                self.allocator.free([req.pending_cow[0]])
                req.pending_cow = None
            if req.kv_blocks:
                if (adopt and self.prefix is not None
                        and req.prefill_target > 0
                        and req.prefill_pos >= req.prefill_target):
                    self.prefix.insert(
                        req.current_prompt()[:req.prefill_pos],
                        req.kv_blocks)
                elif not adopt and self.prefix is not None:
                    # poison-suspect release: the decode poisoning NaN'd
                    # this request's blocks IN PLACE — including any
                    # prompt blocks the trie eagerly cached at prefill
                    # completion. Purge them, or the victim's own retry
                    # re-matches its poisoned prefix (never recovering)
                    # and future shared-prefix admissions are served NaN
                    # KV.
                    self.prefix.invalidate(req.kv_blocks)
                self.allocator.free(req.kv_blocks)
        req.kv_blocks = []

    def _finish(self, slot: int, reason: str,
                outcome: str = "ok") -> bool:
        req = self.slots[slot]
        req.done = True
        req.finish_reason = reason
        req.outcome = outcome
        req.finish_ms = float(self.clock())
        if self.rt.enabled:
            self.rt.finish(req.rid, req.finish_ms, outcome,
                           reason=reason,
                           new_tokens=len(req.generated),
                           replica=self.replica_idx)
        self._release_blocks(req, adopt=outcome != "decode_fault")
        self.finished.append(req)
        self.slots[slot] = None
        self._free.append(slot)
        self.slot_epoch[slot] += 1
        self.recycled += 1
        if self.on_slot_freed is not None:
            self.on_slot_freed(slot)
        return True

    # ---------------------------------------------------------- resilience
    # ISSUE 9: the engine's deadline sweeps, decode-health quarantine and
    # graceful drain manipulate the slot pool through these — slot-state
    # invariants (one request per slot, freed slots fully re-prefilled
    # before any read) stay enforced in ONE place.
    def evict(self, slot: int, outcome: str) -> Request:
        """Terminate the request in ``slot`` with a failure ``outcome``
        (deadline_exceeded | decode_fault | preempted) and recycle the
        slot. The evicted request is finished — it lands in ``finished``
        with ``outcome`` set, never silently dropped."""
        req = self.slots[slot]
        assert req is not None, f"evict of empty slot {slot}"
        self.evicted += 1
        self._finish(slot, outcome, outcome=outcome)
        return req

    def drop_queued(self, req: Request, outcome: str) -> None:
        """Remove a still-queued request (it never held a slot) with a
        terminal ``outcome`` — the admission-time half of deadline
        enforcement."""
        if not remove_by_identity(self.queue, req):
            raise ValueError(f"request rid={req.rid} is not queued")
        req.done = True
        req.finish_reason = outcome
        req.outcome = outcome
        req.finish_ms = float(self.clock())
        if self.rt.enabled:
            self.rt.finish(req.rid, req.finish_ms, outcome,
                           reason=outcome,
                           new_tokens=len(req.generated),
                           replica=self.replica_idx)
        self._release_blocks(req)  # defensive: queued requests hold none
        self.finished.append(req)

    def quarantine(self, slot: int) -> Request:
        """Pull a decode-poisoned request out of ``slot`` for a retry on a
        fresh slot: the slot returns to the BACK of the free pool (so the
        retry prefers a different slot when one is available — its rows
        are fully overwritten by the next prefill either way) and the
        request re-enters the queue at the FRONT, keeping its committed
        tokens (``current_prompt`` re-prefills prompt + generated)."""
        req = self.slots[slot]
        assert req is not None, f"quarantine of empty slot {slot}"
        # adopt=False: this slot's KV is poison-suspect — it must never
        # enter the prefix cache (a poisoned trie would serve NaN KV to
        # every future shared-prefix admission)
        self._release_blocks(req, adopt=False)
        self.slots[slot] = None
        self._free.append(slot)
        self.slot_epoch[slot] += 1
        self.quarantined += 1
        if self.rt.enabled:
            self.rt.note(req.rid, "quarantine", float(self.clock()),
                         slot=slot, replica=self.replica_idx)
        self.queue.appendleft(req)
        if self.on_slot_freed is not None:
            self.on_slot_freed(slot)
        return req

    def cancel_slot(self, slot: int) -> Request:
        """Hedge-loss cancellation (ISSUE 11, serving/fleet.py): free the
        slot WITHOUT a terminal outcome and WITHOUT a ``finished`` entry —
        the cancelled copy is accounted by its winning hedge twin, so a
        ledger entry here would double-count the request. The slot's
        cache rows go stale exactly like an eviction's; the next prefill
        fully overwrites them before any read (the standing slot-pool
        invariant)."""
        req = self.slots[slot]
        assert req is not None, f"cancel of empty slot {slot}"
        self._release_blocks(req)
        self.slots[slot] = None
        self._free.append(slot)
        self.slot_epoch[slot] += 1
        self.cancelled += 1
        if self.on_slot_freed is not None:
            self.on_slot_freed(slot)
        return req

    def cancel_queued(self, req: Request) -> None:
        """Hedge-loss cancellation for a copy that never held a slot:
        identity-based removal from the queue, no ledger entry."""
        if not remove_by_identity(self.queue, req):
            raise ValueError(f"request rid={req.rid} is not queued")
        self.cancelled += 1

    def remove_finished(self, req: Request) -> bool:
        """Strike a request from the ``finished`` ledger (identity-based):
        the hedge loser may complete in the same router tick its twin
        wins, and exactly-one-outcome accounting then requires the
        loser's entry withdrawn. Returns True when an entry was
        removed."""
        for i, q in enumerate(self.finished):
            if q is req:
                del self.finished[i]
                self.cancelled += 1
                return True
        return False

    def pop_queued(self) -> List[Request]:
        """Drain handoff: hand back every still-queued request (outcome
        ``preempted``) for re-submission to another replica — they never
        started, so their state is clean."""
        out = list(self.queue)
        self.queue.clear()
        for r in out:
            r.outcome = "preempted"
        return out
