"""Continuous (iteration-level) batching scheduler for the serving engine.

Orca-style (OSDI'22) iteration-level scheduling over a fixed pool of decode
slots: new requests are admitted into the in-flight decode batch the moment
a slot frees up (no wait for the whole batch to drain), prompts are
length-bucketed so prefill compiles once per bucket instead of once per
prompt length (padding-free in the compile-cache sense: a handful of
static shapes cover every length), finished slots are recycled on
EOS/max-tokens, and admission backpressure is a bounded queue — ``submit``
refuses instead of letting an unbounded backlog eat host memory.

The scheduler is PURE host-side bookkeeping — deterministic by
construction (same submission order + same engine -> same token streams),
which is what the cross-request isolation tests key on. Device work
(prefill/decode/slot writes) lives in serving/engine.py.
"""
from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Deque, List, Optional, Sequence, Tuple

import numpy as np

_req_counter = itertools.count(1)


class QueueFullError(RuntimeError):
    """Admission refused: the bounded submit queue is at capacity
    (``max_queue``). Callers should retry later or shed load — this is the
    backpressure signal, not an internal failure."""


@dataclasses.dataclass
class Request:
    """One generation request. ``prompt`` is a 1-D int token array;
    ``generated`` fills as decode steps commit tokens."""

    prompt: np.ndarray
    max_new_tokens: int
    rid: int = dataclasses.field(default_factory=lambda: next(_req_counter))
    eos_id: Optional[int] = None
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    finish_reason: Optional[str] = None  # "eos" | "length"
    # serving telemetry (per-request): set by the engine
    submit_step: int = 0
    first_token_step: Optional[int] = None
    # sampling-stream tag: the engine keys each request's rng fold on this
    # (submission order) rather than the process-global ``rid`` counter, so
    # the same (prompts, seed) reproduces the same draws run after run
    rng_tag: Optional[int] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])


def default_buckets(max_prompt_len: int, min_bucket: int = 16
                    ) -> Tuple[int, ...]:
    """Geometric prefill buckets: powers of two from ``min_bucket``,
    capped by ``max_prompt_len`` itself as the last bucket (a bucket wider
    than the decode ring would overflow the KV buffers) — each prompt pads
    to the smallest covering bucket, so the prefill jit cache holds at
    most log2(max/min)+1 entries."""
    buckets = []
    b = min(max(int(min_bucket), 1), max_prompt_len)
    while b < max_prompt_len:
        buckets.append(b)
        b *= 2
    buckets.append(min(b, max_prompt_len))
    return tuple(buckets)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        f"prompt length {length} exceeds the largest prefill bucket "
        f"{buckets[-1]} (raise --max-decode-len / the engine's buckets)")


class ContinuousBatchScheduler:
    """Slot allocator + admission queue for iteration-level batching.

    The engine drives it in a loop:

        while scheduler.active or scheduler.queued:
            action = scheduler.next_action()
            if action[0] == "prefill": ...engine prefills into a slot...
            else:                      ...engine runs one decode step...

    Invariants (tested): a slot serves exactly one request at a time; a
    freed slot's cache rows are fully overwritten by the next prefill
    before any decode reads them (no cross-request leakage); admission
    order is FIFO; the whole schedule is a deterministic function of the
    submission sequence.
    """

    def __init__(self, n_slots: int, max_queue: int = 64,
                 buckets: Optional[Sequence[int]] = None,
                 max_len: int = 128):
        assert n_slots >= 1, "need at least one decode slot"
        self.n_slots = n_slots
        self.max_queue = max_queue
        self.max_len = max_len
        self.buckets = tuple(buckets) if buckets else \
            default_buckets(max_len)
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * n_slots
        self._free: Deque[int] = deque(range(n_slots))
        self.finished: List[Request] = []
        # counters for the obs serving block / bench occupancy
        self.queue_depth_hwm = 0
        self.admitted = 0
        self.recycled = 0

    # ------------------------------------------------------------ admission
    @property
    def queued(self) -> int:
        return len(self.queue)

    @property
    def active(self) -> int:
        return self.n_slots - len(self._free)

    def submit(self, req: Request) -> None:
        """FIFO admission with bounded-queue backpressure."""
        if len(self.queue) >= self.max_queue:
            raise QueueFullError(
                f"serving queue full ({self.max_queue} waiting); "
                "retry later or raise --max-inflight/max_queue")
        if req.prompt_len + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {req.prompt_len} + "
                f"max_new_tokens {req.max_new_tokens} exceeds the decode "
                f"ring capacity {self.max_len} (--max-decode-len)")
        # fail HERE, not after next_action() already claimed a slot: a
        # prompt no bucket covers must never corrupt the slot pool
        bucket_for(req.prompt_len, self.buckets)
        self.queue.append(req)
        self.queue_depth_hwm = max(self.queue_depth_hwm, len(self.queue))

    # ------------------------------------------------------------ scheduling
    def next_action(self):
        """("prefill", request, slot, bucket_len) when a request can be
        admitted into a free slot — prefill takes priority so freed
        capacity never idles while work queues; else ("decode",
        [(slot, request), ...]) over the in-flight slots; else None."""
        if self.queue and self._free:
            req = self.queue.popleft()
            slot = self._free.popleft()
            self.slots[slot] = req
            self.admitted += 1
            return ("prefill", req, slot,
                    bucket_for(req.prompt_len, self.buckets))
        live = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if live:
            return ("decode", live)
        return None

    def commit_token(self, slot: int, token: int) -> bool:
        """Record one generated token for the request in ``slot``; returns
        True when the request finished (EOS or length) and the slot was
        recycled."""
        req = self.slots[slot]
        assert req is not None, f"decode token for empty slot {slot}"
        req.generated.append(int(token))
        if req.eos_id is not None and int(token) == int(req.eos_id):
            return self._finish(slot, "eos")
        if len(req.generated) >= req.max_new_tokens:
            return self._finish(slot, "length")
        return False

    def _finish(self, slot: int, reason: str) -> bool:
        req = self.slots[slot]
        req.done = True
        req.finish_reason = reason
        self.finished.append(req)
        self.slots[slot] = None
        self._free.append(slot)
        self.recycled += 1
        return True
