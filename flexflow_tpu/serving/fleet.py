"""Fleet of fault domains: a multi-replica serving router (ISSUE 11).

PR 6 built one ServingEngine and PR 9 taught it to survive deadlines,
overload, poisoned decodes, SIGTERM and device loss — but a single
replica is still a single point of total failure: one lost mesh takes
every queued and in-flight request with it. :class:`ServingFleet` is the
layer above: N engines become independent **fault domains** behind a
router that keeps serving — and keeps the PR 9 "every admitted request
leaves under exactly one outcome" invariant — while replicas die,
degrade, drain and rejoin underneath it.

The router owns N :class:`~.engine.ServingEngine` replicas (each may run
its own searched ``(dp, tp, KV-layout)`` plan — heterogeneous plans are
allowed and :func:`plan_replicas` prices each on its own machine model
and per-(chip generation, dtype) calibration table, the PR 8 store) and
drives them in ONE host loop: each **fleet tick** advances every live
replica by one scheduler action via the ``_ServeLoop.tick()`` hook the
ISSUE 11 engine refactor exposed. On top of that loop:

* **load-aware, prefix-aware dispatch** — each queued request is
  scored per replica as estimated drain time MINUS the priced
  cache-affinity saving (cached prefix tokens x the replica's warm
  ``AdmissionController`` EWMA per-token cost; ISSUE 14): the replica
  that can skip the most prefill compute wins until its queueing delay
  outgrows the saving. Migration re-prefills flow through the same
  gate, so a migrated stream lands on the survivor already holding its
  prefix whenever one exists.
* **health-checked failover** — per-replica health
  (``healthy | degraded | quarantined | draining | dead``) driven by a
  probe decode (``ServingEngine.health_probe``) plus passive signals
  (decode quarantines, dispatch timeouts, replica-fatal errors), with a
  per-replica **circuit breaker** (closed -> open after
  ``--circuit-open-after`` consecutive failures -> half-open probe with
  bounded linear backoff, the PR 9 backoff idiom). A circuit-open
  replica receives ZERO dispatches until its half-open probe passes —
  the router stops feeding a sick replica before its queue becomes a
  graveyard.
* **request migration** — a replica that dies mid-decode has its
  in-flight streams harvested (no terminal outcome) and re-submitted to
  survivors, re-prefilled from host-side committed tokens (the PR 9
  ``DecodeStateLostError`` rebuild path, now crossing replica
  boundaries): continuations are bitwise-unchanged under exact decode,
  rng resuming at ``(tag, tokens_emitted)``. Its queued requests
  re-route through the fleet queue.
* **hedged retries** — a request whose replica blows
  ``--hedge-after-pctl`` percent of its EWMA-predicted service time gets
  a bounded hedge on a second replica; first NEW committed token wins,
  the loser is cancelled with no ledger entry (its slot recycled), and
  hedges are capped (``hedge_cap`` outstanding, idle-target-only) so
  they cannot amplify an overload.
* **fleet-level shedding** — the PR 9 admission controller graduates to
  the router: :meth:`ServingFleet.submit` sheds at the fleet door using
  aggregate queued+in-flight token cost across healthy replicas, with
  ``retry_after_ms`` derived from the BEST replica's drain estimate —
  and never 0 while any replica is draining or circuit-open
  (:data:`FLEET_MIN_RETRY_AFTER_MS`), because a 0 hint invites an
  immediate client retry storm into a degraded fleet.
* **rolling drain / rejoin** — :meth:`ServingFleet.drain` wraps the
  PR 9 SIGTERM drain per replica (zero-downtime restarts: in-flight
  requests finish, queued ones re-route); a rejoining replica re-enters
  through half-open probation (probe decode gates it back to healthy).

Chaos: :class:`~..resilience.chaos.FleetChaosPlan` scripts replica
kills, sustained decode-poison degradation, router<->replica partitions,
drains and rejoins — all once-semantics, all runnable on CPU in tier-1
(tests/test_serving_fleet.py). See docs/fleet.md.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.reqtrace import FleetTimeSeries, get_reqtrace
from .engine import ServingEngine, _ServeLoop
from .journal import NOOP_JOURNAL, RequestJournal, journal_from_config
from .resilience import AdmissionController, OverloadError
from .scheduler import (ContinuousBatchScheduler, QueueFullError, Request,
                        ServingRejection, now_ms, remove_by_identity,
                        reserve_rids)
from .tenancy import (QuotaExceededError, TenantRegistry,
                      WeightedFairQueue)

#: health states a replica moves through (docs/fleet.md has the diagram)
FLEET_HEALTH = ("healthy", "degraded", "quarantined", "draining", "dead")

#: lower bound on the fleet door's ``retry_after_ms`` hint while ANY
#: replica is draining, circuit-open or dead (ISSUE 11 small fix): a 0
#: hint — e.g. from a cold EWMA — invites an immediate client retry
#: storm into a fleet that is already degraded.
FLEET_MIN_RETRY_AFTER_MS = 50.0


class FleetCrashed(RuntimeError):
    """The tier-1 in-process stand-in for whole-process death
    (``FleetChaosPlan.crash_at={tick: "hard"}``, ISSUE 20): raised from
    inside the fleet tick so NO drain, finish or ledger path runs —
    exactly what SIGKILL denies a real process. The journal's
    group-commit buffer is dropped first (un-fsynced tail lost), and
    recovery goes through :meth:`ServingFleet.recover` on the journal
    directory."""


class CircuitBreaker:
    """Per-replica dispatch circuit (closed -> open -> half-open).

    ``record_failure`` counts CONSECUTIVE failures; at ``open_after`` the
    circuit opens and stays open for a bounded-linearly growing backoff
    (``backoff_ticks * opens``, capped at ``max_backoff_ticks`` — the
    PR 9 replan-backoff idiom in tick time). ``ready_to_probe`` then
    admits exactly one half-open probe: success closes the circuit,
    failure reopens it with a longer backoff. Failures while already
    open are ignored (they carry no new information and must not push
    the probe point forever into the future)."""

    def __init__(self, open_after: int = 3, backoff_ticks: int = 4,
                 max_backoff_ticks: int = 32):
        self.open_after = max(int(open_after), 1)
        self.backoff_ticks = max(int(backoff_ticks), 1)
        self.max_backoff_ticks = int(max_backoff_ticks)
        self.state = "closed"  # "closed" | "open" | "half_open"
        self.failures = 0      # consecutive, while closed/half-open
        self.opens = 0
        self.half_open_at: Optional[int] = None

    def record_failure(self, tick: int) -> None:
        if self.state == "open":
            return
        self.failures += 1
        if self.state == "half_open" or self.failures >= self.open_after:
            self.state = "open"
            self.opens += 1
            self.half_open_at = tick + min(
                self.backoff_ticks * self.opens, self.max_backoff_ticks)

    def record_success(self) -> None:
        self.failures = 0
        self.state = "closed"
        self.half_open_at = None

    def ready_to_probe(self, tick: int) -> bool:
        # half_open_at None = held open with no scheduled probe (a killed
        # or drained replica re-enters only via rejoin's probation)
        return (self.state == "open" and self.half_open_at is not None
                and tick >= self.half_open_at)

    def half_open(self) -> None:
        self.state = "half_open"

    def force_open(self, half_open_at: Optional[int] = None) -> None:
        """Open without counting a failure (kill/drain transitions)."""
        if self.state != "open":
            self.state = "open"
            self.opens += 1
        self.failures = 0
        self.half_open_at = half_open_at


class FleetReplica:
    """One fault domain: an engine + its scheduler + its serve loop,
    plus the router-side health bookkeeping."""

    def __init__(self, idx: int, engine: ServingEngine,
                 plan=None, open_after: int = 3):
        self.idx = idx
        self.engine = engine
        self.plan = plan
        self.sched: Optional[ContinuousBatchScheduler] = None
        self.loop: Optional[_ServeLoop] = None
        self.health = "healthy"
        self.circuit = CircuitBreaker(open_after=open_after)
        self.dispatches = 0
        self.probes = 0
        self.probe_failures = 0
        self.quarantine_events = 0
        # scripted degrade (FleetChaosPlan.degrade_replica_at): poison one
        # live slot's KV rows every Nth decode step; 0 = off
        self.degrade_every = 0
        self.degrade_counter = 0
        # scripted partition: router<->replica dispatch raises timeouts
        # until this fleet tick; None = reachable
        self.partitioned_until: Optional[int] = None
        # stats of retired serve loops (drain/rejoin rebuilds the loop)
        self.retired_tokens = 0
        self.retired_decode_steps = 0
        # host-overhead seconds of retired loops: [dispatch, device,
        # bookkeep, overlap] (ISSUE 16/17) — the fleet roll-up must not
        # lose the wall split of a loop a drain/rejoin rebuilt
        self.retired_host = [0.0, 0.0, 0.0, 0.0]
        self.retired_syncs = 0

    @property
    def alive(self) -> bool:
        return self.health != "dead"

    def outstanding_tokens(self) -> int:
        """Queued + in-flight remaining tokens on this replica — the
        load-aware dispatch signal."""
        if self.sched is None:
            return 0
        return AdmissionController._backlog_tokens(self.sched)

    def drain_estimate_ms(self) -> float:
        """Estimated time to drain this replica's backlog, from its warm
        EWMA per-token cost (0.0 while the cost model is cold)."""
        if self.sched is None:
            return 0.0
        cost = self.engine.admission.token_cost_ms
        return cost * self.outstanding_tokens() / max(self.sched.n_slots, 1)

    def tokens_generated(self) -> int:
        live = self.loop.stats.tokens_generated if self.loop is not None \
            else 0
        return self.retired_tokens + live

    def decode_steps(self) -> int:
        live = self.loop.stats.decode_steps if self.loop is not None else 0
        return self.retired_decode_steps + live


@dataclasses.dataclass
class _Hedge:
    """One launched hedge pair: ``primary`` is the externally-submitted
    request, ``twin`` its internal copy on a second replica, ``fork`` the
    committed-token count both copies share at launch. First copy to
    commit a NEW token (or finish) wins; the loser is cancelled with no
    ledger entry."""

    primary: Request
    twin: Request
    fork: int
    primary_replica: int
    twin_replica: int
    winner: Optional[Request] = None
    mirrored: bool = False


@dataclasses.dataclass
class FleetStats:
    """Host-side counters of one fleet run — the bench ``fleet_leg`` and
    the StepTelemetry ``fleet`` block read these. ``outcomes`` is the
    FLEET-WIDE ledger over externally-submitted requests (hedge twins
    are internal and never counted)."""

    replicas: int = 0
    ticks: int = 0
    wall_s: float = 0.0
    requests: int = 0
    tokens_generated: int = 0
    outcomes: Dict[str, int] = dataclasses.field(default_factory=dict)
    sheds: int = 0
    dispatches: List[int] = dataclasses.field(default_factory=list)
    migrations: int = 0
    requeued: int = 0
    failovers: int = 0
    hedges: int = 0
    hedge_twin_wins: int = 0
    hedges_cancelled: int = 0
    # prefix-aware routing (ISSUE 14): dispatches whose replica choice
    # was driven by a cache-affinity hit (the chosen replica's radix
    # trie held a prefix of the request), and the token volume matched
    affinity_hits: int = 0
    affinity_tokens: int = 0
    probes: int = 0
    probe_failures: int = 0
    circuit_opens: int = 0
    drains: int = 0
    rejoins: int = 0
    degrade_poisons: int = 0
    # (tick, replica, from, to, reason) — the health-transition trail
    health_transitions: List[Tuple[int, int, str, str, str]] = \
        dataclasses.field(default_factory=list)
    kill_ticks: List[int] = dataclasses.field(default_factory=list)
    # tokens committed per fleet tick — the failover-recovery series
    tokens_history: List[int] = dataclasses.field(default_factory=list)
    # host-overhead accounting (ISSUE 16, ROADMAP item 5): the replica
    # loops' dispatch/device/bookkeeping splits summed at _finish, plus
    # the router's own host work (dispatch, probes, hedges) in
    # host_dispatch_s — ROADMAP item 5's fleet-level baseline
    host_dispatch_s: float = 0.0
    host_device_s: float = 0.0
    host_bookkeep_s: float = 0.0
    # host work overlapped with in-flight device steps (the async serve
    # loop, ISSUE 17): wall that exists but is NOT overhead — it widens
    # the denominator only
    host_overlap_s: float = 0.0
    # blocking host transfers across all replica loops (ISSUE 17): the
    # fleet analog of ServingStats.host_syncs
    host_syncs: int = 0
    # multi-tenant accounting (ISSUE 19): per-tenant ledgers over
    # requests that carried an explicit tenant label — tenant_outcomes
    # conserves exactly-one-outcome per tenant (tier-1 pins it);
    # quota_sheds counts door rejections under the token-rate quota
    tenant_requests: Dict[str, int] = dataclasses.field(default_factory=dict)
    tenant_outcomes: Dict[str, Dict[str, int]] = \
        dataclasses.field(default_factory=dict)
    tenant_tokens: Dict[str, int] = dataclasses.field(default_factory=dict)
    quota_sheds: int = 0
    # requests injected by the FleetChaosPlan traffic-step/tenant-storm
    # generator (they ARE externally-visible requests and ride the same
    # ledgers; this just says how many came from chaos)
    storm_requests: int = 0
    # autoscaler (ISSUE 19): (tick, "up"|"down", serving replicas after)
    autoscale_ups: int = 0
    autoscale_downs: int = 0
    autoscale_events: List[Tuple[int, str, int]] = \
        dataclasses.field(default_factory=list)
    # waiting requests per fleet tick (door + replica scheduler queues:
    # dispatch drains the door eagerly, so the door alone sees nothing)
    # — the surge-recovery series
    queue_depth_history: List[int] = dataclasses.field(default_factory=list)

    def count_tenant_outcome(self, tenant: Optional[str],
                             outcome: str) -> None:
        if not tenant:
            return  # untenanted traffic stays aggregate-only
        led = self.tenant_outcomes.setdefault(tenant, {})
        led[outcome] = led.get(outcome, 0) + 1

    def surge_recovery_ticks(self, step_tick: int,
                             baseline: Optional[int] = None
                             ) -> Optional[int]:
        """Ticks after ``step_tick`` until the waiting-request depth
        first returns to its pre-step level (or ``baseline``) — the
        traffic-surge analog of :meth:`recovery_ticks`. None when it
        never drained."""
        hist = self.queue_depth_history
        if step_tick >= len(hist):
            return None
        if baseline is None:
            baseline = hist[step_tick - 1] if step_tick > 0 else 0
        for t in range(step_tick + 1, len(hist)):
            if hist[t] <= baseline:
                return t - step_tick
        return None

    def count_outcome(self, outcome: str, n: int = 1) -> None:
        if n:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + int(n)

    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.wall_s if self.wall_s > 0 \
            else 0.0

    def occupancy(self, total_slots: int) -> float:
        """Fraction of decode-slot-ticks that produced a token, over the
        whole run (fleet analog of ``ServingStats.batch_occupancy``)."""
        denom = self.ticks * max(total_slots, 1)
        return min(self.tokens_generated / denom, 1.0) if denom else 0.0

    def recovery_ticks(self, kill_tick: int, frac: float,
                       window: int = 4) -> Optional[int]:
        """Ticks after ``kill_tick`` until the trailing-``window`` mean
        tokens/tick first reaches ``frac`` x the pre-kill trailing mean
        — the failover-recovery-time metric. None when it never
        recovered (or the kill tick has no pre-history)."""
        hist = self.tokens_history
        pre = hist[max(kill_tick - window, 0):kill_tick]
        if not pre or kill_tick >= len(hist):
            return None
        target = frac * (sum(pre) / len(pre))
        for t in range(kill_tick + 1, len(hist) + 1):
            w = hist[max(t - window, kill_tick):t]
            if w and sum(w) / len(w) >= target:
                return t - kill_tick
        return None

    def host_overhead_fraction(self) -> Optional[float]:
        """Fleet-wide fraction of serve wall spent on the host rather
        than waiting on devices (ServingStats analog; ISSUE 16)."""
        total = self.host_dispatch_s + self.host_device_s + \
            self.host_bookkeep_s + self.host_overlap_s
        if total <= 0.0:
            return None
        return (self.host_dispatch_s + self.host_bookkeep_s) / total

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "replicas": self.replicas,
            "ticks": self.ticks,
            "requests": self.requests,
            "tokens_generated": self.tokens_generated,
            "wall_s": round(self.wall_s, 4),
            "tokens_per_s": round(self.tokens_per_s(), 2),
            "dispatches": list(self.dispatches),
        }
        hof = self.host_overhead_fraction()
        if hof is not None:
            out["host_overhead_fraction"] = round(hof, 4)
        if self.host_syncs:
            out["host_syncs"] = self.host_syncs
        if self.outcomes:
            out["outcomes"] = dict(self.outcomes)
        for k in ("sheds", "migrations", "requeued", "failovers", "hedges",
                  "hedge_twin_wins", "hedges_cancelled", "affinity_hits",
                  "affinity_tokens", "probes",
                  "probe_failures", "circuit_opens", "drains", "rejoins",
                  "degrade_poisons", "quota_sheds", "storm_requests"):
            v = getattr(self, k)
            if v:
                out[k] = v
        if self.tenant_outcomes:
            out["tenants"] = {
                t: {"requests": self.tenant_requests.get(t, 0),
                    "tokens": self.tenant_tokens.get(t, 0),
                    "outcomes": dict(led)}
                for t, led in sorted(self.tenant_outcomes.items())}
        if self.autoscale_ups or self.autoscale_downs:
            out["autoscale"] = {"ups": self.autoscale_ups,
                                "downs": self.autoscale_downs,
                                "events": list(self.autoscale_events)}
        if self.health_transitions:
            out["health_transitions"] = len(self.health_transitions)
        return out


def lint_replica_plans(pcg, plans: Sequence) -> None:
    """Per-replica plan lint before the fleet starts (ISSUE 11
    satellite): run ShardLint's FF005 serving-graph check and the FF006
    shape/divisibility dataflow against EACH replica's (possibly
    heterogeneous) plan at fleet construction, so one replica's
    fused-stateful or indivisible plan fails fast WITH THE REPLICA
    NAMED instead of surfacing as mid-serve garbage on 1/N of traffic.
    ``plans`` entries may be ``ServingPlan`` (materialized via
    ``to_strategy``), executor ``Strategy`` objects, or None (naive dp
    — nothing sharded, nothing to misdivide)."""
    from ..analysis import (AnalysisReport, StaticAnalysisError,
                            check_serving_graph, check_shapes)
    from ..analysis.report import Diagnostic

    diags: List[Diagnostic] = []
    ff005 = check_serving_graph(pcg)
    for i, plan in enumerate(plans):
        for d in ff005:
            diags.append(dataclasses.replace(
                d, message=f"replica {i}: {d.message}"))
        if plan is None:
            continue
        strategy = plan.to_strategy(pcg) if hasattr(plan, "to_strategy") \
            else plan
        if strategy is None:
            continue
        for d in check_shapes(pcg, strategy):
            diags.append(dataclasses.replace(
                d, message=f"replica {i}: {d.message}"))
    if diags:
        raise StaticAnalysisError(
            AnalysisReport(diagnostics=diags,
                           checked=("FF005", "FF006")),
            context="fleet per-replica plan lint")


def plan_replicas(pcg, config, replica_devices: Sequence[int],
                  generations: Optional[Sequence[str]] = None) -> List:
    """One searched ServingPlan per replica — heterogeneous device
    counts and chip generations allowed. Each replica is priced on its
    OWN machine model, and (when ``--calibration-dir`` is set) its own
    persistent per-(chip generation, dtype) calibration table — the
    PR 8 store — so a v5e replica and a v6e replica are costed honestly
    rather than by one blended ruler."""
    from ..search.calibration import dtype_label
    from ..search.machine_model import TPUMachineModel
    from ..search.simulator import Simulator
    from .search import serving_search

    plans = []
    cal_dir = getattr(config, "calibration_dir", "") or None
    for i, n_dev in enumerate(replica_devices):
        gen = generations[i] if generations else None
        machine = TPUMachineModel.from_generation(gen, int(n_dev)) \
            if gen else TPUMachineModel.detect(int(n_dev))
        sim = Simulator(machine, calibration_dir=cal_dir,
                        dtype_label=dtype_label(config))
        plans.append(serving_search(pcg, config, int(n_dev),
                                    machine=machine, sim=sim))
    return plans


class ServingFleet:
    """N ServingEngine fault domains behind one load-aware,
    health-checked router (module docstring has the full story).

    The replicas share one compiled model (the tier-1 CPU shape; on real
    meshes each replica owns its device slice and searched plan — the
    ``plans`` argument carries the per-replica layouts and is linted at
    construction). ``generate``/``submit``+``run`` mirror the engine's
    API one level up."""

    def __init__(self, model, n_replicas: Optional[int] = None,
                 n_slots: Optional[int] = None,
                 max_decode_len: Optional[int] = None,
                 max_queue: int = 64, eos_id: Optional[int] = None,
                 exact_decode: bool = False,
                 plans: Optional[Sequence] = None,
                 buckets: Optional[Sequence[int]] = None,
                 clock=None, serve_loop: Optional[str] = None,
                 journal=None):
        assert model.executor is not None, "call model.compile() first"
        config = model.config
        n = int(n_replicas or getattr(config, "fleet_replicas", 0) or 2)
        if n < 1:
            raise ValueError(f"a fleet needs >= 1 replica (got {n})")
        if plans is not None and len(plans) != n:
            raise ValueError(
                f"one plan per replica: got {len(plans)} plans for {n} "
                "replicas")
        if plans is not None:
            # satellite: fail fast at construction, replica named —
            # before any engine (or its compile cache) exists
            lint_replica_plans(model.executor.pcg, plans)
        self.model = model
        self.config = config
        self.n_replicas = n
        self.max_queue = int(max_queue)
        self.eos_id = eos_id
        self.shed_policy = (getattr(config, "shed_policy", "off") or "off")
        self.hedge_after_pctl = float(
            getattr(config, "hedge_after_pctl", 0.0) or 0.0)
        self.health_probe_every = int(
            getattr(config, "health_probe_every", 16) or 16)
        open_after = int(getattr(config, "circuit_open_after", 3) or 3)
        self.replicas = [
            FleetReplica(i, ServingEngine(
                model, n_slots=n_slots, max_decode_len=max_decode_len,
                buckets=buckets, max_queue=max_queue, eos_id=eos_id,
                exact_decode=exact_decode, serve_loop=serve_loop),
                plan=(plans[i] if plans else None),
                open_after=open_after)
            for i in range(n)]
        for rep in self.replicas:
            rep.engine.plan = rep.plan or rep.engine.plan
        # hedge amplification cap: at most this many hedges outstanding,
        # and a hedge only targets an IDLE replica (free slot, empty
        # queue) — a hedge must never displace first-try traffic
        self.hedge_cap = max(1, n - 1)
        # multi-tenant door (ISSUE 19, docs/multitenant.md): the tier
        # registry (policies + quota buckets) and the weighted fair
        # queue replacing the single FIFO — untenanted traffic rides
        # the standard tier and degenerates to exact FIFO
        self.tenants = TenantRegistry.from_config(config)
        self.queue: WeightedFairQueue = WeightedFairQueue(self.tenants)
        # backlog-forecast autoscaler (docs/multitenant.md state
        # machine): off unless --autoscale on; bounds default to
        # [initial N, 2N]; hysteresis = the up/down factor gap plus the
        # consecutive-tick patience plus a post-action cooldown
        self.autoscale = (getattr(config, "autoscale", "off")
                          or "off") == "on"
        self.min_replicas = int(getattr(config, "min_replicas", 0)
                                or 0) or n
        self.max_replicas = max(
            int(getattr(config, "max_replicas", 0) or 0) or 2 * n,
            self.min_replicas)
        self.autoscale_up_after = 2      # consecutive over-SLO ticks
        self.autoscale_down_after = 8    # consecutive slack ticks
        self.autoscale_cooldown = 4      # ticks after any action
        self.autoscale_down_factor = 0.3
        self._forecast_ewma: Optional[float] = None
        self._surge_ticks = 0
        self._slack_ticks = 0
        self._cooldown_until = 0
        self._storm_seq = 0
        self.drained_requests: List[Request] = []
        self.clock = clock if clock is not None else now_ms
        # crash-durable door (ISSUE 20, docs/durability.md): an explicit
        # journal argument wins (recover() hands over the scanned one);
        # otherwise --request-journal DIR builds a fresh journal; the
        # default is the shared allocation-free NOOP_JOURNAL singleton.
        self.journal = (journal if journal is not None
                        else journal_from_config(config, clock=self.clock))
        self._journal_replaying = False
        self.chaos = None
        self.stats = FleetStats(replicas=n, dispatches=[0] * n)
        self.tick_no = 0
        self.max_idle_ticks = 256
        self._requests: List[Request] = []
        self._hedges: List[_Hedge] = []
        self._hedged_ids: set = set()
        self._adopted: List[_Hedge] = []
        self._fleet_draining = False
        self._running = False
        self._serve_args: Dict[str, Any] = {}
        self._tick_tokens = 0
        # ISSUE 16: fleet time-series ring buffers (created lazily in
        # run() when request tracing is live, or attached by a caller)
        # and the router's own host-time outside replica ticks
        self.timeseries: Optional[FleetTimeSeries] = None
        self._host_router_s = 0.0

    # ------------------------------------------------------------- obs hooks
    def _tracer(self):
        return self.model._obs_tracer()

    def _set_health(self, rep: FleetReplica, new: str, reason: str) -> None:
        old = rep.health
        if old == new:
            return
        rep.health = new
        self.stats.health_transitions.append(
            (self.tick_no, rep.idx, old, new, reason))
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("replica_health", replica=rep.idx, tick=self.tick_no,
                         from_state=old, to_state=new, reason=reason)

    # ------------------------------------------------------------- admission
    def total_slots(self) -> int:
        return sum(r.engine.n_slots for r in self.replicas)

    def _stamp_deadline(self, req: Request) -> None:
        timeout = float(getattr(self.config, "request_timeout_ms", 0.0)
                        or 0.0)
        if req.deadline_ms is None and timeout > 0:
            req.deadline_ms = timeout

    def _healthy(self) -> List[FleetReplica]:
        return [r for r in self.replicas
                if r.alive and r.health != "draining"
                and r.circuit.state == "closed"]

    def retry_after_ms(self, tenant: Optional[str] = None) -> float:
        """The fleet door's backoff hint: the MINIMUM over healthy
        replicas' drain estimates (the best replica frees up first — a
        fleet sick on one replica must not shed like a fleet sick
        everywhere), floored at :data:`FLEET_MIN_RETRY_AFTER_MS`
        whenever any replica is draining, circuit-open or dead (ISSUE 11
        small fix: the 0 hint of a cold EWMA would invite an immediate
        retry storm into a degraded fleet).

        With ``tenant`` the hint additionally prices that tenant's OWN
        virtual queue position under WFQ (ISSUE 19 satellite): the door
        tokens scheduled ahead of a new request of this tenant, at the
        tenant's per-token cost. Without it a rejected batch client
        would be handed the interactive tenant's optimistic hint and
        resubmit straight into another rejection."""
        healthy = self._healthy()
        est = min((r.drain_estimate_ms() for r in healthy), default=0.0)
        if tenant is not None and healthy:
            ahead = self.queue.backlog_tokens_ahead(tenant)
            cost = max((r.engine.admission.token_cost_ms_for(tenant)
                        for r in healthy), default=0.0)
            capacity = sum(r.engine.n_slots for r in healthy)
            est += cost * ahead / max(capacity, 1)
        degraded = any(
            (not r.alive) or r.health == "draining"
            or r.circuit.state != "closed" for r in self.replicas)
        if degraded:
            est = max(est, FLEET_MIN_RETRY_AFTER_MS)
        return est

    def _total_queued(self) -> int:
        return len(self.queue) + sum(
            r.sched.queued for r in self.replicas
            if r.alive and r.sched is not None)

    def submit(self, req: Request) -> None:
        """Fleet-door admission: deadline stamp + fleet-level shed gate +
        enqueue for load-aware dispatch. Raises ``OverloadError`` (policy
        shed on aggregate backlog) or ``QueueFullError`` (hard fleet
        queue wall) — both ``ServingRejection`` carrying the
        fleet-derived ``retry_after_ms`` — and either way the request is
        ledgered (outcome ``shed``): exactly-one-outcome holds at the
        fleet door too.

        Journaled mode (ISSUE 20): the submit record is WRITTEN AHEAD
        of every admission decision, and a rid the journal has already
        seen — a client retrying a request that survived the crash, or
        is already finished — dedupes silently at the door instead of
        double-admitting. Recovery replay bypasses the dedupe (the
        replayed rids are exactly the ones already journaled)."""
        jr = self.journal
        if jr.enabled and not self._journal_replaying:
            if not jr.log_submit(req):
                # rid-keyed idempotent dedupe: this request is already
                # journaled (pending or finished) — a retry must not
                # enter the door twice
                return
        self._requests.append(req)
        pol = self.tenants.policy(req.tenant)
        if req.tenant:
            self.stats.tenant_requests[req.tenant] = \
                self.stats.tenant_requests.get(req.tenant, 0) + 1
        # tier deadline default (ISSUE 19): most specific wins — an
        # explicit per-request deadline, then the tenant tier's default,
        # then --request-timeout-ms via _stamp_deadline
        if req.deadline_ms is None and pol.deadline_ms > 0:
            req.deadline_ms = float(pol.deadline_ms)
        self._stamp_deadline(req)
        # the relative deadline budget starts at the FLEET DOOR: waiting
        # here burns it exactly like waiting in a replica queue (the
        # dispatch preserves this stamp across sched.submit's re-stamp)
        if not req.submit_ms:
            req.submit_ms = float(self.clock())
        rt = get_reqtrace()
        if rt.enabled:
            # the timeline opens at the FLEET door (a later replica
            # sched.submit adds a second "submit" note = re-queue edge)
            rt.note(req.rid, "submit", req.submit_ms,
                    prompt_len=req.prompt_len,
                    max_new=req.max_new_tokens,
                    deadline_ms=req.deadline_ms, replica=None,
                    tenant=req.tenant)
        # token-rate quota (docs/multitenant.md): charged on the
        # REQUESTED tokens before any shed gate — a quota breach is the
        # tenant's own doing and must not consume shed headroom
        if pol.quota_tokens_per_s > 0:
            ok, wait_ms = self.tenants.charge(
                req.tenant, req.max_new_tokens, float(self.clock()))
            if not ok:
                self.stats.quota_sheds += 1
                req.outcome = "quota_exceeded"
                if jr.enabled:
                    jr.log_outcome(req)
                self.stats.count_tenant_outcome(req.tenant,
                                                "quota_exceeded")
                if rt.enabled:
                    rt.finish(req.rid, float(self.clock()),
                              "quota_exceeded", policy="quota",
                              tenant=req.tenant,
                              refill_ms=round(wait_ms, 3))
                raise QuotaExceededError(
                    f"request {req.rid} rejected: tenant "
                    f"{pol.name!r} token-rate quota "
                    f"({pol.quota_tokens_per_s:g} tokens/s) exhausted",
                    queued=self._total_queued(), active=0,
                    retry_after_ms=max(
                        wait_ms, self.retry_after_ms(req.tenant)))
        healthy = self._healthy()
        policy = self.shed_policy
        total_queued = self._total_queued()
        if policy == "queue":
            highwater = self._shed_highwater(pol)
            if total_queued >= highwater:
                self.stats.sheds += 1
                req.outcome = "shed"
                if jr.enabled:
                    jr.log_outcome(req)
                self.stats.count_tenant_outcome(req.tenant, "shed")
                if rt.enabled:
                    rt.finish(req.rid, float(self.clock()), "shed",
                              policy="queue", queued=total_queued,
                              highwater=highwater, tenant=req.tenant)
                raise OverloadError(
                    f"request {req.rid} shed at the fleet door (policy "
                    f"'queue'): aggregate queue depth {total_queued} >= "
                    f"high-water {highwater} for tier "
                    f"{pol.name!r} (fleet max_queue {self.max_queue})",
                    queued=total_queued,
                    active=sum(r.sched.active for r in self.replicas
                               if r.sched is not None),
                    retry_after_ms=self.retry_after_ms(req.tenant))
        elif policy == "deadline" and req.deadline_ms is not None \
                and req.deadline_ms > 0 and healthy:
            backlog = sum(r.outstanding_tokens() for r in healthy)
            capacity = sum(r.engine.n_slots for r in healthy)
            cost = min(
                (r.engine.admission.token_cost_ms_for(req.tenant)
                 for r in healthy
                 if r.engine.admission.token_cost_ms_for(req.tenant) > 0),
                default=0.0)
            est = cost * (backlog / max(capacity, 1) + req.max_new_tokens)
            if est > req.deadline_ms:
                self.stats.sheds += 1
                req.outcome = "shed"
                if jr.enabled:
                    jr.log_outcome(req)
                self.stats.count_tenant_outcome(req.tenant, "shed")
                if rt.enabled:
                    # the PRICED estimate that made the decision rides
                    # on the terminal record — sheds are explainable
                    rt.finish(req.rid, float(self.clock()), "shed",
                              policy="deadline", est_ms=round(est, 3),
                              deadline_ms=req.deadline_ms,
                              tenant=req.tenant)
                raise OverloadError(
                    f"request {req.rid} shed at the fleet door (policy "
                    f"'deadline'): estimated completion {est:.1f} ms "
                    f"across {len(healthy)} healthy replica(s) exceeds "
                    f"deadline {req.deadline_ms:.1f} ms",
                    queued=total_queued, active=0,
                    retry_after_ms=self.retry_after_ms(req.tenant))
        if total_queued >= self.max_queue:
            self.stats.sheds += 1
            req.outcome = "shed"
            if jr.enabled:
                jr.log_outcome(req)
            self.stats.count_tenant_outcome(req.tenant, "shed")
            if rt.enabled:
                rt.finish(req.rid, float(self.clock()), "shed",
                          policy="hard_wall", queued=total_queued,
                          tenant=req.tenant)
            raise QueueFullError(
                f"fleet queue full ({total_queued} waiting across "
                f"{self.n_replicas} replicas, shed policy "
                f"'{policy}'); retry later",
                queued=total_queued, active=0,
                retry_after_ms=self.retry_after_ms(req.tenant))
        self.queue.append(req)

    def _shed_highwater(self, pol) -> int:
        """Per-tier queue-shed threshold (docs/multitenant.md): the
        standard tier keeps the pre-tenant ``max_queue // 2`` high-water
        exactly; lower shed priority halves it (batch backs off first,
        preserving headroom for the tiers above), higher priority sheds
        only at the hard wall."""
        base = max(self.max_queue // 2, 1)
        if pol.shed_priority <= 0:
            return max(base // 2, 1)
        if pol.shed_priority == 1:
            return base
        return self.max_queue

    # -------------------------------------------------------------- lifecycle
    def _make_loop(self, rep: FleetReplica) -> None:
        """(Re)build a replica's scheduler + serve loop. Per-replica rng
        base seeds are IDENTICAL across replicas — streams key on
        (submission tag, tokens emitted), so a migrated or hedged stream
        continues bit-identically wherever it lands."""
        if rep.loop is not None:
            # retire the old loop's throughput into the replica's
            # cumulative counters before dropping it
            rep.retired_tokens += rep.loop.stats.tokens_generated
            rep.retired_decode_steps += rep.loop.stats.decode_steps
            rep.retired_host[0] += rep.loop.stats.host_dispatch_s
            rep.retired_host[1] += rep.loop.stats.host_device_s
            rep.retired_host[2] += rep.loop.stats.host_bookkeep_s
            rep.retired_host[3] += rep.loop.stats.host_overlap_s
            rep.retired_syncs += rep.loop.stats.host_syncs
        eng = rep.engine
        sched = ContinuousBatchScheduler(
            n_slots=eng.n_slots, max_queue=eng.max_queue,
            buckets=eng.buckets, max_len=eng.max_decode_len,
            clock=eng.resilience_clock or self.clock)
        sched.replica_idx = rep.idx  # request-trace notes carry the domain
        if self.journal.enabled and self.journal.commit_every > 0:
            # progress journaling rides the scheduler's commit point
            # (--journal-commit-every tokens batch into one record);
            # journal-off leaves on_commit None — the hot path stays
            # one never-taken branch, allocation-free
            sched.on_commit = self.journal.log_progress
        rep.sched = sched
        a = self._serve_args
        rep.loop = eng.start_serve(
            sched, temperature=a.get("temperature", 0.0),
            top_k=a.get("top_k", 0), seed=a.get("seed", 0),
            publish_telemetry=False)
        # the router health-checks every replica: keep the guarded decode
        # live so a poisoned slot quarantines instead of committing junk
        rep.loop.res.force_armed = True
        rep.loop.res_active = True
        rep.loop.guard = True
        eng._last_guard = True

    def _start(self, temperature: float, top_k: int, seed: int) -> None:
        self._serve_args = {"temperature": temperature, "top_k": top_k,
                            "seed": seed}
        if self.journal.enabled:
            # the run record makes recovery self-contained: the exact
            # sampling configuration rides in the journal
            self.journal.log_run(**self._serve_args)
        for rep in self.replicas:
            if rep.loop is None:
                self._make_loop(rep)
        self._running = True

    def drain(self, replica: int) -> None:
        """Rolling zero-downtime restart, one fault domain at a time:
        wraps the PR 9 graceful drain — the replica stops admitting, its
        in-flight requests finish inside ``--drain-grace-s``, its queued
        requests re-route through the fleet queue, and the replica goes
        out of rotation until :meth:`rejoin`."""
        rep = self.replicas[replica]
        if not rep.alive:
            raise ValueError(f"replica {replica} is dead; rejoin() it "
                             "instead of draining")
        if rep.loop is None:
            self._make_loop(rep)
        assert rep.loop is not None
        rep.loop.request_drain()
        self._set_health(rep, "draining", "drain_requested")
        self.stats.drains += 1

    def rejoin(self, replica: int) -> None:
        """Bring a killed/drained replica back — through half-open
        probation: the circuit stays open until the next probe decode
        passes, so a still-sick replica never rejoins rotation. A still-
        alive (degraded/quarantined) replica may hold work the circuit
        deliberately left in place: it is rescued to the fleet queue
        BEFORE the rebuild — the restart must not lose streams."""
        rep = self.replicas[replica]
        inflight, queued = self._harvest(rep)
        for req in reversed(queued):
            self.queue.appendleft(req)
        for req in reversed(inflight):
            self.queue.appendleft(req)
        self.stats.migrations += len(inflight)
        self.stats.requeued += len(queued)
        rep.degrade_every = 0
        rep.degrade_counter = 0
        rep.partitioned_until = None
        rep.engine.reset_decode_pool()
        self._make_loop(rep)
        rep.circuit.force_open(half_open_at=self.tick_no + 1)
        self._set_health(rep, "quarantined", "rejoin_probation")
        self.stats.rejoins += 1

    # --------------------------------------------------------------- routing
    def _dispatchable(self, rep: FleetReplica) -> bool:
        return (rep.alive and rep.loop is not None
                and rep.health != "draining"
                and rep.circuit.state == "closed"
                and (rep.partitioned_until is None
                     or self.tick_no >= rep.partitioned_until)
                and not self._fleet_draining)

    def _dispatch(self) -> None:
        """Prefix-aware, load-aware routing (ISSUE 14): each queued
        request is scored per replica as estimated drain time MINUS the
        priced cache-affinity saving — the tokens of its prompt the
        replica's radix trie already holds, times that replica's EWMA
        per-token cost (prefilling them there costs nothing; doing it
        on a trie-cold replica throws the win away — and migration
        re-prefills flow through the same gate, so survivors' tries are
        consulted). Pricing rather than strict affinity-first keeps the
        router honest under load: a bounded prefill saving can never
        buy unbounded queueing on one warm replica. Raw affinity, then
        outstanding tokens, then index, stay the deterministic
        tie-breaks (a cold cost model scores every replica 0, where
        affinity alone decides). Expired door-queued requests are
        dropped first
        (outcome ``deadline_exceeded``) — a request stuck at the door
        while every circuit is open must not be served seconds past its
        deadline with zero misses recorded."""
        now = self.clock()
        rt = get_reqtrace()
        expired = [r for r in self.queue if r.expired(now)]
        for req in expired:
            remove_by_identity(self.queue, req)
            req.outcome = "deadline_exceeded"
            req.done = True
            if self.journal.enabled:
                self.journal.log_outcome(req)
            if rt.enabled:
                # dropped at the door, never reaches a scheduler _finish
                rt.finish(req.rid, float(now), "deadline_exceeded",
                          reason="door_expired",
                          new_tokens=len(req.generated))
        while self.queue:
            targets = [r for r in self.replicas
                       if self._dispatchable(r) and r.sched is not None
                       and r.sched.queued < r.sched.max_queue]
            if not targets:
                return
            req = self.queue.popleft()
            # hoist the prompt materialization (np.concatenate) out of
            # the per-replica probe loop
            toks = req.current_prompt()
            cap = req.effective_len - 1
            aff = {r.idx: r.engine.prefix_peek(toks, cap=cap)
                   for r in targets}
            # the affinity term is PRICED, not absolute: a cached
            # prefix is worth its skipped prefill compute (matched
            # tokens x the replica's EWMA per-token cost), so the
            # effective score is drain-time minus that saving — a
            # warm-trie replica loses the request the moment its
            # queueing delay exceeds what the cache would save
            # (concentrating unbounded traffic on one replica for a
            # bounded prefill win would invert the feature). With a
            # cold EWMA every term is 0 and the raw affinity breaks
            # the tie.
            def score(r):
                cost = r.engine.admission.token_cost_ms
                return (r.drain_estimate_ms() - aff[r.idx] * cost,
                        -aff[r.idx], r.outstanding_tokens(), r.idx)

            rep = min(targets, key=score)
            if aff[rep.idx] > 0:
                self.stats.affinity_hits += 1
                self.stats.affinity_tokens += aff[rep.idx]
                tracer = self._tracer()
                if tracer.enabled:
                    tracer.event("fleet_affinity", rid=req.rid,
                                 tick=self.tick_no, replica=rep.idx,
                                 tokens=aff[rep.idx])
            assert rep.loop is not None and rep.sched is not None
            rep.loop.res.stamp_deadline(req)
            # a migrated/rescued request already carries a submit stamp:
            # preserve it across the re-dispatch — sched.submit would
            # re-stamp and silently restart the relative deadline budget
            # exactly when replicas fail (the engine's own quarantine
            # retry preserves the budget; migration must match)
            prior_submit = req.submit_ms
            try:
                rep.sched.submit(req)
            except (ValueError, ServingRejection):
                # a migrated stream whose prompt+committed tokens no
                # bucket covers can re-enter nowhere: preempted, exactly
                # once (the caller keeps the partial continuation).
                # ServingRejection covers the ISSUE 12 max-context bound
                # (ContextOverflowError) — every replica shares the
                # model's position table, so no other replica can take
                # it either; one request must never crash the fleet
                req.outcome = "preempted"
                req.done = True
                if self.journal.enabled:
                    self.journal.log_outcome(req)
                if rt.enabled:
                    rt.finish(req.rid, float(self.clock()), "preempted",
                              reason="unadmittable",
                              new_tokens=len(req.generated))
                continue
            if prior_submit:
                req.submit_ms = prior_submit
            rep.dispatches += 1
            self.stats.dispatches[rep.idx] += 1

    # ---------------------------------------------------------------- health
    def _circuit_failure(self, rep: FleetReplica, reason: str,
                         n: int = 1) -> None:
        was_open = rep.circuit.state == "open"
        for _ in range(max(n, 1)):
            rep.circuit.record_failure(self.tick_no)
        if rep.circuit.state == "open" and not was_open:
            self.stats.circuit_opens += 1
            if rep.health in ("healthy", "degraded"):
                self._set_health(rep, "quarantined", reason)
            # stop feeding the sick replica AND rescue what was already
            # fed: its queued requests (including engine-level quarantine
            # retries parked at its queue front) re-route through the
            # fleet queue to a healthy replica — exact-decode streams
            # continue bitwise wherever they land. In-flight slots stay:
            # they are mid-stream and the replica may still finish them.
            if rep.sched is not None and rep.sched.queued:
                rescued = list(rep.sched.queue)
                rep.sched.queue.clear()
                for req in reversed(rescued):
                    self.queue.appendleft(req)
                self.stats.requeued += len(rescued)
        elif rep.health == "healthy":
            self._set_health(rep, "degraded", reason)

    def _circuit_success(self, rep: FleetReplica) -> None:
        """Passive clean-decode signal: resets the consecutive-failure
        count on a CLOSED circuit only. An open (or half-open) circuit
        re-closes exclusively through the half-open probe — a
        quarantined replica still finishing its in-flight slots must
        not talk itself back into rotation with one clean decode."""
        if rep.circuit.state != "closed":
            return
        rep.circuit.record_success()
        if rep.health == "degraded":
            self._set_health(rep, "healthy", "clean_decode")

    def _probe(self, rep: FleetReplica) -> bool:
        """One probe decode against the replica (through the partition
        shim: an unreachable replica fails its probe). Gates half-open
        -> closed; periodic probes on closed circuits feed the passive
        failure count instead."""
        half_open = rep.circuit.state == "open"
        if half_open:
            rep.circuit.half_open()
        reachable = (rep.partitioned_until is None
                     or self.tick_no >= rep.partitioned_until)
        ok = bool(reachable and rep.alive and rep.engine.health_probe())
        rep.probes += 1
        self.stats.probes += 1
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("replica_probe", replica=rep.idx,
                         tick=self.tick_no, ok=ok, half_open=half_open)
        if ok:
            rep.circuit.record_success()
            if rep.health in ("degraded", "quarantined"):
                self._set_health(rep, "healthy", "probe_pass")
        else:
            rep.probe_failures += 1
            self.stats.probe_failures += 1
            self._circuit_failure(rep, "probe_fail")
        return ok

    def _run_probes(self) -> None:
        tick = self.tick_no
        for rep in self.replicas:
            if not rep.alive or rep.health == "draining" \
                    or rep.loop is None:
                continue
            if rep.circuit.ready_to_probe(tick):
                self._probe(rep)
            elif rep.circuit.state == "closed" and self.health_probe_every \
                    and tick > 0 and tick % self.health_probe_every == 0:
                self._probe(rep)

    # ------------------------------------------------------------- autoscale
    def _slo_target_ms(self) -> float:
        """The SLO the forecast is judged against: the TIGHTEST deadline
        present in current traffic (door + in-flight), falling back to
        --request-timeout-ms. The tier with the least headroom sets the
        bar — scaling for the batch tier's deadline while interactive
        burns would invert the feature."""
        deadlines = [float(r.deadline_ms) for r in self.queue
                     if r.deadline_ms and r.deadline_ms > 0]
        for rep in self.replicas:
            if rep.alive and rep.sched is not None:
                deadlines.extend(
                    float(r.deadline_ms)
                    for r in list(rep.sched.queue)
                    + [s for s in rep.sched.slots if s is not None]
                    if r.deadline_ms and r.deadline_ms > 0)
        if deadlines:
            return min(deadlines)
        return float(getattr(self.config, "request_timeout_ms", 0.0)
                     or 0.0)

    def _serving_replicas(self) -> List[FleetReplica]:
        return [r for r in self.replicas
                if r.alive and r.health != "draining"]

    def _waiting_requests(self) -> int:
        """Requests admitted but not yet in a decode slot, fleet-wide:
        the door PLUS the replica scheduler queues (dispatch drains the
        door eagerly, so the door alone under-counts a surge)."""
        return len(self.queue) + sum(
            r.sched.queued for r in self.replicas
            if r.alive and r.sched is not None)

    def _autoscale_tick(self) -> None:
        """Backlog-forecast autoscaler (docs/multitenant.md has the state
        machine): forecast = EWMA of (per-token cost x total outstanding
        tokens / serving slots) — the time the current backlog needs to
        drain. Over-SLO for ``autoscale_up_after`` consecutive ticks
        grows the pool (through half-open probation, like rejoin); under
        ``autoscale_down_factor`` x SLO for ``autoscale_down_after``
        ticks shrinks it through the existing migrate-and-drain. A
        cooldown after each action keeps the controller from flapping on
        its own transient."""
        serving = self._serving_replicas()
        slots = sum(r.engine.n_slots for r in serving)
        cost = max((r.engine.admission.token_cost_ms for r in serving),
                   default=0.0)
        door = sum(r.max_new_tokens - len(r.generated)
                   for r in self.queue)
        backlog = door + sum(r.outstanding_tokens() for r in serving)
        forecast = cost * backlog / max(slots, 1)
        if self._forecast_ewma is None:
            self._forecast_ewma = forecast
        else:
            self._forecast_ewma += 0.2 * (forecast - self._forecast_ewma)
        slo = self._slo_target_ms()
        if slo > 0:
            over = self._forecast_ewma > slo
            under = self._forecast_ewma < self.autoscale_down_factor * slo \
                and len(self.queue) == 0
        else:
            # no deadline anywhere: fall back to waiting-request
            # pressure — more than two full refills queued per slot is
            # a surge, an empty wait line with the in-flight work
            # fitting the slots is slack
            waiting = self._waiting_requests()
            over = waiting >= 2 * max(slots, 1)
            under = waiting == 0 and backlog <= slots
        if over:
            self._surge_ticks += 1
            self._slack_ticks = 0
        elif under:
            self._slack_ticks += 1
            self._surge_ticks = 0
        else:
            self._surge_ticks = 0
            self._slack_ticks = 0
        if self.tick_no < self._cooldown_until:
            return
        if self._surge_ticks >= self.autoscale_up_after \
                and len(serving) < self.max_replicas:
            self._scale_up()
            self._surge_ticks = 0
            self._cooldown_until = self.tick_no + self.autoscale_cooldown
        elif self._slack_ticks >= self.autoscale_down_after \
                and len(serving) > self.min_replicas:
            self._scale_down()
            self._slack_ticks = 0
            self._cooldown_until = self.tick_no + self.autoscale_cooldown

    def _autoscale_plan(self):
        """A searched plan for the new replica's mesh, warm-started from
        the per-(generation, dtype) calibration store via
        :func:`plan_replicas` — None when the seed fleet itself runs
        planless (the tier-1 CPU shape) or the search cannot run here."""
        if all(r.plan is None for r in self.replicas):
            return None
        try:
            import jax
            n_dev = max(1, len(jax.devices()))
            return plan_replicas(self.model.executor.pcg, self.config,
                                 [n_dev])[0]
        except Exception:  # noqa: BLE001 — planless beats no scale-up
            return None

    def _scale_up(self) -> None:
        """Grow the pool by one replica cloned from replica 0's shape.
        The newcomer enters service through the SAME half-open probation
        as a rejoin — its first dispatch waits for a passing probe — and
        its admission controller warm-starts from the warmest sibling
        (ISSUE 19 satellite: post-scale shedding must not be blind)."""
        ref = self.replicas[0].engine
        idx = len(self.replicas)
        eng = ServingEngine(
            self.model, n_slots=ref.n_slots,
            max_decode_len=ref.max_decode_len, buckets=ref.buckets,
            max_queue=ref.max_queue, eos_id=self.eos_id,
            exact_decode=ref.exact_decode,
            serve_loop=getattr(ref, "serve_loop", None))
        warmest = max((r.engine.admission for r in self.replicas),
                      key=lambda a: a.observed_steps)
        eng.admission.warm_start(warmest)
        plan = self._autoscale_plan()
        eng.plan = plan or eng.plan
        rep = FleetReplica(
            idx, eng, plan=plan,
            open_after=int(getattr(self.config, "circuit_open_after", 3)
                           or 3))
        self.replicas.append(rep)
        self.n_replicas = len(self.replicas)
        self.stats.replicas = self.n_replicas
        self.stats.dispatches.append(0)
        self.hedge_cap = max(1, self.n_replicas - 1)
        self._make_loop(rep)
        rep.circuit.force_open(half_open_at=self.tick_no + 1)
        self._set_health(rep, "quarantined", "autoscale_probation")
        self.stats.autoscale_ups += 1
        self.stats.autoscale_events.append(
            (self.tick_no, "up", len(self._serving_replicas())))
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("fleet_autoscale", action="up",
                         tick=self.tick_no, replica=idx,
                         serving=len(self._serving_replicas()),
                         forecast_ms=round(self._forecast_ewma or 0.0, 3))

    def _scale_down(self) -> None:
        """Shrink by one through the existing migrate-and-drain: the
        chosen replica stops admitting, finishes its in-flight streams,
        and its queued work re-routes — scale-down NEVER drops a live
        stream. Deterministic victim: the least-loaded closed-circuit
        replica, highest index breaking ties (LIFO, so the seed replicas
        outlive the surge capacity)."""
        cands = [r for r in self._serving_replicas()
                 if r.loop is not None and r.circuit.state == "closed"]
        if len(cands) <= self.min_replicas:
            return
        rep = min(cands, key=lambda r: (r.outstanding_tokens(), -r.idx))
        self.drain(rep.idx)
        self.stats.autoscale_downs += 1
        self.stats.autoscale_events.append(
            (self.tick_no, "down", len(self._serving_replicas())))
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("fleet_autoscale", action="down",
                         tick=self.tick_no, replica=rep.idx,
                         serving=len(self._serving_replicas()),
                         forecast_ms=round(self._forecast_ewma or 0.0, 3))

    # -------------------------------------------------------------- failover
    def _harvest(self, rep: FleetReplica) -> Tuple[List[Request],
                                                   List[Request]]:
        """Pull every request off a dying replica WITHOUT terminal
        outcomes: (in-flight, queued). In-flight requests keep their
        host-side committed tokens — the migration re-prefill resumes
        them exactly."""
        sched = rep.sched
        inflight: List[Request] = []
        if sched is None:
            return [], []
        # settle the async loop's in-flight decode step first: tokens
        # already sampled on-device belong to the stream — migrating
        # without committing them would fork it. A kill may leave the
        # pending buffers dead; dropping them is then correct (the
        # uncommitted step is simply lost, as on a real crash).
        if rep.loop is not None:
            try:
                rep.loop.settle()
            except Exception:  # noqa: BLE001 — dead device buffers
                pass
        for slot, req in enumerate(list(sched.slots)):
            if req is not None:
                sched.cancel_slot(slot)
                inflight.append(req)
        queued = list(sched.queue)
        sched.queue.clear()
        rt = get_reqtrace()
        if rt.enabled:
            ts = float(self.clock())
            for req in inflight:
                rt.note(req.rid, "migrate", ts, src=rep.idx,
                        tick=self.tick_no, inflight=True)
            for req in queued:
                rt.note(req.rid, "migrate", ts, src=rep.idx,
                        tick=self.tick_no, inflight=False)
        return inflight, queued

    def _kill(self, rep: FleetReplica, reason: str) -> None:
        """A replica died abruptly (its mesh is gone): migrate its work
        to the fleet queue — in-flight streams ahead of its queued ones,
        both ahead of the door queue, preserving progress — and take it
        out of rotation until rejoin."""
        inflight, queued = self._harvest(rep)
        rep.engine.reset_decode_pool()
        rep.circuit.force_open(half_open_at=None)  # probe only via rejoin
        self._set_health(rep, "dead", reason)
        for req in reversed(queued):
            self.queue.appendleft(req)
        for req in reversed(inflight):
            self.queue.appendleft(req)
        self.stats.migrations += len(inflight)
        self.stats.requeued += len(queued)
        self.stats.failovers += 1
        self.stats.kill_ticks.append(self.tick_no)
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("fleet_failover", replica=rep.idx,
                         tick=self.tick_no, migrated=len(inflight),
                         requeued=len(queued), reason=reason)

    def _finish_drain(self, rep: FleetReplica) -> None:
        """A draining replica went idle: close its loop, hand its queued
        requests back (fleet-level drain) or re-route them (rolling
        restart), and take it out of rotation.

        ``ledger_drained=False``: the loop must NOT close the handed
        requests' reqtrace timelines — the rolling-restart branch below
        clears their outcome and re-admits them, and a premature
        "preempted" terminal would wrongly pin (first-terminal-wins) a
        stream that goes on to finish "ok". The fleet-level drain branch
        IS the terminal, so it journals + ledgers there (ISSUE 20
        satellite: a drained rid must not leak outcome-less into a
        crash)."""
        assert rep.loop is not None
        rep.loop.finish(ledger_drained=False)
        handed = list(rep.engine.drained_requests)
        rep.engine.drained_requests = []
        if self._fleet_draining:
            jr = self.journal
            if jr.enabled:
                for req in handed:
                    jr.log_outcome(req, "preempted")
                jr.sync()
            self.drained_requests.extend(handed)
        else:
            for req in handed:
                req.outcome = None
                self.queue.append(req)
            self.stats.requeued += len(handed)
        rep.circuit.force_open(half_open_at=None)
        self._set_health(rep, "dead", "drained")

    # ----------------------------------------------------------------- hedge
    def _launch_hedges(self) -> None:
        if self.hedge_after_pctl <= 0 or self._fleet_draining:
            return
        now = self.clock()
        for rep in self.replicas:
            if len(self._hedges) >= self.hedge_cap:
                return
            if not rep.alive or rep.sched is None:
                continue
            cost = rep.engine.admission.token_cost_ms
            if cost <= 0:
                continue  # cold EWMA: no prediction to blow yet
            slow = [r for r in list(rep.sched.queue)
                    + [s for s in rep.sched.slots if s is not None]
                    if not r.done and id(r) not in self._hedged_ids]
            for req in slow:
                if len(self._hedges) >= self.hedge_cap:
                    return
                est = cost * req.max_new_tokens
                if (now - req.submit_ms) <= \
                        est * self.hedge_after_pctl / 100.0:
                    continue
                # anti-amplification: a hedge only goes to an IDLE
                # replica — free slot, empty queue — never displacing
                # first-try traffic on a loaded one
                idle = [t for t in self.replicas
                        if t is not rep and self._dispatchable(t)
                        and t.sched is not None and t.sched.queued == 0
                        and t.sched.active < t.engine.n_slots]
                if not idle:
                    continue
                target = min(idle, key=lambda t: (
                    t.drain_estimate_ms(), t.outstanding_tokens(), t.idx))
                assert target.sched is not None
                twin = Request(prompt=req.prompt,
                               max_new_tokens=req.max_new_tokens,
                               eos_id=req.eos_id,
                               generated=list(req.generated),
                               rng_tag=req.rng_tag,
                               deadline_ms=req.deadline_ms)
                try:
                    target.sched.submit(twin)
                except ValueError:
                    continue
                target.dispatches += 1
                self.stats.dispatches[target.idx] += 1
                self._hedges.append(_Hedge(
                    primary=req, twin=twin, fork=len(req.generated),
                    primary_replica=rep.idx, twin_replica=target.idx))
                self._hedged_ids.add(id(req))
                self.stats.hedges += 1
                rt = get_reqtrace()
                if rt.enabled:
                    # fold the twin's timeline into the primary's: the
                    # twin's submit note (just emitted) moves over, and
                    # every later note on either copy lands on ONE
                    # connected per-request timeline
                    rt.link(twin.rid, req.rid)
                    rt.note(req.rid, "hedge", float(now), src=rep.idx,
                            replica=target.idx,
                            fork=len(req.generated))
                tracer = self._tracer()
                if tracer.enabled:
                    tracer.event("fleet_hedge", rid=req.rid,
                                 tick=self.tick_no, source=rep.idx,
                                 target=target.idx,
                                 fork=len(req.generated))

    def _cancel_copy(self, req: Request) -> None:
        """Cancel the losing hedge copy wherever it lives — slot, queue,
        finished ledger, or the fleet door queue — with NO terminal
        outcome (the winner owns the ledger entry)."""
        for rep in self.replicas:
            sched = rep.sched
            if sched is None:
                continue
            for i, q in enumerate(sched.slots):
                if q is req:
                    sched.cancel_slot(i)
                    self.stats.hedges_cancelled += 1
                    return
            try:
                sched.cancel_queued(req)
                self.stats.hedges_cancelled += 1
                return
            except ValueError:
                pass
            if sched.remove_finished(req):
                # the loser finished inside the same router tick its twin
                # won: withdraw its ledger entry (the winner's stands)
                req.outcome = None
                req.done = False
                self.stats.hedges_cancelled += 1
                return
        if remove_by_identity(self.queue, req):
            self.stats.hedges_cancelled += 1

    def _resolve_hedges(self) -> None:
        tracer = self._tracer()
        for h in list(self._hedges):
            p_tok = len(h.primary.generated) > h.fork
            t_tok = len(h.twin.generated) > h.fork
            p_failed = h.primary.done and \
                (h.primary.outcome or "ok") != "ok"
            t_failed = h.twin.done and (h.twin.outcome or "ok") != "ok"
            if not (p_tok or t_tok or p_failed or t_failed):
                continue
            # first NEW committed token wins, the primary winning ties
            # (its replica ticked first this round) — EXCEPT that a
            # failed copy (evicted as deadline_exceeded / decode_fault /
            # preempted) never beats a still-viable rival: the hedge
            # exists precisely to rescue a request whose first try died
            if p_failed and not t_failed:
                winner, loser = h.twin, h.primary
            elif t_failed and not p_failed:
                winner, loser = h.primary, h.twin
            elif p_tok or p_failed:
                winner, loser = h.primary, h.twin
            else:
                winner, loser = h.twin, h.primary
            h.winner = winner
            self._cancel_copy(loser)
            if winner is h.twin:
                self.stats.hedge_twin_wins += 1
                self._adopted.append(h)
            self._hedges.remove(h)
            self._hedged_ids.discard(id(h.primary))
            if tracer.enabled:
                tracer.event("fleet_hedge_resolved", rid=h.primary.rid,
                             tick=self.tick_no,
                             winner=("twin" if winner is h.twin
                                     else "primary"))

    def _mirror_adopted(self) -> None:
        """A hedge whose TWIN won streams on under the twin object; the
        caller holds the primary. Mirror the twin's tokens/outcome onto
        the primary as they land so the external view — and the
        exactly-one-outcome ledger — is always the primary's."""
        for h in self._adopted:
            if h.mirrored:
                continue
            h.primary.generated = list(h.twin.generated)
            # the latency stamps must migrate with the tokens: an adopted
            # twin's TTFT / completion times ARE the request's real
            # latencies — without them the caller's primary reports
            # first_token_ms/finish_ms of 0 and bench TTFT goes negative
            if h.twin.first_token_ms and not h.primary.first_token_ms:
                h.primary.first_token_ms = h.twin.first_token_ms
            if h.twin.done:
                h.primary.done = True
                h.primary.finish_reason = h.twin.finish_reason
                h.primary.outcome = h.twin.outcome
                h.primary.finish_ms = h.twin.finish_ms
                h.mirrored = True

    # ----------------------------------------------------------------- chaos
    def _apply_chaos(self, chaos) -> None:
        tick = self.tick_no
        # the base ChaosPlan's serving preemption doubles as the fleet's
        # scripted SIGTERM (keyed on fleet ticks here): os.kill drives
        # the REAL flag-only handler, and the run loop turns it into the
        # fleet-wide graceful drain
        chaos.maybe_preempt_serving(tick)
        kill = getattr(chaos, "maybe_kill_replica", None)
        if kill is None:
            return  # a plain ChaosPlan has no fleet-replica hooks
        crash = getattr(chaos, "maybe_crash", None)
        if crash is not None:
            mode = crash(tick)
            if mode is not None:
                self._crash(mode)
        r = chaos.maybe_kill_replica(tick)
        if r is not None:
            self._kill(self.replicas[r], "chaos_kill")
        r = chaos.maybe_degrade_replica(tick)
        if r is not None:
            rep = self.replicas[r]
            rep.degrade_every = chaos.degrade_poison_every
            rep.degrade_counter = 0
        r = chaos.maybe_partition_replica(tick)
        if r is not None:
            self.replicas[r].partitioned_until = \
                tick + chaos.partition_ticks
        r = chaos.maybe_drain_replica(tick)
        if r is not None and self.replicas[r].alive:
            self.drain(r)
        r = chaos.maybe_rejoin_replica(tick)
        if r is not None:
            self.rejoin(r)
        storm = getattr(chaos, "maybe_fleet_storm", None)
        if storm is not None:
            for tenant, n in storm(tick):
                self._inject_storm(tenant, n, chaos)

    def _inject_storm(self, tenant: Optional[str], n: int,
                      chaos) -> None:
        """Scripted traffic-step/tenant-storm injection (ISSUE 19): ``n``
        synthetic requests of ``tenant`` through the REAL door —
        submit(), quota, shed gates, WFQ and the ledgers all see them as
        ordinary traffic. Storm rng tags live in their own range
        (2_000_000+) so they can never collide with caller tags or the
        engine-level storm's 1_000_000 range."""
        max_new = int(getattr(chaos, "fleet_storm_max_new", 8) or 8)
        plen = int(getattr(chaos, "fleet_storm_prompt_tokens", 3) or 3)
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("fleet_tenant_storm", tick=self.tick_no,
                         tenant=tenant, requests=n)
        for _ in range(int(n)):
            seq = self._storm_seq
            self._storm_seq += 1
            req = Request(
                prompt=np.asarray([(seq % 7) + 1] * plen, np.int32),
                max_new_tokens=max_new, eos_id=self.eos_id,
                rng_tag=2_000_000 + seq, tenant=tenant)
            self.stats.storm_requests += 1
            try:
                self.submit(req)
            except ServingRejection:
                pass  # ledgered at the door; the storm presses on

    def _maybe_degrade_tick(self, rep: FleetReplica) -> None:
        """Scripted sustained decode poison (FleetChaosPlan degrade):
        NaN one live slot's KV rows every Nth decode opportunity — the
        guarded decode quarantines it, and the quarantine rate is the
        passive signal that opens the circuit."""
        sched = rep.sched
        if not rep.degrade_every or rep.engine.state is None \
                or sched is None or not sched.active:
            return
        rep.degrade_counter += 1
        if rep.degrade_counter % rep.degrade_every:
            return
        live = [i for i, r in enumerate(sched.slots) if r is not None]
        if not live:
            return
        from ..resilience.chaos import poison_decode_state

        rep.engine.state = poison_decode_state(rep.engine.state, live[0])
        self.stats.degrade_poisons += 1

    # ------------------------------------------------------------------ tick
    def _tick_replica(self, rep: FleetReplica) -> bool:
        if not rep.alive or rep.loop is None:
            return False
        if rep.partitioned_until is not None:
            if self.tick_no < rep.partitioned_until:
                # the router cannot reach the replica: its progress is
                # invisible (not ticked); each blocked round-trip counts
                # one timeout against the circuit
                if rep.circuit.state != "open":
                    self._circuit_failure(rep, "partition_timeout")
                return False
            rep.partitioned_until = None  # healed; probe re-admits it
        self._maybe_degrade_tick(rep)
        loop = rep.loop
        assert loop is not None
        q_before = loop.res.quarantines
        d_before = loop.stats.decode_steps
        t_before = loop.stats.tokens_generated
        try:
            worked = loop.tick()
        except Exception as e:  # noqa: BLE001 — the fault-domain boundary
            # an error the engine's OWN failover (elastic replan, state
            # rebuild) could not absorb is a replica death: migrate its
            # work and keep the fleet serving
            self._kill(rep, f"{type(e).__name__}: {e}"[:120])
            return True
        self._tick_tokens += loop.stats.tokens_generated - t_before
        dq = loop.res.quarantines - q_before
        if dq:
            rep.quarantine_events += dq
            self._circuit_failure(rep, "decode_quarantine", n=dq)
        elif loop.stats.decode_steps > d_before:
            self._circuit_success(rep)
        if rep.health == "draining" and not worked:
            self._finish_drain(rep)
        return worked

    # ------------------------------------------------------------------- run
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, eos_id: Optional[int] = None,
                 seed: int = 0, chaos=None,
                 deadline_ms: Optional[float] = None) -> List[List[int]]:
        """Generate continuations through the fleet; returns the token
        lists in submission order (shed requests return their — empty —
        partials; read ``self.stats.outcomes`` for the ledger). The
        fleet analog of ``ServingEngine.generate``."""
        reqs = []
        for i, p in enumerate(prompts):
            r = Request(prompt=np.asarray(p, dtype=np.int32),
                        max_new_tokens=max_new_tokens,
                        eos_id=self.eos_id if eos_id is None else eos_id,
                        rng_tag=i, deadline_ms=deadline_ms)
            try:
                self.submit(r)
            except ServingRejection:
                pass  # outcome shed; the fleet ledger picks it up
            reqs.append(r)
        self.run(chaos=chaos, temperature=temperature, top_k=top_k,
                 seed=seed)
        return [list(r.generated) for r in reqs]

    def run(self, chaos=None, temperature: float = 0.0, top_k: int = 0,
            seed: int = 0) -> FleetStats:
        """Drive the fleet until every submitted request has left under
        exactly one outcome. One fleet tick = chaos hooks, probes,
        dispatch, one scheduler action per live replica, hedge
        resolution/launch. Installs the flag-only SIGTERM handler: a
        preemption drains EVERY replica gracefully and hands the
        leftover queue back via ``self.drained_requests``."""
        from ..resilience.session import ResilienceSession

        if chaos is not None:
            self.chaos = chaos
        chaos = self.chaos
        self._start(temperature, top_k, seed)
        session = ResilienceSession(self.model, signals_only=True)
        session.install_signal_handlers()
        t0 = time.perf_counter()
        idle = 0
        if get_reqtrace().enabled and self.timeseries is None:
            self.timeseries = FleetTimeSeries()
        try:
            while True:
                t_iter = time.perf_counter()
                if chaos is not None:
                    self._apply_chaos(chaos)
                self._run_probes()
                if self.autoscale:
                    self._autoscale_tick()
                if session.preempted and not self._fleet_draining:
                    # flag-only handler fired: fleet-wide graceful drain
                    # — checked BEFORE dispatch so admission stops in
                    # the same tick the signal landed
                    self._fleet_draining = True
                    self.stats.drains += 1
                    for rep in self.replicas:
                        if rep.alive and rep.loop is not None:
                            rep.loop.request_drain(session=session)
                            self._set_health(rep, "draining",
                                             "fleet_sigterm")
                self._dispatch()
                self._tick_tokens = 0
                worked = False
                # router host time = loop wall OUTSIDE replica ticks
                # (chaos/probes/dispatch above, hedge machinery below);
                # the per-replica serve loops split their own tick wall
                self._host_router_s += time.perf_counter() - t_iter
                # under --serve-loop async each replica tick leaves one
                # decode transfer in flight and returns immediately, so
                # this plain round-robin already interleaves N replicas'
                # device work on one host: replica i+1's dispatch and
                # bookkeeping run while replica i's step is on the wire
                for rep in self.replicas:
                    worked = self._tick_replica(rep) or worked
                t_post = time.perf_counter()
                self._resolve_hedges()
                self._mirror_adopted()
                self._launch_hedges()
                self._journal_tick()
                self.stats.tokens_history.append(self._tick_tokens)
                self.stats.queue_depth_history.append(
                    self._waiting_requests())
                if self.timeseries is not None:
                    self.timeseries.sample(
                        self.tick_no, len(self.queue), self._tick_tokens,
                        sum(r.drain_estimate_ms() for r in self.replicas
                            if r.alive),
                        [(r.sched.active / max(r.engine.n_slots, 1))
                         if (r.alive and r.sched is not None) else 0.0
                         for r in self.replicas],
                        [r.health for r in self.replicas],
                        tenants=self.queue.queued_by_tenant())
                self.tick_no += 1
                self._host_router_s += time.perf_counter() - t_post
                if worked:
                    idle = 0
                    continue
                # work stranded on a non-tickable replica (a partition
                # that will heal) counts as pending: breaking on it
                # would truncate streams one tick from recovery
                stranded = any(
                    r.alive and r.sched is not None
                    and (r.sched.active or r.sched.queued)
                    for r in self.replicas)
                pending = bool(self.queue) or bool(self._hedges) \
                    or stranded
                if not pending:
                    break
                idle += 1
                none_alive = not any(r.alive for r in self.replicas)
                if none_alive or idle > self.max_idle_ticks:
                    # nowhere left to route: break and let _finish mark
                    # the leftovers preempted — and, under a fleet-level
                    # drain, hand them back via drained_requests (marking
                    # them here would make that handback unreachable)
                    break
        finally:
            self._running = False
            session.close()
        return self._finish(t0)

    def _journal_tick(self) -> None:
        """Per-tick journal sweep (ISSUE 20): every request that
        reached a terminal this tick gets its outcome record (placed
        AFTER the hedge machinery — ``_resolve_hedges``/``_cancel_copy``
        may withdraw a losing copy's outcome the same tick, and an
        outcome record, once written, is forever), then the group-commit
        window is checked. Journal-off cost: one attribute read."""
        jr = self.journal
        if not jr.enabled:
            return
        for req in self._requests:
            if req.done or req.outcome:
                jr.log_outcome(req)
        jr.maybe_sync()

    def _crash(self, mode: str) -> None:
        """Scripted whole-process death (``FleetChaosPlan.crash_at``):
        the journal drops its un-group-committed buffer FIRST — a dead
        process flushes nothing — then ``sigkill`` mode delivers the
        real signal (run the fleet in a child process for this mode)
        while ``hard`` mode raises :class:`FleetCrashed` past every
        drain/finish/ledger path (the tier-1 CPU stand-in). The
        fleet_crash tracer event survives in the shared in-memory
        tracer: the RECOVERY run's trace write publishes it."""
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("fleet_crash", tick=self.tick_no, mode=mode)
        if self.journal.enabled:
            self.journal.crash()
        if mode == "sigkill":
            import os
            import signal as _signal
            os.kill(os.getpid(), _signal.SIGKILL)
        raise FleetCrashed(
            f"fleet crashed at tick {self.tick_no} "
            f"(chaos crash_at, mode {mode!r})")

    @classmethod
    def recover(cls, model, journal_dir: Optional[str] = None, **kw):
        """Restart-after-crash entry point (ISSUE 20,
        docs/durability.md): scan the journal directory (truncating any
        torn tail), then replay every rid with a submit record but no
        outcome record through the REAL fleet door — WFQ, tenancy,
        quota and shed policies all apply to replayed traffic, and a
        progress-journaled stream re-enters carrying its committed
        tokens (the PR 11 re-prefill path resumes it bitwise under
        exact decode). Returns the fleet with the backlog queued; call
        :meth:`run` to serve it. The relative deadline budget restarts
        at recovery — monotonic clocks do not survive a process."""
        config = model.config
        root = journal_dir or getattr(config, "request_journal", "") \
            or ""
        if not root:
            raise ValueError("ServingFleet.recover() needs a journal "
                             "directory (--request-journal DIR or "
                             "journal_dir=)")
        t0 = time.perf_counter()
        jr = RequestJournal(
            root,
            sync_ms=float(getattr(config, "journal_sync_ms", 0.0)
                          or 0.0),
            commit_every=int(getattr(config, "journal_commit_every", 0)
                             or 0),
            clock=kw.get("clock"))
        fleet = cls(model, journal=jr, **kw)
        fleet._replay_journal(t0)
        return fleet

    def _replay_journal(self, t0: float) -> None:
        jr = self.journal
        pending = jr.pending_requests()
        # fresh submits must never collide with a replayed rid: skip
        # the counter past everything the dead process ever issued
        reserve_rids(jr.max_rid())
        rt = get_reqtrace()
        self._journal_replaying = True
        try:
            for req in pending:
                if rt.enabled:
                    rt.note(req.rid, "replay", float(self.clock()),
                            new_tokens=len(req.generated),
                            tenant=req.tenant)
                jr.replayed += 1
                try:
                    self.submit(req)
                except ServingRejection:
                    pass  # door policies hold for replayed traffic too
        finally:
            self._journal_replaying = False
        jr.recovery_wall_s = time.perf_counter() - t0
        tracer = self._tracer()
        if tracer.enabled:
            tracer.event("journal_recover", replayed=jr.replayed,
                         truncated=jr.truncated_records,
                         wall_s=round(jr.recovery_wall_s, 6))

    def _finish(self, t0: float) -> FleetStats:
        st = self.stats
        for rep in self.replicas:
            if rep.loop is not None and not rep.loop.finished:
                # ledger_drained=False: the fleet-wide sweep below is
                # the one place fleet requests' timelines close
                rep.loop.finish(ledger_drained=False)
        # a fleet-level drain hands the door queue back too
        leftovers = list(self.queue)
        self.queue.clear()
        for req in leftovers:
            req.outcome = "preempted"
            req.done = True
        if self._fleet_draining:
            self.drained_requests.extend(leftovers)
        self._mirror_adopted()
        st.ticks = self.tick_no
        st.wall_s = time.perf_counter() - t0
        st.requests = len(self._requests)
        st.tokens_generated = sum(r.tokens_generated()
                                  for r in self.replicas)
        # the FLEET-WIDE outcome ledger: every externally-submitted
        # request under exactly one outcome; hedge twins are internal
        # and never counted (their winner's entry lives on the primary)
        st.outcomes = {}
        # per-tenant ledgers rebuilt from the same sweep (door-time
        # counts were provisional): one outcome per request per tenant
        st.tenant_outcomes = {}
        st.tenant_tokens = {}
        rt = get_reqtrace()
        jr = self.journal
        for req in self._requests:
            outcome = req.outcome or ("ok" if req.done else "preempted")
            if jr.enabled:
                # the journal's exactly-one-outcome terminal mirrors the
                # ledger's (idempotent: ticked-in outcomes drop here)
                jr.log_outcome(req, outcome)
            st.count_outcome(outcome)
            st.count_tenant_outcome(req.tenant, outcome)
            if req.tenant and req.generated:
                st.tenant_tokens[req.tenant] = \
                    st.tenant_tokens.get(req.tenant, 0) + \
                    len(req.generated)
            if rt.enabled:
                # finalize is idempotent (first terminal note wins):
                # requests the schedulers already finished drop this; only
                # paths with no scheduler _finish — door leftovers,
                # streams stranded on a dead/partitioned replica — close
                # their timeline here, mirroring the ledger's outcome
                rt.finish(req.rid, float(self.clock()), outcome,
                          reason=req.finish_reason or outcome,
                          new_tokens=len(req.generated))
        # host-overhead roll-up: every replica serve loop's wall split
        # (live + retired across drain/rejoin rebuilds) plus the
        # router's own chaos/probe/dispatch/hedge time
        st.host_dispatch_s = self._host_router_s
        st.host_device_s = 0.0
        st.host_bookkeep_s = 0.0
        st.host_overlap_s = 0.0
        st.host_syncs = 0
        for rep in self.replicas:
            d, v, b, o = rep.retired_host
            n = rep.retired_syncs
            if rep.loop is not None:
                d += rep.loop.stats.host_dispatch_s
                v += rep.loop.stats.host_device_s
                b += rep.loop.stats.host_bookkeep_s
                o += rep.loop.stats.host_overlap_s
                n += rep.loop.stats.host_syncs
            st.host_dispatch_s += d
            st.host_device_s += v
            st.host_bookkeep_s += b
            st.host_overlap_s += o
            st.host_syncs += n
        tracer = self._tracer()
        if jr.enabled:
            # group-commit the ledger tail, then drop fully-retired
            # segments; close() stays with the CALLER — a fleet object
            # may run() again (rolling batches share one journal)
            jr.sync()
            dropped = jr.compact()
            if tracer.enabled and dropped:
                tracer.event("journal_compact", segments=dropped,
                             tick=self.tick_no)
        self._merge_telemetry(st)
        if tracer.enabled and self.model.config.trace_file:
            tracer.write(self.model.config.trace_file)
        return st

    # -------------------------------------------------------------- telemetry
    def _merge_telemetry(self, st: FleetStats) -> None:
        """Publish the run into a StepTelemetry ``fleet`` block (next to
        the serving / serving_resilience blocks) when a sink wants one."""
        tracer = self._tracer()
        tel = self.model._make_telemetry(tracer,
                                         batch_size=self.total_slots(),
                                         phase="fleet")
        self.model._telemetry = tel or getattr(self.model, "_telemetry",
                                               None)
        if tel is None:
            return
        tel.fleet_replicas = st.replicas
        tel.fleet_ticks = st.ticks
        tel.fleet_requests = st.requests
        tel.fleet_tokens_generated = st.tokens_generated
        tel.fleet_outcomes = dict(st.outcomes)
        tel.fleet_sheds = st.sheds
        tel.fleet_dispatches = list(st.dispatches)
        tel.fleet_migrations = st.migrations
        tel.fleet_hedges = st.hedges
        tel.fleet_hedge_twin_wins = st.hedge_twin_wins
        tel.fleet_affinity_hits = st.affinity_hits
        tel.fleet_probes = st.probes
        tel.fleet_circuit_opens = st.circuit_opens
        tel.fleet_failovers = st.failovers
        tel.fleet_health_transitions = len(st.health_transitions)
        tel.fleet_host_overhead_fraction = st.host_overhead_fraction()
        tel.fleet_tenants = {
            t: {"requests": st.tenant_requests.get(t, 0),
                "tokens": st.tenant_tokens.get(t, 0),
                "outcomes": dict(led)}
            for t, led in sorted(st.tenant_outcomes.items())}
        tel.fleet_quota_sheds = st.quota_sheds
        tel.fleet_autoscale_ups = st.autoscale_ups
        tel.fleet_autoscale_downs = st.autoscale_downs
        jr = self.journal
        if jr.enabled:
            tel.journal_appended = jr.appended
            tel.journal_syncs = jr.syncs
            tel.journal_replayed = jr.replayed
            tel.journal_dedupe_hits = jr.dedupe_hits
            tel.journal_compacted_segments = jr.compacted_segments
            tel.journal_truncated_records = jr.truncated_records
            tel.journal_recovery_wall_s = jr.recovery_wall_s
        tel.finalize()
        if self.model.config.telemetry_file:
            tel.write(self.model.config.telemetry_file)
