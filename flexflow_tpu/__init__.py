"""flexflow_tpu: a TPU-native auto-parallel DNN training framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of FlexFlow (the
Legion/CUDA reference surveyed in SURVEY.md): Keras/PyTorch-style FFModel API,
two-phase graph compiler (Layer graph -> Parallel Computation Graph), Unity
auto-parallelization search over a TPU cost model, first-class parallel
operators, MoE building blocks, and torch-fx/ONNX/Keras frontends.
"""
from .config import FFConfig, FFIterationConfig  # noqa: F401
from .ffconst import (ActiMode, AggrMode, CompMode, DataType, LossType,  # noqa: F401
                      MetricsType, OperatorType, ParameterSyncType, PoolType)
from .tensor import Tensor  # noqa: F401
from .layer import Layer  # noqa: F401
from .machine_view import MachineView, MachineResource  # noqa: F401
from .parallel_tensor import ParallelDim, ParallelTensorShape  # noqa: F401
from .model import FFModel  # noqa: F401
from .execution.optimizers import SGDOptimizer, AdamOptimizer  # noqa: F401
from .execution.metrics import PerfMetrics  # noqa: F401
from .execution.initializers import (GlorotUniformInitializer,  # noqa: F401
                                     ZeroInitializer, ConstantInitializer,
                                     UniformInitializer, NormInitializer)

from .parallel.pipeline import PipelineTrainer  # noqa: F401,E402
from .execution.checkpoint import (latest_checkpoint,  # noqa: F401,E402
                                   restore_checkpoint, save_checkpoint)
from .resilience import ChaosPlan, elastic_restore  # noqa: F401,E402
from .serving import ServingEngine  # noqa: F401,E402

__version__ = "0.1.0"
