"""flexflow_tpu.analysis — ShardLint: static verification of parallel plans.

Every property the strategy-safety layer (ISSUE 5) verified *dynamically*
— by compiling a candidate and running a probe step — that is actually
decidable from the PCG and the Strategy alone, verified statically
(ISSUE 7): an abstract interpreter propagates a per-tensor placement
lattice (``replicated | sharded(axis, dim) | partial_sum(axis)``,
``lattice.py``/``interp.py``) and named rules with stable IDs judge the
result (``rules.py``; table in ``docs/static_analysis.md``):

FF001 partial-sum placement · FF002 donation-aliasing · FF003 rng-stream
collision · FF004 remat segmentation · FF005 serving-state reachability ·
FF006 shape/divisibility dataflow.

Wired in three places: stage 0 of ``resilience.fallback.StrategyCascade``
(statically-rejected candidates degrade down the ranked chain without a
compile), candidate pruning in ``search.unity`` before simulation, and
the CLI (``python -m flexflow_tpu.analysis`` / ``scripts/fflint.py``).
The dynamic checks stay as the backstop for what statics cannot see
(an actual XLA miscompile); they no longer run first.
"""
from __future__ import annotations

from typing import List, Optional

from .interp import InterpResult, interpret  # noqa: F401
from .lattice import Placement  # noqa: F401
from .report import (AnalysisReport, Diagnostic,  # noqa: F401
                     StaticAnalysisError)
from .rules import (RULES, BufferRef, DonationSpec,  # noqa: F401
                    check_donation, check_paged_kv, check_remat,
                    check_rng_streams, check_serving_graph, check_shapes,
                    donation_spec_for_training)

__all__ = [
    "AnalysisReport", "Diagnostic", "StaticAnalysisError", "Placement",
    "InterpResult", "interpret", "RULES", "BufferRef", "DonationSpec",
    "check_donation", "check_paged_kv", "check_remat",
    "check_rng_streams", "check_serving_graph", "check_shapes",
    "donation_spec_for_training",
    "analyze_strategy", "analyze_candidate", "analyze_model",
]


def analyze_strategy(pcg, strategy, *, serving: bool = False,
                     remat_level: Optional[str] = None,
                     remat_segment_size: int = 8,
                     donation: Optional[DonationSpec] = None,
                     schedule: Optional[str] = None,
                     virtual_stages: Optional[int] = None
                     ) -> AnalysisReport:
    """The full static pass over one (PCG, Strategy) pair.

    Runs the abstract interpreter (FF001), the rng-stream check (FF003),
    the remat segmentation check (FF004; ``remat_level`` defaults to the
    strategy's searched level), and the shape/divisibility dataflow
    (FF006). ``serving=True`` adds the serving-state reachability check
    (FF005); ``donation`` adds the aliasing contract check (FF002).
    Pure Python over graph metadata — no device, no compile, no step."""
    diags: List[Diagnostic] = []
    checked = ["FF001", "FF003", "FF004", "FF006"]
    res = interpret(pcg, strategy)
    diags.extend(res.diagnostics)
    diags.extend(check_rng_streams(pcg))
    level = remat_level if remat_level is not None else \
        (getattr(strategy, "remat", "") or "none")
    diags.extend(check_remat(pcg, level, remat_segment_size))
    # pipeline strategies: the STAGE-CHUNK segmentation obeys the same two
    # FF004 laws (partition + topological cuts). The interleaved
    # schedule's pp*v round-robin chunks are judged as chunk CUTS, not
    # device placement — a legal interleaved plan passes (ISSUE 10).
    # ``schedule``/``virtual_stages`` let analyze_model pass the RESOLVED
    # choice (the --schedule flag beats the searched field, exactly as
    # the remat_level resolution above) — defaults read the strategy.
    if strategy is not None and getattr(strategy, "pipeline", None):
        from ..parallel.pipeline import split_stages

        pp = int(strategy.pipeline[0])
        if schedule is None:
            schedule = getattr(strategy, "schedule", "") or ""
        if virtual_stages is None:
            virtual_stages = int(getattr(strategy, "virtual_stages", 1)
                                 or 1)
        v = int(virtual_stages) if schedule == "interleaved" else 1
        n_chunks = pp * max(v, 1)
        if 1 <= n_chunks <= len(pcg.compute_nodes()):
            diags.extend(check_remat(
                pcg, "full", segments=split_stages(pcg, n_chunks),
                kind="stage"))
    if strategy is not None:
        diags.extend(check_shapes(pcg, strategy))
    if serving:
        checked.append("FF005")
        diags.extend(check_serving_graph(pcg))
    if donation is not None:
        checked.append("FF002")
        diags.extend(check_donation(donation))
    desc = strategy.describe() if strategy is not None and \
        hasattr(strategy, "describe") else ""
    return AnalysisReport(diagnostics=diags, checked=tuple(checked),
                          strategy_desc=desc)


def analyze_candidate(pcg, strategy) -> AnalysisReport:
    """The search's fast pruning pass: FF001 (lattice) + FF006 (shapes)
    only — the two rules a search candidate can actually violate, cheap
    enough to run per candidate before the simulator prices it."""
    diags = list(interpret(pcg, strategy).diagnostics)
    diags.extend(check_shapes(pcg, strategy))
    return AnalysisReport(diagnostics=diags, checked=("FF001", "FF006"),
                          strategy_desc=strategy.describe()
                          if strategy is not None else "")


def analyze_model(ffmodel, serving: bool = False,
                  pcg=None) -> AnalysisReport:
    """Analyze a compiled :class:`FFModel` — its live PCG, strategy, remat
    plan, and training-step donation contract. ``pcg`` overrides
    ``ffmodel.pcg`` for callers analyzing mid-compile, before the model
    binds it (the --static-analysis strict path)."""
    from ..execution.remat import resolve_remat_plan

    plan = resolve_remat_plan(ffmodel.config, ffmodel.strategy)
    sched = None
    vstages = None
    if getattr(ffmodel.strategy, "pipeline", None):
        # judge the segmentation the trainer will RUN: --schedule /
        # --virtual-stages beat the searched fields (resolve_schedule),
        # the same flag-beats-searched rule as the remat plan above
        from ..parallel.pipeline import resolve_schedule

        sched, vstages = resolve_schedule(ffmodel.config, ffmodel.strategy)
    return analyze_strategy(
        ffmodel.pcg if pcg is None else pcg, ffmodel.strategy,
        serving=serving, remat_level=plan.level,
        remat_segment_size=plan.segment_size,
        donation=donation_spec_for_training(ffmodel),
        schedule=sched, virtual_stages=vstages)
