"""Abstract interpretation of a parallelized PCG over the placement lattice.

One forward walk of the graph in topological order, tracking a
:class:`~.lattice.Placement` per tensor (``(guid, out_idx)``), seeded from
the Strategy's declared shardings and advanced by per-op transfer
functions:

* a Linear whose kernel is sharded on its **contraction** dim (the
  row-parallel plan of ``parallel/strategies.py``), an attention output
  projection sharded over heads, a vocab-sharded embedding gather, and an
  in-channel-sharded Conv2D all produce ``partial_sum(axis)`` — the psum
  semantics documented on ``parallel/parallel_op.py``'s ReductionOp;
* a declared ``output_spec`` on the producing node discharges the partial
  (lowered to ``with_sharding_constraint``, XLA materializes the psum /
  reduce-scatter that satisfies it);
* an explicit ``OP_REDUCTION`` parallel-op node discharges the partial
  over its ``axes`` — and reducing a value that is NOT partial over those
  axes is the dual defect (a double-counted allreduce);
* every other consumer **requires** a non-partial value.

Violations surface as **FF001** diagnostics during the walk (see
``rules.py`` for the registry); the resulting placement map feeds the
FF006 shape/divisibility checks and the CLI's per-tensor dump. The
interpreter is pure Python over graph metadata — no device, no compile,
no probe step.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..ffconst import OperatorType
from .lattice import Placement, entry_axes
from .report import Diagnostic

# ops that preserve their (single) input's shape and placement elementwise;
# kept in sync with the Unity DP's state-preserving set (search/unity.py) —
# the ops the search itself pins to pass sharded states through unchanged
_STATE_PRESERVING = {
    OperatorType.OP_RELU, OperatorType.OP_GELU, OperatorType.OP_TANH,
    OperatorType.OP_SIGMOID, OperatorType.OP_ELU, OperatorType.OP_IDENTITY,
    OperatorType.OP_DROPOUT, OperatorType.OP_SCALAR_MULTIPLY,
    OperatorType.OP_SCALAR_ADD, OperatorType.OP_SCALAR_SUB,
    OperatorType.OP_SCALAR_TRUE_DIV, OperatorType.OP_CAST,
    OperatorType.OP_EXP, OperatorType.OP_POW, OperatorType.OP_LAYERNORM,
    OperatorType.OP_SOFTMAX, OperatorType.OP_BATCHNORM,
}
_ELEMENTWISE_BINARY = {
    OperatorType.OP_EW_ADD, OperatorType.OP_EW_SUB, OperatorType.OP_EW_MUL,
    OperatorType.OP_EW_DIV, OperatorType.OP_EW_MAX, OperatorType.OP_EW_MIN,
}

# (op_type, weight name, contraction dim of that weight): a strategy that
# shards this weight dim makes the op contract over a sharded dim — the
# output is a partial sum over the sharding axes until reduced
_CONTRACTION_WEIGHT_DIMS = {
    OperatorType.OP_LINEAR: ("kernel", 0),
    OperatorType.OP_MULTIHEAD_ATTENTION: ("wo", 0),
    OperatorType.OP_EMBEDDING: ("weight", 0),
    OperatorType.OP_CONV2D: ("kernel", 2),
}


@dataclasses.dataclass
class InterpResult:
    # (guid, out_idx) -> Placement for every tensor the walk reached
    values: Dict[Tuple[int, int], Placement]
    # FF001 findings discovered during propagation
    diagnostics: List[Diagnostic]


def _default_placement(shape, data_axis: Optional[str]) -> Placement:
    """The placement we assume when nothing is declared: activations ride
    the data-parallel batch split on dim 0, everything else replicated —
    the executor's ``batch_sharding`` convention."""
    ndim = len(shape)
    if ndim == 0 or data_axis is None:
        return Placement.replicated(ndim)
    return Placement(dims=(data_axis,) + (None,) * (ndim - 1))


def _partial_axes_produced(node, ns) -> Tuple[str, ...]:
    """Mesh axes the node's output is an unreduced partial sum over, from
    the strategy's weight shardings alone."""
    if ns is None or not ns.weight_specs:
        return ()
    probe = _CONTRACTION_WEIGHT_DIMS.get(node.op.op_type)
    if probe is None:
        return ()
    wname, cdim = probe
    spec = ns.weight_specs.get(wname)
    if not spec or cdim >= len(spec):
        return ()
    return entry_axes(spec[cdim])


def interpret(pcg, strategy, data_axis: Optional[str] = None
              ) -> InterpResult:
    """Run the abstract interpreter; returns placements + FF001 findings.

    ``strategy`` may be None (a bare graph — everything defaults to the
    batch-split placement and no partials can arise)."""
    from .rules import RULES

    ff001 = RULES["FF001"]
    node_strats = (strategy.node_strategies if strategy is not None else {})
    if data_axis is None and strategy is not None:
        data_axis = (strategy.data_axis
                     if strategy.data_axis in tuple(strategy.axis_names)
                     else None)
    values: Dict[Tuple[int, int], Placement] = {}
    diags: List[Diagnostic] = []
    # one FF001 per offending producer tensor, not per consumer edge —
    # after reporting, the value is treated as reduced so a fan-out of
    # consumers doesn't bury the root cause in repeats
    flagged_partials: set = set()

    for node in pcg.topo_order():
        ot = node.op.op_type
        ns = node_strats.get(node.guid)
        out_shapes = node.out_shapes or [()]
        if ot == OperatorType.OP_INPUT:
            values[(node.guid, 0)] = _default_placement(out_shapes[0],
                                                        data_axis)
            continue
        if ot == OperatorType.OP_WEIGHT:
            values[(node.guid, 0)] = Placement.replicated(len(out_shapes[0]))
            continue

        in_places = [values.get((g, i),
                                Placement.replicated(
                                    len(pcg.nodes[g].out_shapes[i])))
                     for g, i in node.inputs]

        if getattr(node.op, "is_parallel_op", False):
            out = _transfer_parallel_op(pcg, node, ns, in_places, values,
                                        diags, flagged_partials, ff001,
                                        data_axis)
            for idx in range(len(out_shapes)):
                values[(node.guid, idx)] = out
            continue

        # ---- compute op: consuming a partial value is the FF001 defect
        for slot, ((g, i), place) in enumerate(zip(node.inputs, in_places)):
            if place.is_partial and (g, i) not in flagged_partials:
                flagged_partials.add((g, i))
                prod = pcg.nodes[g].name
                axes = ", ".join(sorted(place.partial))
                diags.append(Diagnostic(
                    rule_id="FF001", node=node.name,
                    message=(f"consumes input {slot} from '{prod}' that is "
                             f"an unreduced partial_sum over mesh axis "
                             f"({axes}); only a Reduction parallel op (or "
                             "an output sharding constraint on the "
                             "producer) may consume a partial sum"),
                    fix_hint=ff001.fix_hint))

        partial_axes = _partial_axes_produced(node, ns)
        out_spec = ns.output_spec if ns is not None else None
        if out_spec is not None:
            # a declared constraint both pins the sharding and discharges
            # any partial the op produced (XLA materializes the reduce)
            out = Placement.from_spec(out_spec, len(out_shapes[0]))
        else:
            out = _propagate(node, in_places, out_shapes[0], ns, data_axis)
            if partial_axes:
                out = out.with_partial(partial_axes)
        for idx, shp in enumerate(out_shapes):
            if idx == 0 or len(shp) == len(out_shapes[0]):
                values[(node.guid, idx)] = dataclasses.replace(out)
            else:
                values[(node.guid, idx)] = _default_placement(shp, data_axis)
    return InterpResult(values=values, diagnostics=diags)


def _transfer_parallel_op(pcg, node, ns, in_places, values, diags,
                          flagged_partials, ff001, data_axis) -> Placement:
    """Transfer function for the parallel-op IR nodes
    (parallel/parallel_op.py): Reduction discharges partial sums; every
    other resharding node requires an already-reduced input."""
    ot = node.op.op_type
    g, i = node.inputs[0] if node.inputs else (None, 0)
    inp = in_places[0] if in_places else Placement.replicated(0)
    ndim = len(node.out_shapes[0]) if node.out_shapes else 0
    out_spec = ns.output_spec if ns is not None else None

    if ot == OperatorType.OP_REDUCTION:
        axes = tuple(a for a in (node.op.attrs.get("axes") or ()) if a)
        if not axes:
            axes = tuple(sorted(inp.partial))
        reduced_any = bool(inp.partial & set(axes))
        if not reduced_any and (g, i) not in flagged_partials:
            prod = pcg.nodes[g].name if g in pcg.nodes else "?"
            diags.append(Diagnostic(
                rule_id="FF001", node=node.name,
                message=(f"reduces over mesh axis {axes} but its input "
                         f"from '{prod}' is not a partial_sum over "
                         f"{axes} (placement: {inp.describe()}) — a "
                         "doubled reduction double-counts the allreduce "
                         "and scales the value by the axis degree"),
                fix_hint=ff001.fix_hint))
        out = inp.reduce_over(axes)
        if out_spec is not None:
            return Placement.from_spec(out_spec, ndim)
        return out

    # Combine / Repartition / Replicate / AllToAll / FusedParallel: pure
    # resharding of a *complete* value — moving partial terms between
    # devices without reducing them is the same wrong-gradient defect
    if inp.is_partial and (g, i) not in flagged_partials:
        flagged_partials.add((g, i))
        prod = pcg.nodes[g].name if g in pcg.nodes else "?"
        axes = ", ".join(sorted(inp.partial))
        diags.append(Diagnostic(
            rule_id="FF001", node=node.name,
            message=(f"reshards ({ot.name}) a value from '{prod}' that is "
                     f"still an unreduced partial_sum over ({axes}); "
                     "insert the Reduction before the reshard"),
            fix_hint=ff001.fix_hint))
    if out_spec is not None:
        return Placement.from_spec(out_spec, ndim)
    return dataclasses.replace(inp, partial=frozenset())


def _propagate(node, in_places, out_shape, ns, data_axis) -> Placement:
    """Placement of an undeclared compute output: state-preserving and
    elementwise ops keep their (shape-identical) input placement; a
    column-parallel Linear shards its last dim like its kernel's out-dim;
    anything rank-changing falls back to the batch-split default."""
    ot = node.op.op_type
    ndim = len(out_shape)
    if ot == OperatorType.OP_LINEAR and ns is not None and ns.weight_specs:
        kspec = ns.weight_specs.get("kernel")
        if kspec and len(kspec) >= 2:
            col_axes = entry_axes(kspec[1])
            if col_axes:
                base = _default_placement(out_shape, data_axis)
                dims = list(base.dims)
                dims[-1] = col_axes[0] if len(col_axes) == 1 \
                    else tuple(col_axes)
                return Placement(dims=tuple(dims))
    if (ot in _STATE_PRESERVING or ot in _ELEMENTWISE_BINARY) \
            and in_places:
        src = in_places[0]
        if len(src.dims) == ndim:
            return dataclasses.replace(src, partial=frozenset())
    return _default_placement(out_shape, data_axis)
