"""The per-tensor placement lattice ShardLint's abstract interpreter runs on.

Every tensor in a parallelized PCG is, per mesh axis, in exactly one of
three placement states — the same vocabulary the reference's parallel-op IR
encodes operationally (Replicate/Repartition/Combine/Reduction nodes,
src/parallel_ops/) and the Unity DP encodes as its {R, S, Q, H} sharding
states (search/unity.node_options):

* **replicated** — every device along the axis holds the full value;
* **sharded(axis, dim)** — tensor dim ``dim`` is split over mesh axis
  ``axis`` (covers the DP batch split, tp column outputs, sequence and
  spatial shards alike);
* **partial_sum(axis)** — every device holds an *unreduced partial term*
  of a contraction over a dim that was sharded on ``axis`` (the output of
  a row-parallel Linear before its psum; ``parallel/parallel_op.py``
  ReductionOp semantics, ``parallel/strategies.py`` row-parallel
  comments). A partial value is NOT the tensor: consuming it as if it
  were — or reducing it twice — is the silent-wrong-gradient defect class
  the dynamic audit (resilience/audit.py) can only catch by running a
  probe step. Here it is a lattice state, decidable without hardware.

A :class:`Placement` carries both facets at once: ``dims[d]`` names the
mesh axes tensor dim ``d`` is sharded over (None = not sharded), and
``partial`` is the set of mesh axes the value is an unreduced partial sum
over. ``replicated`` is the bottom element (no sharded dims, no partials).
"""
from __future__ import annotations

import dataclasses
from typing import FrozenSet, Optional, Sequence, Tuple, Union

# one per-dim entry: None, one axis name, or a tuple of axis names (the
# PartitionSpec convention Strategy.weight_specs/output_spec already uses)
DimEntry = Union[None, str, Tuple[str, ...]]


def entry_axes(entry: DimEntry) -> Tuple[str, ...]:
    """Mesh axes named by one per-dim spec entry."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(a for a in entry if a is not None)
    return (entry,)


@dataclasses.dataclass(frozen=True)
class Placement:
    """Abstract placement of one tensor over the strategy's mesh."""

    dims: Tuple[DimEntry, ...] = ()
    partial: FrozenSet[str] = frozenset()

    # ------------------------------------------------------------ factories
    @staticmethod
    def replicated(ndim: int) -> "Placement":
        return Placement(dims=(None,) * ndim)

    @staticmethod
    def from_spec(spec: Optional[Sequence[DimEntry]],
                  ndim: int) -> "Placement":
        """Placement pinned by a declared PartitionSpec (output_spec /
        weight_specs entry). A declared spec never carries partial sums:
        lowering it to ``with_sharding_constraint`` forces XLA to
        materialize the reduction that discharges any pending partial."""
        if spec is None:
            return Placement.replicated(ndim)
        entries = tuple(spec)[:ndim]
        entries = entries + (None,) * (ndim - len(entries))
        return Placement(dims=entries)

    # -------------------------------------------------------------- queries
    def sharded_axes(self) -> Tuple[str, ...]:
        out = []
        for e in self.dims:
            out.extend(entry_axes(e))
        return tuple(out)

    def axes_of_dim(self, dim: int) -> Tuple[str, ...]:
        if 0 <= dim < len(self.dims):
            return entry_axes(self.dims[dim])
        return ()

    @property
    def is_partial(self) -> bool:
        return bool(self.partial)

    # ---------------------------------------------------------- transitions
    def with_partial(self, axes: Sequence[str]) -> "Placement":
        return dataclasses.replace(
            self, partial=self.partial | frozenset(axes))

    def reduce_over(self, axes: Sequence[str]) -> "Placement":
        """Discharge a partial sum over ``axes`` (a Reduction node / an
        output constraint)."""
        return dataclasses.replace(
            self, partial=self.partial - frozenset(axes))

    def describe(self) -> str:
        bits = []
        for d, e in enumerate(self.dims):
            for a in entry_axes(e):
                bits.append(f"sharded({a}@dim{d})")
        for a in sorted(self.partial):
            bits.append(f"partial_sum({a})")
        return " + ".join(bits) if bits else "replicated"
