"""ShardLint diagnostics: stable rule IDs, actionable messages, one report.

Every finding is a :class:`Diagnostic` with a rule ID (FF001..FF006 —
documented with examples in ``docs/static_analysis.md``), the offending
node's name, a message saying what is wrong, and a fix hint saying what to
change. A :class:`AnalysisReport` aggregates one analysis run; consumers:

* ``resilience.fallback.StrategyCascade`` — stage 0: an erroring report
  raises :class:`StaticAnalysisError` and the cascade degrades to the next
  ranked candidate WITHOUT paying a compile/probe;
* ``search.unity`` — candidate pruning before simulation;
* the CLI (``python -m flexflow_tpu.analysis`` / ``scripts/fflint.py``) —
  prints ``format_line()`` per diagnostic, exit status 1 on errors;
* ``obs.StepTelemetry`` — ``telemetry_block()`` is the ``strategy_static``
  summary block.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    rule_id: str          # "FF001".."FF006"
    node: str             # offending PCG node name ("" = graph/plan level)
    message: str          # what is statically wrong
    fix_hint: str = ""    # what to change
    severity: str = "error"   # "error" | "warning"

    def format_line(self) -> str:
        where = f" node '{self.node}'" if self.node else ""
        line = f"{self.rule_id}{where}: {self.message}"
        if self.fix_hint:
            line += f" [fix: {self.fix_hint}]"
        return line


@dataclasses.dataclass
class AnalysisReport:
    """The result of one static analysis pass over (PCG, Strategy)."""

    diagnostics: List[Diagnostic] = dataclasses.field(default_factory=list)
    # which rule checkers ran (rule IDs), independent of whether they fired
    checked: Tuple[str, ...] = ()
    strategy_desc: str = ""

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def rules_fired(self) -> List[str]:
        return sorted({d.rule_id for d in self.diagnostics})

    def describe(self) -> str:
        if not self.diagnostics:
            return "clean (0 diagnostics)"
        return "; ".join(d.format_line() for d in self.diagnostics)

    def format(self) -> str:
        lines = [d.format_line() for d in self.diagnostics]
        lines.append(f"{len(self.errors)} error(s), "
                     f"{len(self.diagnostics) - len(self.errors)} "
                     "warning(s)")
        return "\n".join(lines)

    def telemetry_block(self) -> Dict[str, Any]:
        return {
            "diagnostics": len(self.diagnostics),
            "errors": len(self.errors),
            "rules": self.rules_fired(),
        }


class StaticAnalysisError(ValueError):
    """The analyzer statically rejected the plan — raised by cascade
    stage 0 and by ``FFModel.compile`` under ``--static-analysis strict``.
    The message lists every diagnostic with rule ID, node, and fix hint."""

    def __init__(self, report: AnalysisReport, context: str = ""):
        self.report = report
        head = "static analysis rejected the plan"
        if context:
            head += f" ({context})"
        super().__init__(head + ":\n  " + "\n  ".join(
            d.format_line() for d in report.errors))
