"""ShardLint CLI: statically verify a (model, strategy) pair from the shell.

    python -m flexflow_tpu.analysis --model mlp --strategy hybrid --tp 2
    python -m flexflow_tpu.analysis --model attention --strategy hybrid \
        --inject duplicate               # demo: FF001 doubled reduction
    python -m flexflow_tpu.analysis --model mlp \
        --strategy /path/to/exported_strategy.json

Builds the demo model's PCG (no parameters, no devices, no compile — the
whole point), resolves the strategy (a built-in family or an
``--export-strategy`` JSON file), optionally injects a graph-level
wrong-reshard defect (the ``resilience.chaos`` injection, so the CLI can
demonstrate exactly what the cascade's stage 0 rejects), runs the
analyzer, and prints one diagnostic per line with rule ID and fix hint.
Exit status: 0 clean, 1 diagnostics with errors, 2 usage error.
``scripts/fflint.py`` wraps this (and adds the code-level lint gate).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from . import analyze_strategy
from .report import AnalysisReport


def _build_demo(model: str):
    """A tiny model of the requested family, as (FFModel, PCG). Imports
    live here so ``--help`` works without jax."""
    from .. import FFConfig, FFModel

    cfg = FFConfig()
    ff = FFModel(cfg)
    if model == "mlp":
        # 3 dense layers so hybrid/tp plans have a row-parallel MIDDLE
        # layer — a partial-sum producer with consumers, i.e. an
        # --inject-able reduction site (the last layer's partial sum has
        # no consumers to mis-serve)
        x = ff.create_tensor((8, 16), name="x")
        t = ff.dense(x, 32, name="d1")
        t = ff.relu(t)
        t = ff.dense(t, 32, name="d2")
        t = ff.relu(t)
        t = ff.dense(t, 10, name="d3")
    elif model == "attention":
        x = ff.create_tensor((8, 16, 32), name="x")
        t = ff.multihead_attention(x, x, x, embed_dim=32, num_heads=4,
                                   name="attn")
        t = ff.dense(t, 32, name="proj")
        t = ff.relu(t)
        t = ff.dense(t, 10, name="head")
    else:
        print(f"error: unknown --model {model!r} (mlp|attention)",
              file=sys.stderr)
        raise SystemExit(2)
    return ff, ff.create_pcg()


def _resolve_strategy(pcg, kind: str, dp: int, tp: int):
    from ..parallel.strategies import hybrid_data_tensor_strategy
    from ..parallel.strategy import Strategy, data_parallel_strategy

    if kind.endswith(".json"):
        try:
            with open(kind) as f:
                return Strategy.from_json(f.read(), pcg)
        except Exception as e:
            print(f"error: cannot load strategy from {kind!r}: {e}",
                  file=sys.stderr)
            raise SystemExit(2)
    if kind == "dp":
        return data_parallel_strategy(pcg, dp)
    if kind in ("tp", "hybrid"):
        return hybrid_data_tensor_strategy(pcg, dp if kind == "hybrid"
                                           else 1, tp)
    if kind == "pipeline":
        s = data_parallel_strategy(pcg, dp)
        s.pipeline = (2, max(dp // 2, 1), 2)
        return s
    if kind == "remat":
        s = data_parallel_strategy(pcg, dp)
        s.remat = "selective"
        return s
    print(f"error: unknown --strategy {kind!r} "
          "(dp|tp|hybrid|pipeline|remat|*.json)", file=sys.stderr)
    raise SystemExit(2)


def _print_report(report: AnalysisReport, as_json: bool,
                  header: str) -> None:
    if as_json:
        print(json.dumps({
            "strategy": report.strategy_desc,
            "checked": list(report.checked),
            "diagnostics": [{
                "rule": d.rule_id, "node": d.node, "severity": d.severity,
                "message": d.message, "fix": d.fix_hint,
            } for d in report.diagnostics],
            **report.telemetry_block(),
        }, indent=2))
        return
    print(header)
    for d in report.diagnostics:
        print("  " + d.format_line())
    n_err = len(report.errors)
    verdict = "FAIL" if n_err else "clean"
    print(f"  {len(report.diagnostics)} diagnostic(s), {n_err} error(s) "
          f"-- {verdict} (rules checked: {', '.join(report.checked)})")


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.analysis",
        description="ShardLint: static sharding/dataflow verification of "
                    "a parallel plan (docs/static_analysis.md)")
    ap.add_argument("--model", default="mlp",
                    help="demo model family: mlp | attention")
    ap.add_argument("--strategy", default="hybrid",
                    help="dp | tp | hybrid | pipeline | remat, or a "
                         "--export-strategy JSON file")
    ap.add_argument("--dp", type=int, default=4,
                    help="data-parallel degree of the built-in strategies")
    ap.add_argument("--tp", type=int, default=2,
                    help="tensor-parallel degree of tp/hybrid strategies")
    ap.add_argument("--inject", default="none",
                    choices=("none", "drop", "duplicate"),
                    help="inject a graph-level wrong-reshard defect "
                         "before analyzing (FF001 demo)")
    ap.add_argument("--serving", action="store_true",
                    help="also run the serving-state reachability check "
                         "(FF005)")
    ap.add_argument("--placements", action="store_true",
                    help="dump the per-tensor placement lattice")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    _ff, pcg = _build_demo(args.model)
    strategy = _resolve_strategy(pcg, args.strategy, args.dp, args.tp)
    injected = ""
    if args.inject != "none":
        from ..resilience.chaos import inject_wrong_reshard

        try:
            injected = inject_wrong_reshard(pcg, strategy,
                                            mode=args.inject)
        except ValueError as e:
            print(f"error: cannot --inject {args.inject}: {e}",
                  file=sys.stderr)
            return 2
    report = analyze_strategy(pcg, strategy, serving=args.serving)
    header = (f"ShardLint: model={args.model} "
              f"strategy='{strategy.describe()}' nodes={len(pcg)}")
    if injected:
        header += f" [injected: {injected}]"
    _print_report(report, args.as_json, header)
    if args.placements and not args.as_json:
        from .interp import interpret

        for (guid, idx), place in sorted(
                interpret(pcg, strategy).values.items()):
            node = pcg.nodes.get(guid)
            if node is not None:
                print(f"  {node.name}[{idx}]: {place.describe()}")
    return 1 if report.errors else 0


if __name__ == "__main__":
    sys.exit(main())
