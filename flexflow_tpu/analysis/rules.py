"""ShardLint rule registry and the non-lattice rule checkers.

Stable, documented rule IDs (``docs/static_analysis.md`` holds the full
table — ID, what it proves, example diagnostic, fix hint; the
``scripts/check_docs_rules.py`` housekeeping gate keeps the two in sync):

* **FF001** — partial-sum placement: an unreduced ``partial_sum`` reaching
  a consumer that requires a complete value, or a Reduction applied to a
  value that is not partial (a doubled allreduce). Emitted by the
  abstract interpreter (``interp.py``).
* **FF002** — donation-aliasing safety: a buffer the jitted step donates
  (``donate_argnums``) that something still references after the step
  without a device-side copy — the PR 4 async-checkpoint bug class.
* **FF003** — rng-stream collision: two stochastic op executions that
  statically fold the same (key, counter) stream.
* **FF004** — remat segmentation: remat blocks that fail to partition the
  compute graph, or cut an edge backwards against the topological order.
* **FF005** — serving-state reachability: stateful/position ops folded
  inside a FusedOp, where the serving engine cannot thread decode state —
  the ``serving/engine.py`` runtime refusal, promoted to a pre-serve
  diagnostic.
* **FF006** — shape/divisibility dataflow: every declared PartitionSpec
  axis exists in the mesh and every sharded dim divides its axis size —
  the per-node half of ``resilience.preflight.preflight_strategy``, which
  now routes through this checker (single source of truth).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..ffconst import OperatorType
from .lattice import entry_axes
from .report import Diagnostic


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    title: str
    proves: str       # the property a clean pass establishes
    fix_hint: str     # default remediation shown with each diagnostic


RULES: Dict[str, Rule] = {r.rule_id: r for r in (
    Rule("FF001", "partial-sum placement",
         "every partial_sum produced by a sharded contraction is reduced "
         "exactly once before any consumer needs the complete value",
         "add the missing Reduction parallel op (or output_spec) after "
         "the sharded contraction, or remove the duplicated one"),
    Rule("FF002", "donation-aliasing safety",
         "no buffer donated to the jitted step (donate_argnums) is "
         "referenced after the step without a device-side copy",
         "snapshot the buffer with jnp.copy / checkpoint._device_snapshot "
         "before the step donates it"),
    Rule("FF003", "rng-stream collision",
         "no two stochastic op executions fold the same (key, counter) "
         "prng stream",
         "give every stochastic node a unique guid in the execution "
         "order (a node scheduled twice replays the same dropout mask)"),
    Rule("FF004", "remat segmentation",
         "remat blocks partition the compute graph and respect the "
         "topological order (no edge flows backwards across a cut)",
         "use execution.remat.remat_segments for the segmentation, or "
         "repair the graph order with PCG.retopo()"),
    Rule("FF005", "serving-state reachability",
         "no stateful (attention/LSTM) or position op is folded inside a "
         "FusedOp region, where the serving engine cannot thread decode "
         "state",
         "recompile without --fusion to serve this model"),
    Rule("FF006", "shape/divisibility dataflow",
         "every declared PartitionSpec axis exists in the mesh and every "
         "sharded tensor dim divides its mesh-axis size",
         "use a mesh whose axis sizes divide the sharded dims, or drop "
         "the offending spec entry"),
)}


# ------------------------------------------------------------------- FF002
@dataclasses.dataclass(frozen=True)
class BufferRef:
    """A reference held across the step boundary."""

    holder: str            # who retains it ("CheckpointManager", ...)
    buffer: str            # which step argument ("params", "opt_state", ..)
    device_copy: bool = False  # True when snapshotted (jnp.copy) pre-step


@dataclasses.dataclass(frozen=True)
class DonationSpec:
    """The aliasing contract of one jitted step: which arguments the jit
    donates, and every reference something retains past the dispatch."""

    step: str
    donated: Tuple[str, ...]
    post_step_refs: Tuple[BufferRef, ...] = ()


def check_donation(spec: DonationSpec) -> List[Diagnostic]:
    """FF002: donated buffers are INVALIDATED by the step; any retained
    reference must be a device-side copy or it reads freed memory (the
    async-checkpoint bug class PR 4 fixed with ``_device_snapshot``)."""
    out: List[Diagnostic] = []
    donated = set(spec.donated)
    for ref in spec.post_step_refs:
        if ref.buffer in donated and not ref.device_copy:
            out.append(Diagnostic(
                rule_id="FF002", node=spec.step,
                message=(f"'{ref.holder}' keeps a reference to donated "
                         f"buffer '{ref.buffer}' past the step dispatch "
                         "without a device-side copy; donate_argnums "
                         "invalidates the buffer the moment the step "
                         "runs"),
                fix_hint=RULES["FF002"].fix_hint))
    return out


def donation_spec_for_training(ffmodel) -> DonationSpec:
    """The live training step's aliasing contract: the jit donates params
    and opt_state (execution/executor.py make_train_step); the known
    retainer (CheckpointManager) DECLARES whether it snapshots
    device-side via ``checkpoint.SNAPSHOT_DEVICE_COPY``, co-located with
    the ``_device_snapshot`` copy code — the analyzer checks the declared
    contract, it does not re-derive it from the implementation."""
    from ..execution.checkpoint import SNAPSHOT_DEVICE_COPY

    refs = []
    cfg = ffmodel.config
    if getattr(cfg, "checkpoint_dir", "") and \
            int(getattr(cfg, "checkpoint_every", 0) or 0) > 0:
        refs.append(BufferRef("CheckpointManager", "params",
                              device_copy=SNAPSHOT_DEVICE_COPY))
        refs.append(BufferRef("CheckpointManager", "opt_state",
                              device_copy=SNAPSHOT_DEVICE_COPY))
    return DonationSpec(step="train_step", donated=("params", "opt_state"),
                        post_step_refs=tuple(refs))


# ------------------------------------------------------------------- FF003
_STOCHASTIC_OPS = {OperatorType.OP_DROPOUT}


def _is_stochastic(op) -> bool:
    if op.op_type in _STOCHASTIC_OPS:
        return True
    if op.op_type in (OperatorType.OP_MULTIHEAD_ATTENTION,
                      OperatorType.OP_SDPA):
        return float(op.attrs.get("dropout", 0.0) or 0.0) > 0.0
    if op.op_type == OperatorType.OP_FUSED:
        return any(_is_stochastic(s) for s in getattr(op, "sub_ops", ()))
    return False


def check_rng_streams(pcg) -> List[Diagnostic]:
    """FF003: the executor derives every stochastic op's stream as
    ``fold_in(step_rng, guid)`` (and ``fold_in(.., sub_index)`` inside a
    FusedOp). A guid scheduled more than once in the execution order
    therefore replays the SAME stream — two dropout applications with an
    identical mask, statically decidable from the order alone."""
    out: List[Diagnostic] = []
    seen: Dict[int, int] = {}
    for guid in pcg._order:
        seen[guid] = seen.get(guid, 0) + 1
    for guid, count in seen.items():
        if count <= 1:
            continue
        node = pcg.nodes.get(guid)
        if node is None or not _is_stochastic(node.op):
            continue
        out.append(Diagnostic(
            rule_id="FF003", node=node.name,
            message=(f"stochastic op is scheduled {count} times in the "
                     f"execution order with the same guid {guid}: every "
                     "execution folds the identical (key, counter) rng "
                     "stream and replays the same mask"),
            fix_hint=RULES["FF003"].fix_hint))
    return out


# ------------------------------------------------------------------- FF004
def check_remat(pcg, level: str, segment_size: int = 8,
                segments: Optional[Sequence[Sequence[int]]] = None,
                kind: str = "remat") -> List[Diagnostic]:
    """FF004: the remat segmentation must partition the compute nodes
    (every node checkpointed exactly once) and respect the topological
    order — an edge flowing backwards across a cut means a block would
    consume a boundary value produced by a LATER block, which the
    checkpointed forward cannot thread (a stateful CacheOp edge cut this
    way is the pre-PR 6 decode-state bug class).

    ``kind="stage"`` judges a PIPELINE stage-chunk segmentation by the
    same two laws (partition + topological cuts) with stage-cut wording.
    Note the laws are about CUT ORDER in the graph, not device placement:
    the interleaved schedule's round-robin chunk->device assignment
    (chunk c on device c % pp, pp*v chunks) is a legal segmentation — a
    validator that conflated chunk index with device rank would
    misdiagnose every interleaved plan as a backwards stage cut
    (ISSUE 10; tests/test_pipeline_schedules.py pins this)."""
    if kind == "remat" and (not level or level == "none"):
        return []
    what_seg = "remat" if kind == "remat" else "stage-chunk"
    block = "remat block" if kind == "remat" else "stage chunk"
    if segments is None:
        from ..execution.remat import remat_segments

        segments = remat_segments(pcg, segment_size)
    out: List[Diagnostic] = []
    compute = [n.guid for n in pcg.compute_nodes()]
    seg_of: Dict[int, int] = {}
    dupes = set()
    for si, seg in enumerate(segments):
        for g in seg:
            if g in seg_of:
                dupes.add(g)
            seg_of[g] = si
    missing = [g for g in compute if g not in seg_of]
    for what, guids in (("misses", missing), ("duplicates", sorted(dupes))):
        if not guids:
            continue
        names = [pcg.nodes[g].name for g in guids if g in pcg.nodes]
        out.append(Diagnostic(
            rule_id="FF004", node=names[0] if names else "",
            message=(f"{what_seg} segmentation {what} compute node(s) "
                     f"{names}: the blocks do not partition the graph, so "
                     "the checkpointed forward and the simulator's memory "
                     "accounting diverge"),
            fix_hint=RULES["FF004"].fix_hint))
    for n in pcg.compute_nodes():
        if n.guid not in seg_of:
            continue
        for g, _i in n.inputs:
            if g in seg_of and seg_of[g] > seg_of[n.guid]:
                prod = pcg.nodes[g]
                stateful = (" (stateful edge)"
                            if prod.op.op_type == OperatorType.OP_CACHE
                            else "")
                out.append(Diagnostic(
                    rule_id="FF004", node=n.name,
                    message=(f"consumes '{prod.name}' from {block} "
                             f"{seg_of[g]} while living in earlier "
                             f"{block} {seg_of[n.guid]}{stateful}: the "
                             "cut runs against the topological order"),
                    fix_hint=RULES["FF004"].fix_hint))
    return out


# ------------------------------------------------------------------- FF005
def check_serving_graph(pcg) -> List[Diagnostic]:
    """FF005: the per-node serving machinery (prefill/decode state
    threading, position-constant overrides) cannot see inside a FusedOp —
    a fused stateful op would decode without history and a fused position
    constant escapes the override hook. The serving engine refuses such
    graphs at run time (serving/engine.py); this is the same judgement,
    available before any engine (or device) exists."""
    from ..serving.kvcache import is_position_constant

    out: List[Diagnostic] = []
    for node in pcg.compute_nodes():
        if node.op.op_type != OperatorType.OP_FUSED:
            continue
        for sub in getattr(node.op, "sub_ops", ()):
            stateful = sub.op_type in (OperatorType.OP_MULTIHEAD_ATTENTION,
                                       OperatorType.OP_LSTM)
            positional = (sub.op_type == OperatorType.OP_CONSTANT
                          and is_position_constant(sub.attrs.get("value")))
            if stateful or positional:
                out.append(Diagnostic(
                    rule_id="FF005", node=node.name,
                    message=(f"fusion folded the stateful/position op "
                             f"'{sub.name}' into a fused region; the "
                             "serving engine cannot thread decode state "
                             "through it and would generate history-free "
                             "garbage"),
                    fix_hint=RULES["FF005"].fix_hint))
    return out


# ------------------------------------------------------------------- FF006
def check_shapes(pcg, strategy) -> List[Diagnostic]:
    """FF006: the declared-spec shape/divisibility dataflow. This IS the
    per-node half of ``preflight_strategy`` — the preflight re-routes
    through here (single source of truth), so the diagnostic messages
    keep the exact preflight error texts the tests and users know."""
    axes = tuple(strategy.axis_names)
    axis_size = dict(zip(axes, (int(s) for s in strategy.mesh_shape)))
    out: List[Diagnostic] = []

    def check_spec(node_name: str, where: str, spec, shape) -> None:
        for dim, e in enumerate(spec or ()):
            for a in entry_axes(e):
                if a not in axis_size:
                    out.append(Diagnostic(
                        rule_id="FF006", node=node_name,
                        message=(f"{where}: PartitionSpec names mesh axis "
                                 f"{a!r} (dim {dim}) but the strategy's "
                                 f"mesh axes are {axes}"),
                        fix_hint=RULES["FF006"].fix_hint))
                    continue
                sz = axis_size[a]
                if shape is not None and dim < len(shape) and sz > 1 and \
                        shape[dim] % sz:
                    out.append(Diagnostic(
                        rule_id="FF006", node=node_name,
                        message=(f"{where}: dim {dim} has size "
                                 f"{shape[dim]}, not divisible by mesh "
                                 f"axis {a!r} (size {sz}); the plan "
                                 "cannot shard it evenly"),
                        fix_hint=RULES["FF006"].fix_hint))

    for guid, ns in strategy.node_strategies.items():
        node = pcg.nodes.get(guid) if pcg is not None else None
        name = node.name if node is not None else f"node guid {guid}"
        wshapes: Dict[str, Tuple[int, ...]] = {}
        if node is not None and ns.weight_specs:
            try:
                in_shapes = [pcg.nodes[g].out_shapes[i]
                             for g, i in node.inputs]
                wshapes = {w: tuple(s) for w, (s, _d, _i) in
                           node.op.weight_specs(in_shapes).items()}
            except Exception:
                wshapes = {}
        for wname, spec in (ns.weight_specs or {}).items():
            check_spec(name, f"{name}.{wname}", spec, wshapes.get(wname))
        if ns.output_spec:
            oshape = (tuple(node.out_shapes[0])
                      if node is not None and node.out_shapes else None)
            check_spec(name, f"{name} output", ns.output_spec, oshape)
    return out


def check_paged_kv(pcg, *, block_size: int, pool_blocks: int,
                   max_blocks_per_slot: int, max_context: int,
                   kv_layout: str = "replicated",
                   tp: int = 1,
                   prefill_chunk_tokens: int = 0,
                   seq_shards: int = 1,
                   n_devices: int = 1,
                   context_buckets: Sequence[int] = ()) -> List[Diagnostic]:
    """FF006 extension (ISSUE 12; chunk laws ISSUE 14): static shape
    laws of a paged-KV serving configuration — judged with ZERO compile,
    so a misconfigured layout is rejected at engine construction (or
    plan lint), not by an opaque scatter failure ten decode steps in.

    * ``block_size`` must be positive, and the pool must be whole blocks
      with at least one usable block past the reserved garbage block;
    * the pool must hold at least one max-context request — anything
      smaller deadlocks admission by construction — PLUS one live chunk
      when chunked prefill is on (the chunk's copy-on-write spare and
      co-scheduled neighbors otherwise starve);
    * ``--prefill-chunk-tokens`` must be a whole number of KV blocks:
      a chunk boundary inside a block would split one block's rows
      across two chunk programs, breaking the write-before-read law
      shared blocks rely on;
    * the block TABLE must cover the max supported context
      (``max_blocks_per_slot * block_size >= max_context``): a shorter
      table would silently truncate a legal request's KV extent;
    * under a heads-sharded KV layout every attention node's head count
      must divide ``tp`` — the per-chip pool shard otherwise splits a
      head's rows across chips;
    * sequence-parallel decode (ISSUE 18): ``seq_shards`` must divide
      the block-table width evenly (each shard chip owns a contiguous
      ``max_blocks_per_slot / seq_shards`` run — a ragged split would
      give shards different compiled extents), every searched context
      bucket must fit the table, and on a real mesh ``seq_shards`` must
      divide the device count — composed with a heads-sharded layout,
      ``tp * seq_shards`` must too (the seq axis multiplies the KV
      grid, it does not replace it).
    """
    out: List[Diagnostic] = []
    hint = ("fix the paged-KV knobs (--kv-block-size / --kv-pool-blocks "
            "/ --max-decode-len) so the block table and pool cover the "
            "supported context")
    if block_size < 1:
        out.append(Diagnostic(
            rule_id="FF006", node="",
            message=f"paged KV: block_size must be >= 1 (got "
                    f"{block_size})", fix_hint=hint))
        return out
    if pool_blocks < 2:
        out.append(Diagnostic(
            rule_id="FF006", node="",
            message=(f"paged KV: pool has {pool_blocks} block(s); needs "
                     ">= 2 (the reserved garbage block + at least one "
                     "usable block)"), fix_hint=hint))
    chunk_blocks = 0
    if prefill_chunk_tokens:
        if prefill_chunk_tokens % block_size:
            out.append(Diagnostic(
                rule_id="FF006", node="",
                message=(f"chunked prefill: --prefill-chunk-tokens "
                         f"({prefill_chunk_tokens}) must be a multiple "
                         f"of --kv-block-size ({block_size}) — a chunk "
                         "boundary inside a block would split one "
                         "block's rows across two chunk programs"),
                fix_hint="pick a chunk size that is a whole number of "
                         "KV blocks"))
        chunk_blocks = -(-int(prefill_chunk_tokens) // int(block_size))
    need = -(-int(max_context) // int(block_size))
    if pool_blocks - 1 < need + chunk_blocks:
        plus = (f" plus one live {prefill_chunk_tokens}-token chunk"
                if chunk_blocks else "")
        out.append(Diagnostic(
            rule_id="FF006", node="",
            message=(f"paged KV: pool's {pool_blocks - 1} usable blocks "
                     f"({(pool_blocks - 1) * block_size} tokens) cannot "
                     f"hold one max-context request ({max_context} "
                     f"tokens){plus} — admission would deadlock"),
            fix_hint=hint))
    if max_blocks_per_slot * block_size < max_context:
        out.append(Diagnostic(
            rule_id="FF006", node="",
            message=(f"paged KV: block table covers "
                     f"{max_blocks_per_slot * block_size} tokens "
                     f"({max_blocks_per_slot} blocks x {block_size}) "
                     f"< max supported context {max_context}"),
            fix_hint=hint))
    if kv_layout == "sharded" and tp > 1 and pcg is not None:
        for node in pcg.compute_nodes():
            if node.op.op_type != OperatorType.OP_MULTIHEAD_ATTENTION:
                continue
            heads = int(node.op.attrs.get("num_heads", 1))
            if heads % tp:
                out.append(Diagnostic(
                    rule_id="FF006", node=node.name,
                    message=(f"paged KV: heads-sharded layout needs "
                             f"num_heads ({heads}) divisible by tp "
                             f"({tp}); a pool block's head axis cannot "
                             "split a head across chips"),
                    fix_hint="use the replicated KV layout or a tp that "
                             "divides num_heads"))
    shard_hint = ("pick --seq-shards so it divides the block-table "
                  "width (--max-decode-len / --kv-block-size) and the "
                  "mesh; size --context-buckets within the table")
    if seq_shards < 1:
        out.append(Diagnostic(
            rule_id="FF006", node="",
            message=(f"sequence-parallel decode: seq_shards must be "
                     f">= 1 (got {seq_shards})"), fix_hint=shard_hint))
        return out
    if max_blocks_per_slot % seq_shards:
        out.append(Diagnostic(
            rule_id="FF006", node="",
            message=(f"sequence-parallel decode: --seq-shards "
                     f"({seq_shards}) must divide the block-table width "
                     f"({max_blocks_per_slot} blocks) — each shard chip "
                     "owns one contiguous equal run of a slot's blocks; "
                     "a ragged split would give shards different "
                     "compiled extents"), fix_hint=shard_hint))
    for bucket in context_buckets:
        if bucket > max_blocks_per_slot * block_size:
            out.append(Diagnostic(
                rule_id="FF006", node="",
                message=(f"sequence-parallel decode: context bucket "
                         f"{bucket} exceeds the block table's "
                         f"{max_blocks_per_slot * block_size}-token "
                         f"extent ({max_blocks_per_slot} blocks x "
                         f"{block_size}) — requests routed to it could "
                         "never hold their KV"), fix_hint=shard_hint))
    if seq_shards > 1 and n_devices > 1:
        if n_devices % seq_shards:
            out.append(Diagnostic(
                rule_id="FF006", node="",
                message=(f"sequence-parallel decode: --seq-shards "
                         f"({seq_shards}) must divide the mesh "
                         f"({n_devices} devices) — the seq axis is a "
                         "mesh axis, not a remainder"),
                fix_hint=shard_hint))
        elif kv_layout == "sharded" and n_devices % (tp * seq_shards):
            out.append(Diagnostic(
                rule_id="FF006", node="",
                message=(f"sequence-parallel decode: composed KV grid "
                         f"tp x seq_shards ({tp} x {seq_shards} = "
                         f"{tp * seq_shards}) must divide the mesh "
                         f"({n_devices} devices) — the seq axis "
                         "multiplies the heads-sharded layout, it does "
                         "not replace it"), fix_hint=shard_hint))
    return out
