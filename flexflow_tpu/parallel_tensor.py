"""Parallel (sharded) tensor metadata.

Analog of the reference's ``ParallelDim`` / ``ParallelTensorShape`` /
``ParallelTensorBase`` (include/flexflow/parallel_tensor.h:36-126). Each tensor
dim carries ``{size, degree, is_replica_dim}`` exactly as in the reference, plus
the TPU-native realization: the tuple of **mesh axis names** the dim is sharded
over. A replica dim's "size" is its replication degree; at lowering time replica
dims vanish from the array shape — their mesh axes simply do not appear in the
PartitionSpec, which makes the tensor replicated over them (or, for gradients,
unreduced — the distinction drives psum insertion, reference:
Reduction/Replicate parallel-op semantics, src/parallel_ops/).
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

from .ffconst import DataType


@dataclasses.dataclass(frozen=True)
class ParallelDim:
    """One dim of a ParallelTensorShape (reference: parallel_tensor.h:36-70)."""

    size: int  # global extent (for replica dims: the replication degree)
    degree: int = 1  # number of shards along this dim
    parallel_idx: int = -1  # kept for strategy-serialization parity
    is_replica_dim: bool = False
    mesh_axes: Tuple[str, ...] = ()  # mesh axes realizing the sharding

    def __post_init__(self):
        object.__setattr__(self, "mesh_axes", tuple(self.mesh_axes))
        if self.is_replica_dim:
            assert self.degree == self.size, "replica dim degree == size"

    @property
    def is_sharded(self) -> bool:
        return self.degree > 1


@dataclasses.dataclass(frozen=True)
class ParallelTensorShape:
    """Sharded shape (reference: parallel_tensor.h:76)."""

    dims: Tuple[ParallelDim, ...]
    dtype: DataType = DataType.DT_FLOAT

    def __post_init__(self):
        object.__setattr__(self, "dims", tuple(self.dims))

    # -- constructors -----------------------------------------------------------
    @staticmethod
    def unsharded(shape: Sequence[int], dtype: DataType = DataType.DT_FLOAT
                  ) -> "ParallelTensorShape":
        return ParallelTensorShape(
            tuple(ParallelDim(size=int(s)) for s in shape), dtype)

    # -- views ------------------------------------------------------------------
    @property
    def array_dims(self) -> Tuple[ParallelDim, ...]:
        """Dims that exist in the materialized array (replica dims dropped)."""
        return tuple(d for d in self.dims if not d.is_replica_dim)

    @property
    def array_shape(self) -> Tuple[int, ...]:
        return tuple(d.size for d in self.array_dims)

    @property
    def replica_dims(self) -> Tuple[ParallelDim, ...]:
        return tuple(d for d in self.dims if d.is_replica_dim)

    @property
    def num_replica_axes(self) -> Tuple[str, ...]:
        axes: Tuple[str, ...] = ()
        for d in self.replica_dims:
            axes += d.mesh_axes
        return axes

    def total_degree(self) -> int:
        n = 1
        for d in self.dims:
            n *= d.degree
        return n

    def get_piece_shape(self) -> Tuple[int, ...]:
        """Per-shard extent of the materialized array."""
        return tuple(d.size // max(d.degree, 1) for d in self.array_dims)

    def get_piece_num_elements(self) -> int:
        n = 1
        for s in self.get_piece_shape():
            n *= s
        return n

    def num_elements(self) -> int:
        n = 1
        for s in self.array_shape:
            n *= s
        return n

    # -- lowering to jax.sharding ----------------------------------------------
    def partition_spec(self):
        """NamedSharding PartitionSpec over the materialized dims.

        Mesh axes attached to replica dims are intentionally absent from the
        spec: XLA then replicates over them (the Replicate parallel-op
        semantics, reference src/parallel_ops/replicate.cc).
        """
        from jax.sharding import PartitionSpec

        entries = []
        for d in self.array_dims:
            if not d.mesh_axes:
                entries.append(None)
            elif len(d.mesh_axes) == 1:
                entries.append(d.mesh_axes[0])
            else:
                entries.append(tuple(d.mesh_axes))
        # trim trailing Nones for canonical form
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def with_dim_sharded(self, dim_idx: int, axes: Tuple[str, ...], degree: int
                         ) -> "ParallelTensorShape":
        dims = list(self.dims)
        d = dims[dim_idx]
        dims[dim_idx] = dataclasses.replace(d, degree=degree, mesh_axes=axes)
        return ParallelTensorShape(tuple(dims), self.dtype)

    def __str__(self) -> str:
        parts = []
        for d in self.dims:
            tag = "R" if d.is_replica_dim else ""
            parts.append(f"{d.size}{tag}/{d.degree}{list(d.mesh_axes)}")
        return f"PTS[{', '.join(parts)}:{self.dtype.name}]"
