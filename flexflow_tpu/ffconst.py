"""Framework-wide enums.

Mirrors the reference's enum vocabulary (include/flexflow/ffconst.h) so that user
code, frontends, and serialized strategies speak the same language, while the
values themselves are idiomatic Python enums.
"""
from __future__ import annotations

import enum


class ActiMode(enum.IntEnum):
    """Activation fused into an op (reference: ffconst.h:10-17)."""

    AC_MODE_NONE = 10
    AC_MODE_RELU = 11
    AC_MODE_SIGMOID = 12
    AC_MODE_TANH = 13
    AC_MODE_GELU = 14


class RegularizerMode(enum.IntEnum):
    """reference: flexflow/type.py RegularizerMode."""

    REG_MODE_NONE = 17
    REG_MODE_L1 = 18
    REG_MODE_L2 = 19


class AggrMode(enum.IntEnum):
    """Embedding aggregation (reference: ffconst.h:18-22)."""

    AGGR_MODE_NONE = 20
    AGGR_MODE_SUM = 21
    AGGR_MODE_AVG = 22


class PoolType(enum.IntEnum):
    """Pooling flavor (reference: ffconst.h:24-27)."""

    POOL_MAX = 30
    POOL_AVG = 31


class DataType(enum.IntEnum):
    """Tensor element types (reference: ffconst.h:29-37)."""

    DT_BOOLEAN = 40
    DT_INT32 = 41
    DT_INT64 = 42
    DT_HALF = 43
    DT_BFLOAT16 = 44
    DT_FLOAT = 45
    DT_DOUBLE = 46
    DT_NONE = 49


class LossType(enum.IntEnum):
    """Loss functions (reference: ffconst.h:39-45)."""

    LOSS_CATEGORICAL_CROSSENTROPY = 50
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 51
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 52
    LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 53
    LOSS_IDENTITY = 54


class CompMode(enum.IntEnum):
    """Training vs inference compilation (reference: ffconst.h:47-50)."""

    COMP_MODE_TRAINING = 55
    COMP_MODE_INFERENCE = 56


class ParameterSyncType(enum.IntEnum):
    """Gradient-sync backend of a weight (reference: config.h:56-59).

    On TPU both map to XLA collectives inserted by sharded autodiff; the enum is
    kept for API/strategy-file compatibility.
    """

    NONE = 60
    PS = 61
    NCCL = 62  # on TPU: psum over the mesh (kept for strategy-file parity)


class MetricsType(enum.IntEnum):
    """Metrics (reference: ffconst.h:58-65)."""

    METRICS_ACCURACY = 1001
    METRICS_CATEGORICAL_CROSSENTROPY = 1002
    METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 1004
    METRICS_MEAN_SQUARED_ERROR = 1008
    METRICS_ROOT_MEAN_SQUARED_ERROR = 1016
    METRICS_MEAN_ABSOLUTE_ERROR = 1032


class OperatorType(enum.IntEnum):
    """Operator vocabulary (reference: ffconst.h:69-160).

    Includes the parallel ops — they are first-class graph nodes exactly as in
    the reference PCG.
    """

    OP_NOOP = 1
    OP_INPUT = 2
    OP_WEIGHT = 3
    OP_CONV2D = 4
    OP_DROPOUT = 5
    OP_LINEAR = 6
    OP_BATCHMATMUL = 7
    OP_POOL2D = 8
    OP_SCALAR_MULTIPLY = 9
    OP_SCALAR_ADD = 10
    OP_SCALAR_SUB = 11
    OP_SCALAR_TRUE_DIV = 12
    OP_RELU = 13
    OP_IDENTITY = 14
    OP_SIGMOID = 15
    OP_TANH = 16
    OP_ELU = 17
    OP_GELU = 18
    OP_FLAT = 19
    OP_SOFTMAX = 20
    OP_BATCHNORM = 21
    OP_CONCAT = 22
    OP_SPLIT = 23
    OP_EMBEDDING = 24
    OP_GROUP_BY = 25
    OP_CACHE = 26
    OP_AGGREGATE = 27
    OP_AGG_SPEC = 28
    OP_RESHAPE = 29
    OP_REVERSE = 30
    OP_TRANSPOSE = 31
    OP_EW_ADD = 32
    OP_EW_MUL = 33
    OP_MATMUL = 34
    OP_MUL = 35
    OP_ENLARGE = 36
    OP_SQUEEZE = 37
    OP_UNSQUEEZE = 38
    OP_EW_SUB = 39
    OP_EW_DIV = 40
    OP_EW_EQUAL = 41
    OP_EW_GREATER = 42
    OP_EW_LESS = 43
    OP_EW_MAX = 44
    OP_EW_MIN = 45
    OP_REDUCE_ARGMAX = 46
    OP_REDUCE_ARGMIN = 47
    OP_REDUCE_MAX = 48
    OP_REDUCE_MEAN = 49
    OP_REDUCE_MIN = 50
    OP_REDUCE_PROD = 51
    OP_REDUCE_SUM = 52
    OP_PAD = 53
    OP_SHAPE = 54
    OP_SIZE = 55
    OP_TOPK = 56
    OP_WHERE = 57
    OP_CEIL = 58
    OP_CAST = 59
    OP_EXP = 60
    OP_ROUND = 61
    OP_LOG = 62
    OP_LOGICAL_NOT = 63
    OP_SQRT = 64
    OP_SIN = 65
    OP_COS = 66
    OP_LEAKYRELU = 67
    OP_SLICE = 68
    OP_RESIZE = 69
    OP_PRELU = 70
    OP_MULTIHEAD_ATTENTION = 71
    OP_FUSED = 72
    OP_RSQRT = 73
    OP_POW = 74
    OP_MEAN = 75
    OP_LAYERNORM = 76
    OP_GATHER = 77
    OP_BROADCAST = 78
    # Parallel ops (reference: ffconst.h:153-160)
    OP_REPARTITION = 90
    OP_COMBINE = 91
    OP_REPLICATE = 92
    OP_REDUCTION = 93
    OP_PIPELINE = 94
    OP_FUSED_PARALLEL = 95
    # TPU-native extensions (no reference analog)
    OP_RMSNORM = 110
    OP_RING_ATTENTION = 111
    OP_ALLTOALL = 112
    # recurrent family (reference: nmt/ hand-written lstm.cu predating the
    # FFModel op set; we promote it to a first-class op)
    OP_LSTM = 113
    # constant (frozen host tensor baked into the graph — needed by the
    # torch-fx frontend for traced buffers like position_ids)
    OP_CONSTANT = 114
    # attention core without projections (torch F.scaled_dot_product_attention;
    # reference analog: the cuDNN MHA core inside attention.cu)
    OP_SDPA = 115
    # batched expert FFN: all experts' weights stacked into one (n, d_in,
    # d_out) tensor driven by batched matmul — the TPU-native (GShard-style)
    # form of the reference's per-expert Linear nodes fed by group_by
    # (src/ops/group_by.cc), shardable over the expert dim for EP
    OP_EXPERTS = 116


# --- dtype helpers -------------------------------------------------------------

_DTYPE_TO_STR = {
    DataType.DT_BOOLEAN: "bool",
    DataType.DT_INT32: "int32",
    DataType.DT_INT64: "int64",
    DataType.DT_HALF: "float16",
    DataType.DT_BFLOAT16: "bfloat16",
    DataType.DT_FLOAT: "float32",
    DataType.DT_DOUBLE: "float64",
}

_STR_TO_DTYPE = {v: k for k, v in _DTYPE_TO_STR.items()}


def dtype_to_jnp(dt: "DataType"):
    """Map a DataType enum to the corresponding jnp dtype."""
    import jax.numpy as jnp

    return jnp.dtype(_DTYPE_TO_STR[dt])


def str_to_dtype(name: str) -> "DataType":
    """Parse a dtype name (CLI `--compute-dtype`); accepts common aliases."""
    name = name.lower()
    name = {"bf16": "bfloat16", "fp16": "float16", "half": "float16",
            "fp32": "float32", "float": "float32", "fp64": "float64",
            "double": "float64"}.get(name, name)
    if name not in _STR_TO_DTYPE:
        raise ValueError(f"unsupported dtype {name}")
    return _STR_TO_DTYPE[name]


def jnp_to_dtype(dt) -> "DataType":
    import numpy as np

    name = np.dtype(dt).name
    if name not in _STR_TO_DTYPE:
        raise ValueError(f"unsupported dtype {name}")
    return _STR_TO_DTYPE[name]


def size_of_datatype(dt: "DataType") -> int:
    import numpy as np

    return np.dtype(_DTYPE_TO_STR[dt]).itemsize
