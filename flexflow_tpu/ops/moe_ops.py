"""Mixture-of-Experts building blocks: GroupBy, Aggregate, AggregateSpec,
Experts, Cache.

Reference: src/ops/group_by.cc (534 LoC, ragged scatter with capacity factor
``alpha``), aggregate.cc (569, gate-weighted gather + load-balance loss term
``lambda_bal``), aggregate_spec.cc (519, speculative variant), cache.cc (291).

TPU-native design (SURVEY §7 hard-part 4): the reference's dynamic ragged
routing becomes **fixed-capacity scatter/gather dispatch** — per-token
destination slots computed from a cumulative count (O(tokens·experts) int32,
no (tokens, experts, capacity) one-hot blow-up), scattered with
``.at[].add`` and gathered back by slot index; both directions differentiate
through XLA. Capacity = ceil(k * batch * alpha / n), matching the
reference's per-expert buffer; overflowing tokens are dropped exactly as the
reference drops them when the buffer fills (priority = scan order,
group_by.cu). GroupBy and Aggregate recompute the same deterministic
dispatch from ``assign`` so they stay consistent without ragged state.

``Experts`` (OP_EXPERTS) is the TPU-native batched form of the reference's
per-expert Linear nodes: all experts' FFN weights stacked into one
(n, d_in, d_out) tensor driven by a batched matmul on the MXU, shardable
over the expert dim — the expert-parallel strategy the reference expresses
with per-expert MachineViews becomes one NamedSharding axis, and the
token all-to-all is emitted by XLA at the sharding boundary.
"""
from __future__ import annotations


import numpy as np

from ..ffconst import OperatorType
from .base import Op, OpContext, register_op


def moe_capacity(k: int, batch: int, alpha: float, n: int) -> int:
    return int(np.ceil(k * batch * alpha / n))


def dispatch_indices(assign_flat, n: int, capacity: int):
    """assign_flat: (t,) int in [0, n) -> (dest (t,), keep (t,)).

    ``dest`` is the flat slot ``expert * capacity + position`` where each
    token lands; ``keep`` is False for tokens past their expert's capacity
    (dropped, like the reference when the buffer fills). Position is the
    token's rank among same-expert tokens in scan order (group_by.cu packs
    in this order). O(t·n) int32 intermediate — the (t, n, cap) one-hot of
    the dense-dispatch formulation never materializes."""
    import jax.nn as jnn
    import jax.numpy as jnp

    onehot = jnn.one_hot(assign_flat, n, dtype=jnp.int32)  # (t, n)
    pos_all = jnp.cumsum(onehot, axis=0) - 1  # (t, n)
    pos = jnp.take_along_axis(pos_all, assign_flat[:, None], axis=1)[:, 0]
    keep = pos < capacity
    dest = assign_flat * capacity + jnp.clip(pos, 0, capacity - 1)
    return dest, keep


def dispatch_mask(assign, n: int, capacity: int):
    """assign: (tokens,) -> (tokens, n, capacity) one-hot dispatch tensor.

    Kept as the reference implementation for the alignment tests (grads of
    the scatter path are verified against it); production ops use
    ``dispatch_indices``."""
    import jax.nn as jnn
    import jax.numpy as jnp

    expert_onehot = jnn.one_hot(assign, n, dtype=jnp.int32)  # (t, n)
    pos = jnp.cumsum(expert_onehot, axis=0) * expert_onehot - 1  # (t, n)
    pos_clipped = jnp.clip(pos, 0, capacity - 1)
    keep = (pos >= 0) & (pos < capacity)
    slot = jnn.one_hot(pos_clipped, capacity, dtype=jnp.int32)  # (t, n, cap)
    return slot * keep[..., None]  # (t, n, cap) in {0,1}


def _scatter_group(x_flat, assign_flat, n: int, cap: int):
    """(t, d) tokens -> (n, cap, d) expert buffers via scatter-add."""
    import jax.numpy as jnp

    d = x_flat.shape[-1]
    dest, keep = dispatch_indices(assign_flat, n, cap)
    contrib = x_flat * keep[:, None].astype(x_flat.dtype)
    grouped = jnp.zeros((n * cap, d), x_flat.dtype).at[dest].add(contrib)
    return grouped.reshape(n, cap, d)


@register_op(OperatorType.OP_GROUP_BY)
class GroupByOp(Op):
    """attrs: n (num experts), alpha (capacity factor), stacked (bool —
    TPU-native: emit one (n, cap, d) tensor instead of n (cap, d) tensors,
    feeding the batched Experts op).

    inputs: (input (batch, d), assign (batch, k) int)
    outputs: n tensors of (capacity, d) — reference: FFModel::group_by,
    src/ops/group_by.cc — or [(n, capacity, d)] when stacked.
    """

    def _cap(self, input_shapes):
        (batch, _d), (_, k) = input_shapes
        n = self.attrs["n"]
        return moe_capacity(k, batch, self.attrs.get("alpha", 1.0), n)

    def infer_output_shapes(self, input_shapes):
        (_batch, d) = input_shapes[0]
        n = self.attrs["n"]
        cap = self._cap(input_shapes)
        if self.attrs.get("stacked"):
            return [(n, cap, d)]
        return [(cap, d)] * n

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        x, assign = inputs
        batch, d = x.shape
        k = assign.shape[1]
        n = self.attrs["n"]
        cap = moe_capacity(k, batch, self.attrs.get("alpha", 1.0), n)
        assign_flat = assign.reshape(-1).astype(jnp.int32)  # (batch*k,)
        x_flat = jnp.repeat(x, k, axis=0)  # token order matches assign_flat
        grouped = _scatter_group(x_flat, assign_flat, n, cap)
        if self.attrs.get("stacked"):
            return [grouped]
        return [grouped[e] for e in range(n)]

    def parallelizable_dims(self, input_shapes):
        # expert parallelism: the expert dim shards over the model axis
        # (reference: per-expert MachineViews)
        return {"batch": False, "expert": True}


@register_op(OperatorType.OP_EXPERTS)
class ExpertsOp(Op):
    """Batched expert FFN (TPU-native; replaces the reference's n separate
    Linear ops consuming group_by outputs — src/ops/moe.cc:20-45 builds
    those): one (n, d_in, out_dim) weight, one batched matmul.

    attrs: n, out_dim, activation, use_bias.
    inputs: (dispatched (n, cap, d),)
    output: (n, cap, out_dim).
    Expert-parallel: shard dim 0 of weights/activations over the model axis.
    """

    def infer_output_shapes(self, input_shapes):
        n, cap, _d = input_shapes[0]
        return [(n, cap, self.attrs["out_dim"])]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import (DefaultBiasInitializer,
                                              DefaultWeightInitializer)

        n, _cap, d = input_shapes[0]
        out = self.attrs["out_dim"]
        specs = {"kernel": ((n, d, out), self.data_type,
                            self.attrs.get("kernel_initializer")
                            or DefaultWeightInitializer())}
        if self.attrs.get("use_bias", True):
            specs["bias"] = ((n, out), self.data_type,
                             DefaultBiasInitializer())
        return specs

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs  # (n, cap, d)
        y = jnp.einsum("ncd,ndo->nco", x, params["kernel"],
                       preferred_element_type=jnp.float32).astype(x.dtype)
        if "bias" in params:
            y = y + params["bias"][:, None, :].astype(y.dtype)
        from ..ffconst import ActiMode
        from .linear import apply_activation

        return [apply_activation(y, self.attrs.get(
            "activation", ActiMode.AC_MODE_NONE) or ActiMode.AC_MODE_NONE)]

    def flops(self, input_shapes, output_shapes):
        n, cap, d = input_shapes[0]
        return 2 * n * cap * d * self.attrs["out_dim"]

    def parallelizable_dims(self, input_shapes):
        return {"batch": False, "expert": True}


def _combine_tokens(exp_preds, gate_preds, gate_assign, n: int,
                    weighted: bool = True):
    """(n, cap, d) expert outputs -> (batch, k, d) per-assignment rows."""
    import jax.numpy as jnp

    batch, k = gate_assign.shape
    cap = exp_preds.shape[1]
    d = exp_preds.shape[2]
    assign_flat = gate_assign.reshape(-1).astype(jnp.int32)
    dest, keep = dispatch_indices(assign_flat, n, cap)
    gathered = exp_preds.reshape(n * cap, d)[dest]  # (t, d)
    gathered = gathered * keep[:, None].astype(gathered.dtype)
    if weighted:
        gathered = gathered * gate_preds.reshape(-1)[:, None].astype(
            gathered.dtype)
    return gathered.reshape(batch, k, d)


def _load_balance_aux(gate_assign, full_gate, n: int, lambda_bal: float,
                      ctx: OpContext):
    """The lambda_bal surrogate (reference: aggregate.cu backward): load_e =
    fraction of routed (token, k) assignments to expert e — ALL k slots, not
    just top-1 — times mean gate probability, summed over experts."""
    import jax.nn as jnn
    import jax.numpy as jnp

    if not lambda_bal or not ctx.training or ctx.aux_losses is None:
        return
    assign_all = gate_assign.reshape(-1).astype(jnp.int32)  # (batch*k,)
    load = jnp.mean(jnn.one_hot(assign_all, n, dtype=jnp.float32), axis=0)
    importance = jnp.mean(full_gate.astype(jnp.float32), axis=0)
    ctx.aux_losses.append(lambda_bal * n * jnp.sum(load * importance))


@register_op(OperatorType.OP_AGGREGATE)
class AggregateOp(Op):
    """attrs: n, lambda_bal.

    inputs: (gate_preds (batch, k), gate_assign (batch, k),
             true_gate_assign (batch, k), full_gate_grads (batch, n),
             exp_pred_0..exp_pred_{n-1} each (capacity, d) — or one stacked
             (n, capacity, d) tensor)
    output: (batch, d) — reference: src/ops/aggregate.cc. The load-balance
    term flows through autodiff via the aux-loss hook (the reference
    hand-codes it in aggregate.cu's backward).
    """

    def infer_output_shapes(self, input_shapes):
        batch = input_shapes[0][0]
        d = input_shapes[4][-1]
        return [(batch, d)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        gate_preds, gate_assign = inputs[0], inputs[1]
        if len(inputs) == 5 and inputs[4].ndim == 3:
            exp_preds = inputs[4]  # stacked (n, cap, d)
        else:
            exp_preds = jnp.stack(inputs[4:], axis=0)
        n = self.attrs["n"]
        rows = _combine_tokens(exp_preds, gate_preds, gate_assign, n)
        out = rows.sum(axis=1)  # (batch, d)
        _load_balance_aux(gate_assign, inputs[3], n,
                          self.attrs.get("lambda_bal", 0.0), ctx)
        return [out.astype(exp_preds.dtype)]


@register_op(OperatorType.OP_AGG_SPEC)
class AggregateSpecOp(Op):
    """Speculative aggregation: one output row per (token, assignment) so the
    loss supervises every expert's prediction; labels are replicated k times
    by compile (reference: aggregate_spec.cc; model.cc:2875-2877).
    """

    def infer_output_shapes(self, input_shapes):
        batch, k = input_shapes[1]
        d = input_shapes[4][-1]
        return [(batch * k, d)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        gate_assign = inputs[1]
        if len(inputs) == 5 and inputs[4].ndim == 3:
            exp_preds = inputs[4]
        else:
            exp_preds = jnp.stack(inputs[4:], axis=0)
        n = self.attrs["n"]
        batch, k = gate_assign.shape
        rows = _combine_tokens(exp_preds, None, gate_assign, n,
                               weighted=False)
        _load_balance_aux(gate_assign, inputs[3], n,
                          self.attrs.get("lambda_bal", 0.0), ctx)
        return [rows.reshape(batch * k, -1).astype(exp_preds.dtype)]


@register_op(OperatorType.OP_CACHE)
class CacheOp(Op):
    """Caches an intermediate tensor across iterations, re-using it while a
    user score function deems it fresh (reference: src/ops/cache.cc:291; pairs
    with dynamic recompile, recompile.h). The executor threads a cache-state
    pytree: forward blends the cached value in via ``ctx.cache_in`` and
    publishes the fresh value through ``ctx.cache_out`` (the executor's
    train/eval step returns it; FFModel.fit scores it host-side with
    ``score_fn`` and feeds the recompile trigger).

    attrs: num_batches, score_fn (callable(cached, fresh) -> float).
    """

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        fresh = inputs[0]
        if ctx.cache_out is not None:
            ctx.cache_out[self.name] = fresh
        if ctx.cache_in is not None and self.name in ctx.cache_in:
            use_cache = ctx.cache_in.get("__use_cache__")
            if use_cache is not None:
                import jax.numpy as jnp

                cached = ctx.cache_in[self.name]
                return [jnp.where(use_cache, cached.astype(fresh.dtype),
                                  fresh)]
        return [fresh]
