"""Mixture-of-Experts building blocks: GroupBy, Aggregate, AggregateSpec, Cache.

Reference: src/ops/group_by.cc (534 LoC, ragged scatter with capacity factor
``alpha``), aggregate.cc (569, gate-weighted gather + load-balance loss term
``lambda_bal``), aggregate_spec.cc (519, speculative variant), cache.cc (291).

TPU-native design (SURVEY §7 hard-part 4): the reference's dynamic ragged
routing becomes **fixed-capacity dense dispatch** — a one-hot dispatch tensor
computed from the assignments, contracted on the MXU (the Switch/GShard
recipe). Capacity = ceil(k * batch * alpha / n), matching the reference's
definition of its per-expert buffer. Overflowing tokens are dropped exactly as
the reference drops them when the buffer fills. Both GroupBy and Aggregate
recompute the same deterministic dispatch from ``assign`` so they stay
consistent without carrying ragged state.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..ffconst import DataType, OperatorType
from .base import Op, OpContext, register_op


def moe_capacity(k: int, batch: int, alpha: float, n: int) -> int:
    return int(np.ceil(k * batch * alpha / n))


def dispatch_mask(assign, n: int, capacity: int):
    """assign: (tokens,) int in [0, n) -> (tokens, n, capacity) one-hot dispatch.

    Token priority is index order (the reference packs in scan order,
    group_by.cu). Tokens past an expert's capacity get an all-zero row (drop).
    """
    import jax.numpy as jnp
    import jax.nn as jnn

    expert_onehot = jnn.one_hot(assign, n, dtype=jnp.int32)  # (t, n)
    pos = jnp.cumsum(expert_onehot, axis=0) * expert_onehot - 1  # (t, n)
    pos_clipped = jnp.clip(pos, 0, capacity - 1)
    keep = (pos >= 0) & (pos < capacity)
    slot = jnn.one_hot(pos_clipped, capacity, dtype=jnp.int32)  # (t, n, cap)
    return slot * keep[..., None]  # (t, n, cap) in {0,1}


@register_op(OperatorType.OP_GROUP_BY)
class GroupByOp(Op):
    """attrs: n (num experts), alpha (capacity factor).

    inputs: (input (batch, d), assign (batch, k) int)
    outputs: n tensors of (capacity, d) — reference: FFModel::group_by,
    src/ops/group_by.cc.
    """

    def infer_output_shapes(self, input_shapes):
        (batch, d), (_, k) = input_shapes
        n = self.attrs["n"]
        cap = moe_capacity(k, batch, self.attrs.get("alpha", 1.0), n)
        return [(cap, d)] * n

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        x, assign = inputs
        batch, d = x.shape
        k = assign.shape[1]
        n = self.attrs["n"]
        cap = moe_capacity(k, batch, self.attrs.get("alpha", 1.0), n)
        assign_flat = assign.reshape(-1).astype(jnp.int32)  # (batch*k,)
        x_flat = jnp.repeat(x, k, axis=0)  # token order matches assign_flat
        disp = dispatch_mask(assign_flat, n, cap).astype(x.dtype)  # (t, n, c)
        grouped = jnp.einsum("td,tnc->ncd", x_flat, disp,
                             preferred_element_type=jnp.float32).astype(x.dtype)
        return [grouped[e] for e in range(n)]

    def parallelizable_dims(self, input_shapes):
        # expert parallelism: each output (expert buffer) placeable on its own
        # submesh (reference: per-expert MachineViews) -> shard the expert dim
        return {"batch": False, "expert": True}


@register_op(OperatorType.OP_AGGREGATE)
class AggregateOp(Op):
    """attrs: n, lambda_bal.

    inputs: (gate_preds (batch, k), gate_assign (batch, k),
             true_gate_assign (batch, k), full_gate_grads (batch, n),
             exp_pred_0..exp_pred_{n-1} each (capacity, d))
    output: (batch, d) — reference: src/ops/aggregate.cc. The load-balance
    term flows through autodiff via the gate contraction (the reference
    hand-codes it in aggregate.cu's backward).
    """

    def infer_output_shapes(self, input_shapes):
        batch = input_shapes[0][0]
        d = input_shapes[4][1]
        return [(batch, d)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp
        import jax.nn as jnn

        gate_preds, gate_assign = inputs[0], inputs[1]
        exp_preds = jnp.stack(inputs[4:], axis=0)  # (n, cap, d)
        batch, k = gate_assign.shape
        n = self.attrs["n"]
        cap = exp_preds.shape[1]
        assign_flat = gate_assign.reshape(-1).astype(jnp.int32)
        disp = dispatch_mask(assign_flat, n, cap)  # (t, n, c)
        combine = disp.astype(gate_preds.dtype) * gate_preds.reshape(-1)[:, None, None]
        out_flat = jnp.einsum("tnc,ncd->td", combine, exp_preds,
                              preferred_element_type=jnp.float32)
        out = out_flat.reshape(batch, k, -1).sum(axis=1)
        # load-balance auxiliary loss (reference: lambda_bal term applied in
        # aggregate.cu's backward): n * sum_e(load_e * importance_e), the
        # Switch/GShard differentiable surrogate. full_gate_grads = gate
        # probabilities over all n experts (batch, n).
        lambda_bal = self.attrs.get("lambda_bal", 0.0)
        if lambda_bal and ctx.training and ctx.aux_losses is not None:
            full_gate = inputs[3].astype(jnp.float32)  # (batch, n)
            load = jnp.mean(
                jnn.one_hot(gate_assign[:, 0].astype(jnp.int32), n,
                            dtype=jnp.float32), axis=0)  # top-1 token fraction
            importance = jnp.mean(full_gate, axis=0)
            ctx.aux_losses.append(lambda_bal * n * jnp.sum(load * importance))
        return [out.astype(exp_preds.dtype)]


@register_op(OperatorType.OP_AGG_SPEC)
class AggregateSpecOp(Op):
    """Speculative aggregation: one output row per (token, assignment) so the
    loss supervises every expert's prediction; labels are replicated k times by
    compile (reference: aggregate_spec.cc; model.cc:2875-2877).
    """

    def infer_output_shapes(self, input_shapes):
        batch, k = input_shapes[1]
        d = input_shapes[4][1]
        return [(batch * k, d)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        gate_assign = inputs[1]
        exp_preds = jnp.stack(inputs[4:], axis=0)
        batch, k = gate_assign.shape
        n = self.attrs["n"]
        cap = exp_preds.shape[1]
        assign_flat = gate_assign.reshape(-1).astype(jnp.int32)
        disp = dispatch_mask(assign_flat, n, cap).astype(exp_preds.dtype)
        out = jnp.einsum("tnc,ncd->td", disp, exp_preds,
                         preferred_element_type=jnp.float32)
        return [out.astype(exp_preds.dtype)]


@register_op(OperatorType.OP_CACHE)
class CacheOp(Op):
    """Caches an intermediate tensor across iterations, re-using it while a
    user score function deems it fresh (reference: src/ops/cache.cc:291; pairs
    with dynamic recompile, recompile.h). Functionally: the executor threads a
    ``cache_state`` aux pytree; forward selects cached vs fresh value.

    attrs: num_batches, score_fn (callable(cached, fresh) -> float, host-side).
    """

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        # Cache state handling lives in the executor (aux-state pytree); inside
        # the pure graph the op is identity on its input.
        return [inputs[0]]
