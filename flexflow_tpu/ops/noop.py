"""NoOp / Input / Weight placeholder nodes of the PCG.

Reference: src/ops/noop.cc:255 — OP_INPUT/OP_WEIGHT/OP_NOOP nodes anchor graph
sources so the search can treat inputs/weights uniformly.
"""
from __future__ import annotations

from ..ffconst import OperatorType
from .base import Op, OpContext, register_op


@register_op(OperatorType.OP_NOOP)
class NoOp(Op):
    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0]]


@register_op(OperatorType.OP_INPUT)
class InputOp(Op):
    """Graph source; attrs: shape, dtype."""

    def infer_output_shapes(self, input_shapes):
        return [tuple(self.attrs["shape"])]

    def forward(self, params, inputs, ctx: OpContext):
        raise RuntimeError("InputOp is bound by the executor, never executed")


@register_op(OperatorType.OP_WEIGHT)
class WeightOp(Op):
    """Weight source node; attrs: shape, dtype."""

    def infer_output_shapes(self, input_shapes):
        return [tuple(self.attrs["shape"])]

    def forward(self, params, inputs, ctx: OpContext):
        raise RuntimeError("WeightOp is bound by the executor, never executed")


@register_op(OperatorType.OP_CONSTANT)
class ConstantOp(Op):
    """Frozen host tensor baked into the graph (attrs: value — np.ndarray).
    Needed by the torch-fx frontend for traced module buffers (position_ids,
    token_type_ids, attention masks); the reference keeps such buffers as
    non-trainable weight tensors."""

    def infer_output_shapes(self, input_shapes):
        import numpy as np

        return [tuple(np.asarray(self.attrs["value"]).shape)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        from ..ffconst import dtype_to_jnp

        return [jnp.asarray(self.attrs["value"],
                            dtype=dtype_to_jnp(self.data_type))]
