"""Operator library: every compute op of the reference's src/ops/ inventory
(SURVEY §2.2) as a jax-traceable Op subclass, registered by OperatorType."""
from .base import Op, OpContext, op_class_for, register_op  # noqa: F401
from . import linear  # noqa: F401
from . import conv  # noqa: F401
from . import elementwise  # noqa: F401
from . import normalization  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import attention  # noqa: F401
from . import embedding  # noqa: F401
from . import moe_ops  # noqa: F401
from . import noop  # noqa: F401
from . import recurrent  # noqa: F401
from . import fused  # noqa: F401
