"""Shape/data-movement ops: reshape, transpose, reverse, concat, split, gather,
reduce, mean, topk, batch_matmul.

Reference: src/ops/{reshape,transpose,reverse,concat,split,gather,reduce,mean,
topk,batch_matmul}.cc. All are single XLA HLO ops here — including top-k
(``jax.lax.top_k``), where the reference needs a hand-written GPU kernel
(topk.cu:514) but XLA's TPU sort is already tuned for the MoE routing shapes.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from ..ffconst import DataType, OperatorType
from .base import Op, OpContext, register_op


@register_op(OperatorType.OP_RESHAPE)
class ReshapeOp(Op):
    """attrs: shape (new shape, batch included; -1 allowed once)."""

    def infer_output_shapes(self, input_shapes):
        target = list(self.attrs["shape"])
        vol = int(np.prod(input_shapes[0]))
        if -1 in target:
            i = target.index(-1)
            rest = int(np.prod([t for t in target if t != -1]))
            target[i] = vol // rest
        assert int(np.prod(target)) == vol, (input_shapes, target)
        return [tuple(target)]

    def forward(self, params, inputs, ctx: OpContext):
        out_shape = self.infer_output_shapes([inputs[0].shape])[0]
        return [inputs[0].reshape(out_shape)]

    def can_inplace_output(self):
        return True


@register_op(OperatorType.OP_TRANSPOSE)
class TransposeOp(Op):
    """attrs: perm (full permutation, reference: src/ops/transpose.cc)."""

    def infer_output_shapes(self, input_shapes):
        s = input_shapes[0]
        return [tuple(s[p] for p in self.attrs["perm"])]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        return [jnp.transpose(inputs[0], self.attrs["perm"])]


@register_op(OperatorType.OP_REVERSE)
class ReverseOp(Op):
    """attrs: axis."""

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        return [jnp.flip(inputs[0], axis=self.attrs["axis"])]


@register_op(OperatorType.OP_CONCAT)
class ConcatOp(Op):
    """attrs: axis; variadic inputs (reference: src/ops/concat.cc)."""

    def infer_output_shapes(self, input_shapes):
        axis = self.attrs["axis"] % len(input_shapes[0])
        out = list(input_shapes[0])
        out[axis] = sum(s[axis] for s in input_shapes)
        return [tuple(out)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        return [jnp.concatenate(inputs, axis=self.attrs["axis"])]


@register_op(OperatorType.OP_SPLIT)
class SplitOp(Op):
    """attrs: sizes (list), axis (reference: src/ops/split.cc)."""

    def infer_output_shapes(self, input_shapes):
        s = input_shapes[0]
        axis = self.attrs["axis"] % len(s)
        outs = []
        for sz in self.attrs["sizes"]:
            o = list(s)
            o[axis] = sz
            outs.append(tuple(o))
        return outs

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        axis = self.attrs["axis"] % x.ndim
        offsets = np.cumsum(self.attrs["sizes"])[:-1].tolist()
        return list(jnp.split(x, offsets, axis=axis))


@register_op(OperatorType.OP_GATHER)
class GatherOp(Op):
    """torch.gather semantics (reference: src/ops/gather.cc:440).

    inputs: (input, index); attrs: dim. output shape == index shape.
    """

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[1]]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        x, idx = inputs
        dim = self.attrs["dim"] % x.ndim
        return [jnp.take_along_axis(x, idx.astype(jnp.int32), axis=dim)]


@register_op(OperatorType.OP_REDUCE_SUM)
class ReduceSumOp(Op):
    """attrs: axes, keepdims (reference: src/ops/reduce.cc)."""

    def _axes(self, ndim):
        return tuple(sorted(a % ndim for a in self.attrs["axes"]))

    def infer_output_shapes(self, input_shapes):
        s = input_shapes[0]
        axes = self._axes(len(s))
        keep = self.attrs.get("keepdims", False)
        out = [(1 if keep else None) if i in axes else d for i, d in enumerate(s)]
        return [tuple(d for d in out if d is not None)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        return [jnp.sum(x, axis=self._axes(x.ndim),
                        keepdims=self.attrs.get("keepdims", False))]


@register_op(OperatorType.OP_REDUCE_MEAN)
class ReduceMeanOp(ReduceSumOp):
    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        return [jnp.mean(x, axis=self._axes(x.ndim),
                         keepdims=self.attrs.get("keepdims", False))]


@register_op(OperatorType.OP_MEAN)
class MeanOp(ReduceMeanOp):
    """reference: src/ops/mean.cc."""


@register_op(OperatorType.OP_TOPK)
class TopKOp(Op):
    """attrs: k, sorted, use_pallas. outputs: (values, indices) over last dim
    (reference: src/ops/topk.cc:437, custom GPU kernel — here lax.top_k by
    default; XLA's TPU sort covers the MoE routing shapes. The dedicated
    Pallas sweep kernel, kernels/topk.py, routes on explicit opt-in like the
    softmax kernel)."""

    def infer_output_shapes(self, input_shapes):
        s = input_shapes[0]
        out = tuple(s[:-1]) + (self.attrs["k"],)
        return [out, out]

    def output_dtypes(self, input_dtypes, num_outputs):
        return [input_dtypes[0], DataType.DT_INT32]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.lax as lax

        (x,) = inputs
        k = self.attrs["k"]
        from ..kernels.topk import pallas_topk, should_use_pallas_topk

        if should_use_pallas_topk(x, k,
                                  opt_in=self.attrs.get("use_pallas", False)):
            values, indices = pallas_topk(x, k)
        else:
            values, indices = lax.top_k(x, k)
        return [values, indices]


@register_op(OperatorType.OP_BATCHMATMUL)
class BatchMatmulOp(Op):
    """(b, m, k) x (b, k, n) -> (b, m, n)
    (reference: src/ops/batch_matmul.cc, cuBLAS strided-batched)."""

    def infer_output_shapes(self, input_shapes):
        a, b = input_shapes
        assert a[-1] == b[-2], (a, b)
        return [tuple(a[:-1]) + (b[-1],)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        a, b = inputs
        y = jnp.matmul(a, b, preferred_element_type=jnp.float32)
        return [y.astype(a.dtype)]

    def flops(self, input_shapes, output_shapes):
        a = input_shapes[0]
        n = output_shapes[0][-1]
        return 2 * int(np.prod(a)) * n


@register_op(OperatorType.OP_SLICE)
class SliceOp(Op):
    """Static tensor slicing / indexing (reference: OP_SLICE, ffconst.h; the
    torch frontend's getitem). attrs: items — a tuple where each element is
    ("slice", start, stop, step) with None encoded as "none", ("index", i),
    or ("newaxis",)."""

    def _indexer(self):
        def dec(v):
            return None if v == "none" else v

        idx = []
        for it in self.attrs["items"]:
            if it[0] == "slice":
                idx.append(slice(dec(it[1]), dec(it[2]), dec(it[3])))
            elif it[0] == "index":
                idx.append(int(it[1]))
            elif it[0] == "newaxis":
                idx.append(None)
            else:
                raise ValueError(f"bad slice item {it}")
        return tuple(idx)

    def infer_output_shapes(self, input_shapes):
        # zero-stride view: shape inference without allocating the input
        ref = np.broadcast_to(np.int8(0), input_shapes[0])
        return [tuple(ref[self._indexer()].shape)]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0][self._indexer()]]

    def can_inplace_output(self):
        return False


def encode_slice_items(items) -> Tuple:
    """Python (slice | int | None) tuple -> hashable SliceOp attrs encoding."""
    enc = []
    for it in items:
        if isinstance(it, slice):
            n = "none"
            enc.append(("slice",
                        n if it.start is None else int(it.start),
                        n if it.stop is None else int(it.stop),
                        n if it.step is None else int(it.step)))
        elif it is None:
            enc.append(("newaxis",))
        elif isinstance(it, (int, np.integer)):
            enc.append(("index", int(it)))
        else:
            raise NotImplementedError(f"slice item {it!r}")
    return tuple(enc)
