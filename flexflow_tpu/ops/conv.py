"""Conv2D, Pool2D, Flat, BatchNorm.

Reference: src/ops/conv_2d.cc (1198 LoC, cuDNN), pool_2d.cc, flat.cc,
batch_norm.cc. User-visible layout is NCHW to match the reference API
(FFModel::conv2d, model.h); internally XLA picks the TPU-friendly layout, and
kernels are stored HWIO which is what lax.conv_general_dilated wants.
"""
from __future__ import annotations


import numpy as np

from ..ffconst import ActiMode, OperatorType, PoolType
from .base import Op, OpContext, register_op
from .linear import apply_activation


def _conv_out(size: int, kernel: int, stride: int, pad: int) -> int:
    return (size + 2 * pad - kernel) // stride + 1


@register_op(OperatorType.OP_CONV2D)
class Conv2DOp(Op):
    """attrs: out_channels, kernel_h/w, stride_h/w, padding_h/w, activation,
    groups, use_bias (reference builder: FFModel::conv2d, src/ops/conv_2d.cc)."""

    def infer_output_shapes(self, input_shapes):
        n, c, h, w = input_shapes[0]
        a = self.attrs
        oh = _conv_out(h, a["kernel_h"], a["stride_h"], a["padding_h"])
        ow = _conv_out(w, a["kernel_w"], a["stride_w"], a["padding_w"])
        return [(n, a["out_channels"], oh, ow)]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import (DefaultBiasInitializer,
                                              DefaultWeightInitializer)

        a = self.attrs
        in_c = input_shapes[0][1] // a.get("groups", 1)
        specs = {
            "kernel": ((a["kernel_h"], a["kernel_w"], in_c, a["out_channels"]),
                       self.data_type,
                       a.get("kernel_initializer") or DefaultWeightInitializer()),
        }
        if a.get("use_bias", True):
            specs["bias"] = ((a["out_channels"],), self.data_type,
                             a.get("bias_initializer") or DefaultBiasInitializer())
        return specs

    def forward(self, params, inputs, ctx: OpContext):
        import jax.lax as lax

        (x,) = inputs
        a = self.attrs
        y = lax.conv_general_dilated(
            x, params["kernel"],
            window_strides=(a["stride_h"], a["stride_w"]),
            padding=((a["padding_h"], a["padding_h"]),
                     (a["padding_w"], a["padding_w"])),
            dimension_numbers=("NCHW", "HWIO", "NCHW"),
            feature_group_count=a.get("groups", 1),
            preferred_element_type=np.float32,
        ).astype(x.dtype)
        if "bias" in params:
            y = y + params["bias"][None, :, None, None]
        return [apply_activation(y, a.get("activation", ActiMode.AC_MODE_NONE))]

    def flops(self, input_shapes, output_shapes):
        a = self.attrs
        n, co, oh, ow = output_shapes[0]
        ci = input_shapes[0][1] // a.get("groups", 1)
        return 2 * n * co * oh * ow * ci * a["kernel_h"] * a["kernel_w"]

    def parallelizable_dims(self, input_shapes):
        return {
            "batch": True,
            "channel_out": {"output_dim": 1, "weights": {"kernel": 3, "bias": 0}},
            # attribute (spatial) parallelism of the reference's
            # create_mapping_xfers<Conv2D> (substitution.cc:1797) maps to
            # sharding H: only valid for 1x1-pad-free convs; search checks.
        }


@register_op(OperatorType.OP_POOL2D)
class Pool2DOp(Op):
    """attrs: kernel_h/w, stride_h/w, padding_h/w, pool_type, activation
    (reference: src/ops/pool_2d.cc)."""

    def infer_output_shapes(self, input_shapes):
        n, c, h, w = input_shapes[0]
        a = self.attrs
        oh = _conv_out(h, a["kernel_h"], a["stride_h"], a["padding_h"])
        ow = _conv_out(w, a["kernel_w"], a["stride_w"], a["padding_w"])
        return [(n, c, oh, ow)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.lax as lax
        import jax.numpy as jnp

        (x,) = inputs
        a = self.attrs
        window = (1, 1, a["kernel_h"], a["kernel_w"])
        strides = (1, 1, a["stride_h"], a["stride_w"])
        pads = ((0, 0), (0, 0), (a["padding_h"], a["padding_h"]),
                (a["padding_w"], a["padding_w"]))
        if a.get("pool_type", PoolType.POOL_MAX) == PoolType.POOL_MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        else:
            ones = jnp.ones_like(x)
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            y = s / cnt
        return [apply_activation(y, a.get("activation", ActiMode.AC_MODE_NONE))]


@register_op(OperatorType.OP_FLAT)
class FlatOp(Op):
    """Flatten all non-batch dims (reference: src/ops/flat.cc)."""

    def infer_output_shapes(self, input_shapes):
        s = input_shapes[0]
        return [(s[0], int(np.prod(s[1:])))]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        return [x.reshape(x.shape[0], -1)]

    def can_inplace_output(self):
        return True


@register_op(OperatorType.OP_BATCHNORM)
class BatchNormOp(Op):
    """attrs: relu, momentum, eps (reference: src/ops/batch_norm.cc, cuDNN).

    Running statistics are non-trainable params updated functionally: forward
    returns the output; the executor threads running stats as mutable state.
    For parity with the reference (which only tracks stats for inference) the
    training path uses batch statistics.
    """

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import ConstantInitializer, ZeroInitializer

        c = input_shapes[0][1]
        return {
            "scale": ((c,), self.data_type, ConstantInitializer(1.0)),
            "bias": ((c,), self.data_type, ZeroInitializer()),
        }

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        eps = self.attrs.get("eps", 1e-5)
        axes = (0, 2, 3) if x.ndim == 4 else (0,)
        xf = x.astype(jnp.float32)  # batch statistics in f32 under bf16 compute
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        scale = params["scale"].reshape((1, -1) + (1,) * (x.ndim - 2))
        bias = params["bias"].reshape((1, -1) + (1,) * (x.ndim - 2))
        y = ((xf - mean) * scale / jnp.sqrt(var + eps) + bias).astype(x.dtype)
        if self.attrs.get("relu", True):
            import jax.nn as jnn

            y = jnn.relu(y)
        return [y]
