"""Linear (dense) operator — the canonical op (reference: src/ops/linear.cc:1184,
kernels src/ops/kernels/linear_kernels.cu).

TPU-native: a single jnp.dot that XLA tiles onto the MXU, with the activation
fused by XLA (the reference fuses via cuBLAS epilogue / cuDNN activation).
Weight layout is (in_dim, out_dim) so row/column tensor-parallelism is a
sharding of one weight dim:

* column-parallel = shard ``out_dim`` (reference: replicate-linear-combine xfer,
  substitution.cc:3226) — output is sharded, no collective.
* row-parallel = shard ``in_dim`` (reference: partition-linear-combine,
  substitution.cc:3041) — output needs a psum, inserted by XLA when the
  contraction dim is sharded.
"""
from __future__ import annotations


import jax
import numpy as np

from ..ffconst import ActiMode, OperatorType
from .base import Op, OpContext, register_op


@jax.custom_vjp
def bias_add(y, b):
    """Broadcast bias add with a layout-friendly gradient.

    The naive ``y + b`` backward asks XLA to reduce dy over EVERY leading
    axis at once; at bf16 that lowers to the multi-axis convert+reduce
    fusion that showed up as 2.2 ms/step of the r05 seq-4096 baseline
    (it re-reads dy once per reduced axis in a minor-dim-hostile order).
    The custom backward collapses the leading axes FIRST — one reshape to
    (rows, out_dim), which is free on a row-major layout — then does a
    single-axis f32 column reduce, the shape the TPU reducer streams at
    full HBM bandwidth."""
    return y + b


def _bias_add_fwd(y, b):
    # residual is the (out_dim,) bias itself — only its dtype is consumed,
    # but a raw numpy dtype is not a pytree leaf JAX transforms accept
    return y + b, b


def _bias_add_bwd(b, g):
    import jax.numpy as jnp

    rows = g.reshape(-1, g.shape[-1])
    db = jnp.sum(rows.astype(jnp.float32), axis=0).astype(b.dtype)
    return g, db


bias_add.defvjp(_bias_add_fwd, _bias_add_bwd)


def apply_activation(x, activation: ActiMode):
    import jax.numpy as jnp
    import jax.nn as jnn

    if activation == ActiMode.AC_MODE_NONE:
        return x
    if activation == ActiMode.AC_MODE_RELU:
        return jnn.relu(x)
    if activation == ActiMode.AC_MODE_SIGMOID:
        return jnn.sigmoid(x)
    if activation == ActiMode.AC_MODE_TANH:
        return jnp.tanh(x)
    if activation == ActiMode.AC_MODE_GELU:
        return jnn.gelu(x)
    raise ValueError(f"unknown activation {activation}")


def apply_weight_regularizer(spec, kernel, ctx: OpContext) -> None:
    """("l1"|"l2", lambda) weight-decay penalty added to the training loss
    via the aux-loss hook (reference: keras/regularizers.py carries the
    RegularizerMode into the Linear layer)."""
    if not spec or not ctx.training or ctx.aux_losses is None:
        return
    kind, lam = spec
    import jax.numpy as jnp

    w = kernel.astype(jnp.float32)
    if kind == "l1":
        ctx.aux_losses.append(lam * jnp.sum(jnp.abs(w)))
    elif kind == "l2":
        ctx.aux_losses.append(lam * jnp.sum(w * w))
    else:
        raise ValueError(f"unknown regularizer kind {kind!r}")


@register_op(OperatorType.OP_LINEAR)
class LinearOp(Op):
    """attrs: out_dim, activation, use_bias, kernel_initializer, bias_initializer."""

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        return [tuple(ishape[:-1]) + (self.attrs["out_dim"],)]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import (DefaultBiasInitializer,
                                              DefaultWeightInitializer)

        in_dim = input_shapes[0][-1]
        out_dim = self.attrs["out_dim"]
        specs = {
            "kernel": ((in_dim, out_dim), self.data_type,
                       self.attrs.get("kernel_initializer")
                       or DefaultWeightInitializer()),
        }
        if self.attrs.get("use_bias", True):
            specs["bias"] = ((out_dim,), self.data_type,
                             self.attrs.get("bias_initializer")
                             or DefaultBiasInitializer())
        return specs

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        kernel = params["kernel"]
        y = jnp.dot(x, kernel, preferred_element_type=jnp.float32)
        y = y.astype(x.dtype)
        if "bias" in params:
            y = bias_add(y, params["bias"])
        apply_weight_regularizer(self.attrs.get("kernel_regularizer"),
                                 kernel, ctx)
        return [apply_activation(y, self.attrs.get("activation",
                                                   ActiMode.AC_MODE_NONE))]

    def flops(self, input_shapes, output_shapes):
        ishape = input_shapes[0]
        return 2 * int(np.prod(ishape)) * self.attrs["out_dim"]

    def parallelizable_dims(self, input_shapes):
        ndim = len(input_shapes[0])
        return {
            "batch": True,
            # shard out_dim (column-parallel): kernel dim 1, bias dim 0
            "channel_out": {"output_dim": ndim - 1,
                            "weights": {"kernel": 1, "bias": 0}},
            # shard in_dim (row-parallel): kernel dim 0; output unreduced -> psum
            "channel_in": {"input_dim": ndim - 1, "weights": {"kernel": 0},
                           "reduces_output": True},
        }
