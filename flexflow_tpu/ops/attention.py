"""Multi-head attention.

Reference: src/ops/attention.cc (926 LoC) using cuDNN's packed
``cudnnMultiHeadAttnForward`` (attention.cu:35-128). TPU-native: separate
q/k/v/o projections (MXU matmuls) + scaled-dot-product core. The core runs
either as plain einsums (XLA fuses + tiles) or the Pallas flash-attention
kernel (kernels/flash_attention.py) for long sequences — selected at lowering
time, not by the user.

Parallelism: shardable over batch (sample) and heads (the reference's
attribute parallelism, substitution.cc:3169 create_partition_attention_combine)
by sharding the head dim of the projection weights; sequence parallelism /
ring attention is provided by the RING_ATTENTION variant (parallel extension,
absent in the reference — SURVEY §5 long-context).
"""
from __future__ import annotations


import numpy as np

from ..ffconst import OperatorType
from .base import Op, OpContext, register_op


def mha_core(q, k, v, *, causal: bool = False, dropout: float = 0.0,
             rng=None, training: bool = False, attn_mask=None,
             scale: float = None):
    """q,k,v: (batch, heads, seq, head_dim) -> (batch, heads, seq_q, head_dim).
    attn_mask: optional additive mask broadcastable to (b, h, seq_q, seq_k)."""
    import jax
    import jax.numpy as jnp

    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if attn_mask is not None:
        if jnp.issubdtype(attn_mask.dtype, jnp.bool_):
            # torch bool-mask semantics: True = attend, False = -inf
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if training and dropout > 0.0 and rng is not None:
        keep = 1.0 - dropout
        probs = probs * jax.random.bernoulli(rng, keep, probs.shape) / keep
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


@register_op(OperatorType.OP_MULTIHEAD_ATTENTION)
class MultiHeadAttentionOp(Op):
    """attrs: embed_dim, num_heads, kdim, vdim, dropout, bias, add_bias_kv,
    add_zero_attn, causal, use_flash (builder: FFModel::multihead_attention,
    reference model.h:520-537).

    inputs: (query, key, value), each (batch, seq, dim).
    output: (batch, seq_q, embed_dim).
    """

    def _dims(self):
        a = self.attrs
        embed = a["embed_dim"]
        heads = a["num_heads"]
        kdim = a.get("kdim") or embed // heads
        vdim = a.get("vdim") or embed // heads
        return embed, heads, kdim, vdim

    def infer_output_shapes(self, input_shapes):
        q = input_shapes[0]
        return [(q[0], q[1], self.attrs["embed_dim"])]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import (DefaultBiasInitializer,
                                              DefaultWeightInitializer)

        embed, heads, kdim, vdim = self._dims()
        q_in = input_shapes[0][-1]
        k_in = input_shapes[1][-1]
        v_in = input_shapes[2][-1]
        init = self.attrs.get("kernel_initializer") or DefaultWeightInitializer()
        specs = {
            "wq": ((q_in, heads, kdim), self.data_type, init),
            "wk": ((k_in, heads, kdim), self.data_type, init),
            "wv": ((v_in, heads, vdim), self.data_type, init),
            "wo": ((heads, vdim, embed), self.data_type, init),
        }
        if self.attrs.get("bias", True):
            specs["bo"] = ((embed,), self.data_type, DefaultBiasInitializer())
        return specs

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        q_in, k_in, v_in = inputs
        # NOTE: a packed q/k/v projection (one concat-weight matmul, like the
        # reference's cuDNN MHA packed weight, attention.cu:225) was measured
        # SLOWER on v5e (81.5 ms vs 72.9 ms step) — the runtime concat +
        # split copies outweigh the single-matmul win; XLA already schedules
        # the three projections back-to-back on the MXU.
        q = jnp.einsum("bsd,dhk->bhsk", q_in, params["wq"])
        k = jnp.einsum("bsd,dhk->bhsk", k_in, params["wk"])
        v = jnp.einsum("bsd,dhk->bhsk", v_in, params["wv"])
        use_flash = self.attrs.get("use_flash", "auto")
        causal = self.attrs.get("causal", False)
        seq_axis = self.attrs.get("sequence_parallel_axis")
        dropout = self.attrs.get("dropout", 0.0)
        live_dropout = _resolve_live_dropout(dropout, ctx)
        seed = _dropout_seed(ctx.rng) if live_dropout else None
        if ctx.serving is not None:
            # serving engine prefill/decode (ISSUE 6): the KV ring buffer is
            # the execution path, selected before any kernel routing —
            # decode shapes (seq 1) must never reach flash/ring
            out = _serving_attention(self.name, q, k, v, ctx.serving,
                                     causal=causal)
        elif seq_axis and ctx.mesh is not None and seq_axis in ctx.mesh.shape:
            if self.attrs.get("sequence_parallel_mode") == "alltoall":
                from ..kernels.ulysses_attention import ulysses_attention

                out = ulysses_attention(q, k, v, ctx.mesh, seq_axis=seq_axis,
                                        causal=causal,
                                        dropout=live_dropout, seed=seed)
            else:  # default schedule: ring rotation over ICI
                from ..kernels.ring_attention import ring_attention

                out = ring_attention(q, k, v, ctx.mesh, seq_axis=seq_axis,
                                     causal=causal,
                                     dropout=live_dropout, seed=seed)
        elif _should_use_flash(use_flash, q, k, causal) \
                and _flash_blocks(q.shape[-2], k.shape[-2]) is not None:
            from ..kernels.flash_attention import flash_attention

            bq, bk = _flash_blocks(q.shape[-2], k.shape[-2])
            out = flash_attention(q, k, v, causal, bq, bk,
                                  dropout=live_dropout, seed=seed)
        else:
            # the already-resolved live_dropout is the single gate (the r5
            # warning path); rng only rides along when dropout is live, so
            # _resolve_live_dropout cannot be second-guessed downstream
            out = mha_core(q, k, v, causal=causal, dropout=live_dropout,
                           rng=ctx.rng if live_dropout else None,
                           training=ctx.training)
        y = jnp.einsum("bhsv,hvd->bsd", out, params["wo"],
                       preferred_element_type=jnp.float32).astype(q_in.dtype)
        if "bo" in params:
            y = y + params["bo"]
        return [y]

    def flops(self, input_shapes, output_shapes):
        b, sq, _ = input_shapes[0]
        sk = input_shapes[1][1]
        embed, heads, kdim, vdim = self._dims()
        proj = 2 * b * sq * input_shapes[0][-1] * heads * kdim * 3 \
            + 2 * b * sq * heads * vdim * embed
        core = 2 * b * heads * sq * sk * (kdim + vdim)
        return proj + core

    def parallelizable_dims(self, input_shapes):
        return {
            "batch": True,
            # head (attribute) parallelism: shard heads dim of all projections
            "heads": {"weights": {"wq": 1, "wk": 1, "wv": 1, "wo": 0},
                      "reduces_output": True},
        }


def _serving_attention(name: str, q, k, v, sv, *, causal: bool):
    """Prefill/decode attention over the serving KV ring buffer
    (serving/kvcache.py; ISSUE 6). Numerics are kept IDENTICAL to
    ``mha_core``'s einsum path — same scale, same ``-1e30`` additive mask,
    same f32-accumulating einsums — so prefill+decode logits bitwise-match
    the whole-sequence forward (tests/test_serving.py's equivalence gate):
    masked lanes contribute exp(-1e30-max) == 0.0 exactly, and the ring
    buffer's unwritten tail is zeros, so the wider reduction adds exact
    zeros only.

    * prefill: q/k/v carry the whole padded prompt; the causal core runs
      unchanged and k/v land at position 0 of a fresh ``max_len`` buffer.
    * decode: q/k/v carry ONE token per slot; k/v are written at
      ``positions[slot]`` (per-slot dynamic_update_slice — static shapes,
      no recompile) and q attends over the full buffer under the mask
      ``key_pos <= position``.

    Paged decode (ISSUE 12, ``sv.paged``): the per-slot ring becomes a
    block pool + per-slot block tables (serving/kvcache.py). The token
    write is a pool scatter at (table[pos // bs], pos % bs); the read is
    either the Pallas flash-decode kernel (TPU fast path — O(true
    length) HBM traffic, kernels/flash_decode.py) or a pure gather back
    to position order followed by EXACTLY the ring math below — gathered
    rows are bitwise the stored rows and garbage-block rows are masked
    to exact zeros, so paged fp decode stays bitwise-identical to the
    ring (and, under ``sv.exact``, to the whole-sequence forward). The
    int8 layout dequantizes per-(token, head) rows on read and is judged
    against a pinned tolerance band instead.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..serving.kvcache import (dequantize_kv, gather_paged_kv,
                                   gather_paged_scales, quantize_kv,
                                   write_token_kv, write_token_kv_paged,
                                   write_token_scale_paged)

    if not causal:
        raise ValueError(
            f"{name}: serving prefill/decode requires CAUSAL self-attention "
            "(bidirectional attention cannot be decoded incrementally); "
            "build the model with causal=True")
    if sv.mode == "chunk":
        return _chunk_prefill_attention(name, q, k, v, sv)
    if sv.mode == "prefill":
        b, h, L, hd = k.shape
        kbuf = lax.dynamic_update_slice(
            jnp.zeros((b, h, sv.max_len, hd), k.dtype), k, (0, 0, 0, 0))
        vbuf = lax.dynamic_update_slice(
            jnp.zeros((b, h, sv.max_len, v.shape[-1]), v.dtype), v,
            (0, 0, 0, 0))
        sv.cache_out[name] = (kbuf, vbuf)
        return mha_core(q, k, v, causal=True)
    scale = 1.0 / np.sqrt(q.shape[-1])
    if sv.paged:
        tables, bs = sv.block_tables, sv.block_size
        if sv.kv_dtype == "int8":
            kq, ks, vq, vs = sv.cache_in[name]
            k_new, ks_new = quantize_kv(k)   # (S,h,1,hd) -> scale (S,h,1)
            v_new, vs_new = quantize_kv(v)
            kq = write_token_kv_paged(kq, k_new, sv.positions, tables, bs)
            ks = write_token_scale_paged(ks, ks_new, sv.positions, tables,
                                         bs)
            vq = write_token_kv_paged(vq, v_new, sv.positions, tables, bs)
            vs = write_token_scale_paged(vs, vs_new, sv.positions, tables,
                                         bs)
            sv.cache_out[name] = (kq, ks, vq, vs)
            kernel_out = _maybe_flash_decode(
                q, (kq, ks, vq, vs), tables, sv, scale)
            if kernel_out is not None:
                return kernel_out
            kc = dequantize_kv(gather_paged_kv(kq, tables),
                               gather_paged_scales(ks, tables), k.dtype)
            vc = dequantize_kv(gather_paged_kv(vq, tables),
                               gather_paged_scales(vs, tables), v.dtype)
        else:
            kp, vp = sv.cache_in[name]
            kp = write_token_kv_paged(kp, k, sv.positions, tables, bs)
            vp = write_token_kv_paged(vp, v, sv.positions, tables, bs)
            sv.cache_out[name] = (kp, vp)
            kernel_out = _maybe_flash_decode(q, (kp, vp), tables, sv,
                                             scale)
            if kernel_out is not None:
                return kernel_out
            kc = gather_paged_kv(kp, tables)
            vc = gather_paged_kv(vp, tables)
    else:
        kc, vc = sv.cache_in[name]
        kc = write_token_kv(kc, k, sv.positions)
        vc = write_token_kv(vc, v, sv.positions)
        sv.cache_out[name] = (kc, vc)
    extent = kc.shape[2]  # max_len (ring) | blocks * block_size (paged)
    if sv.seq_shards > 1:
        return _seqpar_decode(q, kc, vc, sv, scale, extent)
    if sv.exact:
        # bitwise mode: the 1-token q rides a full-extent score GEMM (its
        # row is extracted afterwards) so the d-axis accumulation order
        # matches the whole-sequence forward exactly; the fast path below
        # lowers to a matvec that differs by ~1 ulp
        qpad = write_token_kv(
            jnp.zeros(kc.shape[:2] + (extent, q.shape[-1]), q.dtype),
            q, sv.positions)
        full = jnp.einsum("bhqd,bhkd->bhqk", qpad, kc,
                          preferred_element_type=jnp.float32) * scale
        logits = jnp.take_along_axis(
            full, sv.positions[:, None, None, None], axis=2)
    else:
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                            preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(extent)
    mask = kpos[None, None, None, :] <= sv.positions[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vc.dtype), vc,
                     preferred_element_type=jnp.float32)
    return out.astype(vc.dtype)


def _seqpar_decode(q, kc, vc, sv, scale, extent):
    """Sequence-parallel decode step (ISSUE 18): the gathered extent is
    partitioned into ``sv.seq_shards`` contiguous key segments — on a
    mesh each segment is one chip's run of pool blocks; on a single
    device the same decomposition runs locally, which is what tier-1
    pins.

    ``exact`` keeps the bitwise contract against the single-shard
    reference: every shard scores the SAME full-extent padded q against
    its key segment, and the score einsum never reduces over the key
    axis — shard s's columns are elementwise the unsharded GEMM's
    columns ``[s*seg, (s+1)*seg)``, so concatenating in position order
    reproduces the single-shard logits bit-for-bit and one unsharded
    softmax/PV finishes the step (the combine collective carries raw
    score columns instead of (m, l, acc) in this audit mode).

    The fast path is the deployable layout: each shard folds its
    segment through the flash-decode online-softmax recurrence into a
    partial ``(m, l, acc)`` and the priced segment-merge combines them
    (kernels/seqpar_decode.py) — ~1 ulp from the single-shard fast
    matvec, the same band the fast-vs-exact delta already occupies.
    Fully-masked segments (write cursor below the shard's range)
    contribute exact zeros via ``exp(-1e30 - m*)``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..kernels.seqpar_decode import (combine_partials,
                                         decode_shard_partial,
                                         shard_segment)
    from ..serving.kvcache import write_token_kv

    S = int(sv.seq_shards)
    seg = shard_segment(extent, S)
    kpos = jnp.arange(extent)
    mask = kpos[None, None, None, :] <= sv.positions[:, None, None, None]
    if sv.exact:
        qpad = write_token_kv(
            jnp.zeros(kc.shape[:2] + (extent, q.shape[-1]), q.dtype),
            q, sv.positions)
        cols = []
        for s in range(S):
            kseg = lax.slice_in_dim(kc, s * seg, (s + 1) * seg, axis=2)
            full = jnp.einsum("bhqd,bhkd->bhqk", qpad, kseg,
                              preferred_element_type=jnp.float32) * scale
            cols.append(jnp.take_along_axis(
                full, sv.positions[:, None, None, None], axis=2))
        logits = jnp.where(mask, jnp.concatenate(cols, axis=-1), -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vc.dtype), vc,
                         preferred_element_type=jnp.float32)
        return out.astype(vc.dtype)
    partials = []
    for s in range(S):
        lo, hi = s * seg, (s + 1) * seg
        partials.append(decode_shard_partial(
            q, lax.slice_in_dim(kc, lo, hi, axis=2),
            lax.slice_in_dim(vc, lo, hi, axis=2),
            mask[..., lo:hi], scale))
    return combine_partials(partials).astype(vc.dtype)


def _chunk_prefill_attention(name: str, q, k, v, sv):
    """One prefill CHUNK for a single slot over the paged pool
    (ISSUE 14, docs/serving.md "Prefix cache & chunked prefill"): q/k/v
    carry ``chunk_len`` tokens of ONE request (batch 1) starting at
    position ``sv.positions[0]``; the chunk's k/v rows are scattered
    into the slot's pool blocks (pad rows beyond ``sv.lengths[0]`` go to
    the garbage block) and q attends over the slot's full gathered
    extent — the already-written prefix (a cached trie hit or earlier
    chunks) plus this chunk — under the mask ``key_pos <= row_pos``.

    Numerics are BITWISE the one-shot prefill's, by construction, in
    every engine mode (not just ``exact``): the chunk's score product
    always rides a full-extent GEMM (chunk rows scattered into a
    zero-padded extent-row q, the decode-``exact`` idiom) so the d-axis
    accumulation order matches the whole-sequence forward's; masked
    lanes — the stale rows of freshly-recycled blocks included — are
    finite and contribute exp(-1e30 - max) == 0.0 exactly; and the
    row-wise projections run at the chunk program's fixed compiled
    width (floor 2 — a 1-row matvec is the one lowering that breaks
    per-row equality). This is what lets the prefix cache default ON
    without perturbing a single token of any cold stream: a trie-hit
    admission's suffix chunk, a chunked long prompt and a cold one-shot
    prefill all commit identical KV rows and identical next-token
    logits. The extent-wide score pad is the price (one chunk pays
    O(extent^2) score FLOPs instead of O(chunk x extent)); chunks run
    once per admitted prompt, decode runs per token, so the trade
    follows the decode-``exact`` precedent. int8 pools quantize the
    chunk rows per-(token, head) on write — band-judged like every
    int8 path, never bitwise."""
    import jax
    import jax.numpy as jnp

    from ..serving.kvcache import (dequantize_kv, gather_paged_kv,
                                   gather_paged_scales, quantize_kv,
                                   write_chunk_kv_paged,
                                   write_chunk_scale_paged)

    if not sv.paged:
        raise NotImplementedError(
            f"{name}: chunked prefill requires the paged KV layout "
            "(kv_cache='paged'); the ring layout has no block pool to "
            "write chunks into")
    tables, bs = sv.block_tables, sv.block_size  # tables: (1, mb)
    row = tables[0]
    start = sv.positions[0]
    n_new = sv.lengths[0]
    chunk_len = q.shape[2]
    pos = start + jnp.arange(chunk_len, dtype=jnp.int32)
    valid = jnp.arange(chunk_len) < n_new
    if sv.kv_dtype == "int8":
        kq, ks, vq, vs = sv.cache_in[name]
        k_new, ks_new = quantize_kv(k)
        v_new, vs_new = quantize_kv(v)
        kq = write_chunk_kv_paged(kq, k_new, pos, valid, row, bs)
        ks = write_chunk_scale_paged(ks, ks_new, pos, valid, row, bs)
        vq = write_chunk_kv_paged(vq, v_new, pos, valid, row, bs)
        vs = write_chunk_scale_paged(vs, vs_new, pos, valid, row, bs)
        sv.cache_out[name] = (kq, ks, vq, vs)
        kc = dequantize_kv(gather_paged_kv(kq, tables),
                           gather_paged_scales(ks, tables), k.dtype)
        vc = dequantize_kv(gather_paged_kv(vq, tables),
                           gather_paged_scales(vs, tables), v.dtype)
    else:
        kp, vp = sv.cache_in[name]
        kp = write_chunk_kv_paged(kp, k, pos, valid, row, bs)
        vp = write_chunk_kv_paged(vp, v, pos, valid, row, bs)
        sv.cache_out[name] = (kp, vp)
        kc = gather_paged_kv(kp, tables)
        vc = gather_paged_kv(vp, tables)
    extent = kc.shape[2]
    scale = 1.0 / np.sqrt(q.shape[-1])
    # full-extent score GEMM: chunk q rows scattered at their positions
    # into a zero extent-row buffer (pad rows dropped out of bounds),
    # rows re-extracted after the product — the decode-exact idiom
    safe = jnp.where(valid, pos, extent + 1)
    qpad = jnp.zeros((1, q.shape[1], extent, q.shape[-1]), q.dtype)
    qpad = qpad.at[0, :, safe].set(jnp.swapaxes(q[0], 0, 1), mode="drop")
    full = jnp.einsum("bhqd,bhkd->bhqk", qpad, kc,
                      preferred_element_type=jnp.float32) * scale
    logits = jnp.take_along_axis(
        full, jnp.clip(pos, 0, extent - 1)[None, None, :, None], axis=2)
    kpos = jnp.arange(extent)
    mask = kpos[None, None, None, :] <= pos[None, None, :, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vc.dtype), vc,
                     preferred_element_type=jnp.float32)
    return out.astype(vc.dtype)


def _maybe_flash_decode(q, entry, tables, sv, sm_scale):
    """Route one paged decode read through the Pallas flash-decode kernel
    when eligible (on-TPU, non-exact numerics, MXU-friendly dims) —
    returns the (S, h, 1, hd) output or None for the gather fallback.
    Consults ``_flash_tuning(kernel="flash_decode")`` so an unmeasured
    chip generation warns once for THIS kernel (ISSUE 12 satellite)."""
    from ..kernels.flash_decode import flash_decode, use_flash_decode

    if (sv.exact or sv.seq_shards > 1
            or not use_flash_decode(q.shape[-1], sv.block_size)):
        # seq_shards > 1: the shard decomposition runs the split-K math
        # per segment over the gathered extent (_seqpar_decode); the
        # single whole-extent kernel launch would bypass the combine
        return None
    _flash_tuning(kernel="flash_decode")  # per-(generation, kernel) warn
    n_keys = sv.positions + 1
    if sv.kv_dtype == "int8":
        kq, ks, vq, vs = entry
        out = flash_decode(q[:, :, 0, :], kq, vq, tables, n_keys,
                           sm_scale=sm_scale, kscale=ks, vscale=vs)
    else:
        kp, vp = entry
        out = flash_decode(q[:, :, 0, :], kp, vp, tables, n_keys,
                           sm_scale=sm_scale)
    return out[:, :, None, :]


def _dropout_seed(rng):
    """Fold the step rng into one traced uint32 scalar for the counter-based
    in-kernel dropout PRNG (reseeds every step without recompiling)."""
    import jax
    import jax.numpy as jnp

    return jax.random.bits(rng, (), jnp.uint32)


def _resolve_live_dropout(dropout, ctx) -> float:
    """Effective dropout rate for this forward. A training context that
    requests dropout but carries no rng would otherwise SILENTLY train
    without dropout on every kernel path (the kernel entry points raise,
    the op layer used to swallow it — ADVICE r4): surface it loudly."""
    if not dropout or not ctx.training:
        return 0.0
    if ctx.rng is None:
        import warnings

        warnings.warn(
            f"attention dropout={dropout} requested with training=True but "
            f"the step context has no rng — training WITHOUT dropout. "
            f"Thread an rng through the executor (fit/make_train_step do "
            f"this automatically).", stacklevel=3)
        return 0.0
    return float(dropout)


# Flash crossover/tile constants, keyed by TPU generation (VERDICT r4
# weak #7: these are hardware-generation-specific). ONLY the v5e row is
# MEASURED (the chip of this image, round-5 streaming kernels, b1 h16
# s4096 d64 bf16 sweep: (block_q 512, block_k 1024) fwd 1.72 ms /
# fwd+fused-bwd 3.58 ms vs 4.6 ms at (512,512) and 7.8 ms at (256,256);
# wider k tiles amortize the per-grid-step scratch round-trip, block_k >
# 1024 overflows VMEM in the fused backward's score tile; min_block 256:
# at 128-wide tiles — e.g. seq 640's only divisor — the einsum core wins).
# Other generations inherit the v5e numbers as UNMEASURED estimates;
# re-measure recipe: on the target chip, time
# jax.jit(jax.grad(lambda q,k,v: flash_attention(q,k,v,False,bq,bk).sum()))
# at b1 h16 s4096 d64 bf16 over (bq, bk) in {128,256,512}x{256,512,1024}
# and vs mha_core at seq 640, then update the row.
FLASH_TUNING = {
    # v5e is the only MEASURED row; _flash_tuning() falls back to it for
    # every other generation (v4/v5p/v6e: add a measured row here after
    # running the recipe above on that chip)
    "v5e": {"block_q_cap": 512, "block_k_cap": 1024, "min_block": 256},
}
_tuning_cache: dict = {}


def _detect_tpu_generation():
    """(on_tpu, generation) of the process's first device — one probe,
    cached; the shared detection behind every kernel's tuning lookup
    (monkeypatch point for the warn-once tests)."""
    gen = None
    on_tpu = False
    try:
        import jax

        from ..search.machine_model import detect_generation

        dev = jax.devices()[0]
        on_tpu = dev.platform == "tpu"
        gen = detect_generation(dev.device_kind)
    except Exception:
        pass
    return on_tpu, gen


def _flash_tuning(kernel: str = "flash_attention") -> dict:
    """The FLASH_TUNING row for the current chip (device_kind normalized by
    machine_model.detect_generation — the one shared matcher; v5e's
    measured row is the default for unknown kinds). When an UNMEASURED TPU
    generation inherits the v5e row, warn once PER (generation, kernel) —
    not once per process (ISSUE 12 satellite: the old module-level
    warn-once meant a v5e-tuned tile row inherited by another generation
    was silenced for the flash-DECODE kernel after the first training
    warning): if a flash kernel regresses on that chip, the trace must
    point at the tuning table, not the kernels (ADVICE r5)."""
    if "probe" not in _tuning_cache:
        _tuning_cache["probe"] = _detect_tpu_generation()
        _tuning_cache["warned"] = set()
    on_tpu, gen = _tuning_cache["probe"]
    if on_tpu and gen not in FLASH_TUNING and \
            (gen, kernel) not in _tuning_cache["warned"]:
        import warnings

        _tuning_cache["warned"].add((gen, kernel))
        warnings.warn(
            f"{kernel}: flash tile table has no MEASURED row for TPU "
            f"generation {gen!r}; inheriting the v5e tiling (block_q "
            f"{FLASH_TUNING['v5e']['block_q_cap']} / block_k "
            f"{FLASH_TUNING['v5e']['block_k_cap']} / min_block "
            f"{FLASH_TUNING['v5e']['min_block']}) as an unmeasured "
            f"estimate — on-chip regressions are traceable here; "
            f"re-measure per the FLASH_TUNING recipe and add a row.",
            stacklevel=2)
    return FLASH_TUNING.get(gen, FLASH_TUNING["v5e"])


def _flash_blocks(seq_q: int, seq_k: int):
    """Block sizes for the streaming flash kernels from the current chip's
    FLASH_TUNING row, or None when a sequence has no 128-multiple divisor
    (the kernel's grid floor-divisions would silently drop the tail — fall
    back to the einsum core instead)."""
    tune = _flash_tuning()

    def pick(seq, cap):
        for b in (cap, 512, 384, 256, 128):
            if b <= cap and seq % b == 0:
                return b
        return None

    bq = pick(seq_q, tune["block_q_cap"])
    bk = pick(seq_k, tune["block_k_cap"])
    if bq is None or bk is None:
        return None
    return bq, bk


def _should_use_flash(use_flash, q, k, causal) -> bool:
    if causal and q.shape[-2] > k.shape[-2]:
        return False  # empty attention windows — einsum core only
    if use_flash is True:
        return True
    if use_flash == "auto":
        import jax

        try:
            on_tpu = jax.devices()[0].platform == "tpu"
        except Exception:
            on_tpu = False
        if not on_tpu or q.shape[-1] % 64 != 0:
            return False
        # head_dim 64 is fine on the MXU (the (block_q, d) tiles pad lanes
        # to 128). Only take flash when both sequences admit blocks >= the
        # generation's measured crossover (FLASH_TUNING.min_block): below
        # it the einsum core wins, e.g. seq 640 only divides by 128.
        blocks = _flash_blocks(q.shape[-2], k.shape[-2])
        return blocks is not None and \
            min(blocks) >= _flash_tuning()["min_block"]
    return False


@register_op(OperatorType.OP_SDPA)
class SDPAOp(Op):
    """Scaled-dot-product attention core without projections (torch
    F.scaled_dot_product_attention; reference analog: the cuDNN core inside
    src/ops/attention.cu minus the packed q/k/v/o projections).

    inputs: (q, k, v[, additive attn_mask]), q/k/v (batch, heads, seq, hd).
    attrs: dropout, causal, scale (None = 1/sqrt(head_dim)), use_flash.
    """

    def infer_output_shapes(self, input_shapes):
        q, _k, v = input_shapes[:3]
        return [tuple(q[:-1]) + (v[-1],)]

    def forward(self, params, inputs, ctx: OpContext):
        q, k, v = inputs[:3]
        mask = inputs[3] if len(inputs) > 3 else None
        causal = self.attrs.get("causal", False)
        # flash kernel has no mask/scale parameters — only take it when the
        # request needs neither (dropout IS supported in-kernel)
        dropout = self.attrs.get("dropout", 0.0)
        live_dropout = _resolve_live_dropout(dropout, ctx)
        if mask is None and self.attrs.get("scale") is None \
                and _should_use_flash(
                    self.attrs.get("use_flash", "auto"), q, k, causal) \
                and _flash_blocks(q.shape[-2], k.shape[-2]) is not None:
            from ..kernels.flash_attention import flash_attention

            bq, bk = _flash_blocks(q.shape[-2], k.shape[-2])
            seed = _dropout_seed(ctx.rng) if live_dropout else None
            return [flash_attention(q, k, v, causal, bq, bk,
                                    dropout=live_dropout, seed=seed)]
        # same single-gate rule as MultiHeadAttentionOp: pass the resolved
        # live_dropout, rng only when it is live
        return [mha_core(q, k, v, causal=causal, dropout=live_dropout,
                         rng=ctx.rng if live_dropout else None,
                         training=ctx.training,
                         attn_mask=mask, scale=self.attrs.get("scale"))]

    def flops(self, input_shapes, output_shapes):
        b, h, sq, d = input_shapes[0]
        sk = input_shapes[1][2]
        vd = input_shapes[2][3]
        return 2 * b * h * sq * sk * (d + vd)

    def parallelizable_dims(self, input_shapes):
        return {"batch": True}
