"""Op base class: the typed node of the Parallel Computation Graph.

Analog of the reference's ``class Op`` (include/flexflow/operator.h:51). The
reference contract — virtual ``init/forward/backward`` building Legion index
launches plus ``measure_operator_cost`` — maps TPU-natively to:

* ``forward(params, inputs, ctx)``: a pure, jax-traceable function. Backward is
  derived by ``jax.grad`` (sharded autodiff inserts the collectives the
  reference implements by hand in optimizer_kernel.cu / parallel ops).
* shape/dtype inference (``infer_output_shapes``) replacing Legion region setup.
* ``weight_specs``: declared parameters with initializers (reference: per-op
  weight ParallelTensors).
* ``flops`` / ``memory_bytes``: analytic cost hooks for the simulator
  (reference: measure_operator_cost, simulator.cc:489).

Op *Params* dataclass-equality/hashing for node dedup (reference:
``get_or_create_node`` cache, include/flexflow/model.h:679-706) is provided by
``params_key``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ffconst import DataType, OperatorType
from ..machine_view import MachineView


@dataclasses.dataclass
class OpContext:
    """Per-call context threaded through forward (replaces reference OpMeta)."""

    training: bool = True
    rng: Any = None  # jax PRNGKey, split per dropout-like op
    seq_length: int = -1
    mesh: Any = None  # jax Mesh when running under pjit
    profiling: bool = False
    # auxiliary loss terms appended by ops (e.g. MoE load-balance, the
    # reference's lambda_bal term in aggregate.cu backward); the executor adds
    # their sum to the training loss. Shared list across all node contexts.
    aux_losses: Any = None
    # cache-op state (reference: src/ops/cache.cc + recompile pairing):
    # cache_in = {op_name: cached_tensor, "__use_cache__": bool scalar} fed
    # into the step; cache_out = dict the CacheOps fill with fresh values,
    # returned by the executor's step for host-side scoring. Shared dicts
    # across all node contexts.
    cache_in: Any = None
    cache_out: Any = None
    # serving state (ISSUE 6, flexflow_tpu/serving): a
    # ``serving.kvcache.ServingState`` when this forward is a prefill or
    # decode step of the inference engine — ops with sequence state
    # (causal attention's KV, the LSTM carry) read ``cache_in`` and
    # publish into ``cache_out`` keyed by op name. None during training
    # and plain whole-sequence inference, which is the only cost the
    # existing paths pay.
    serving: Any = None


# registry: OperatorType -> Op subclass
_OP_REGISTRY: Dict[OperatorType, type] = {}


def register_op(op_type: OperatorType):
    def deco(cls):
        _OP_REGISTRY[op_type] = cls
        cls.op_type = op_type
        return cls

    return deco


def op_class_for(op_type: OperatorType) -> type:
    if op_type not in _OP_REGISTRY:
        raise KeyError(f"no Op registered for {op_type.name}")
    return _OP_REGISTRY[op_type]


class Op:
    """Base PCG operator."""

    op_type: OperatorType = OperatorType.OP_NOOP

    def __init__(self, name: str, attrs: Dict[str, Any], dtype: DataType,
                 num_inputs: int = 1):
        self.name = name
        self.attrs = dict(attrs)
        self.data_type = dtype
        self.num_inputs = num_inputs
        self.machine_view: Optional[MachineView] = None

    # -- identity / dedup -------------------------------------------------------
    def params_key(self) -> Tuple:
        """Hashable params tuple (reference: <op>_params.h structs)."""
        return (self.op_type, self.data_type,
                tuple(sorted((k, _freeze(v)) for k, v in self.attrs.items())))

    # -- shape inference --------------------------------------------------------
    def infer_output_shapes(
        self, input_shapes: List[Tuple[int, ...]]
    ) -> List[Tuple[int, ...]]:
        raise NotImplementedError(self.op_type.name)

    def output_dtype(self, input_dtypes: List[DataType]) -> DataType:
        return input_dtypes[0] if input_dtypes else self.data_type

    def output_dtypes(self, input_dtypes: List[DataType],
                      num_outputs: int) -> List[DataType]:
        """Per-output dtypes; override for ops with heterogeneous outputs
        (e.g. TopK's int32 indices)."""
        return [self.output_dtype(input_dtypes)] * num_outputs

    # -- parameters -------------------------------------------------------------
    def weight_specs(
        self, input_shapes: List[Tuple[int, ...]]
    ) -> Dict[str, Tuple[Tuple[int, ...], DataType, Any]]:
        """name -> (shape, dtype, initializer); empty for stateless ops."""
        return {}

    # -- compute ----------------------------------------------------------------
    def forward(self, params: Dict[str, Any], inputs: List[Any],
                ctx: OpContext) -> List[Any]:
        raise NotImplementedError(self.op_type.name)

    # -- cost model hooks (reference: measure_operator_cost) --------------------
    def flops(self, input_shapes: List[Tuple[int, ...]],
              output_shapes: List[Tuple[int, ...]]) -> int:
        """Forward FLOPs; default = elementwise over outputs."""
        return sum(int(np.prod(s)) for s in output_shapes)

    def memory_bytes(self, input_shapes, output_shapes) -> int:
        from ..ffconst import size_of_datatype

        el = size_of_datatype(self.data_type)
        return el * (sum(int(np.prod(s)) for s in input_shapes)
                     + sum(int(np.prod(s)) for s in output_shapes))

    # -- parallelization metadata ----------------------------------------------
    def parallelizable_dims(self, input_shapes) -> Dict[str, Any]:
        """Which logical dims of output 0 may be sharded, and how weights follow.

        TPU-native analog of the reference's ParallelDimMappingRecord machinery
        (operator.h:22-118): returns {"batch": True, "channel_out": idx or None,
        ...} consumed by the strategy search.
        """
        return {"batch": True}

    def can_inplace_output(self) -> bool:
        return False

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name})"


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return (v.shape, v.dtype.str, v.tobytes())
    if callable(v) and not isinstance(v, type):
        return getattr(v, "__name__", repr(v))
    return v
