"""Elementwise binary/unary ops, scalar ops, cast, dropout.

Reference: src/ops/element_binary.cc (812 LoC, broadcast support),
element_unary.cc (720, inplace option), cast.cc, dropout.cc. TPU-native these
are single jnp calls — XLA fuses them into neighboring matmuls, which is the
whole point of not hand-writing kernels for them.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from ..ffconst import OperatorType, dtype_to_jnp
from .base import Op, OpContext, register_op


def _broadcast_shape(a: Tuple[int, ...], b: Tuple[int, ...]) -> Tuple[int, ...]:
    return tuple(np.broadcast_shapes(a, b))


class _BinaryOp(Op):
    _fn_name = ""

    def infer_output_shapes(self, input_shapes):
        a, b = input_shapes
        return [_broadcast_shape(a, b)]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        a, b = inputs
        fn = getattr(jnp, self._fn_name)
        return [fn(a, b)]

    def can_inplace_output(self):
        return True


@register_op(OperatorType.OP_EW_ADD)
class AddOp(_BinaryOp):
    _fn_name = "add"


@register_op(OperatorType.OP_EW_SUB)
class SubOp(_BinaryOp):
    _fn_name = "subtract"


@register_op(OperatorType.OP_EW_MUL)
class MulOp(_BinaryOp):
    _fn_name = "multiply"


@register_op(OperatorType.OP_EW_DIV)
class DivOp(_BinaryOp):
    _fn_name = "divide"


@register_op(OperatorType.OP_EW_MAX)
class MaxOp(_BinaryOp):
    _fn_name = "maximum"


@register_op(OperatorType.OP_EW_MIN)
class MinOp(_BinaryOp):
    _fn_name = "minimum"


class _UnaryOp(Op):
    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def _apply(self, x):
        raise NotImplementedError

    def forward(self, params, inputs, ctx: OpContext):
        return [self._apply(inputs[0])]

    def can_inplace_output(self):
        return True


def _make_unary(op_type: OperatorType, fn_src: str, name: str):
    """fn_src: 'jnn.<f>' or 'jnp.<f>'."""

    @register_op(op_type)
    class _U(_UnaryOp):
        def _apply(self, x):
            import jax.numpy as jnp
            import jax.nn as jnn

            mod, f = fn_src.split(".")
            return getattr({"jnp": jnp, "jnn": jnn}[mod], f)(x)

    _U.__name__ = name
    return _U


ReluOp = _make_unary(OperatorType.OP_RELU, "jnn.relu", "ReluOp")
SigmoidOp = _make_unary(OperatorType.OP_SIGMOID, "jnn.sigmoid", "SigmoidOp")
TanhOp = _make_unary(OperatorType.OP_TANH, "jnp.tanh", "TanhOp")
EluOp = _make_unary(OperatorType.OP_ELU, "jnn.elu", "EluOp")
GeluOp = _make_unary(OperatorType.OP_GELU, "jnn.gelu", "GeluOp")
ExpOp = _make_unary(OperatorType.OP_EXP, "jnp.exp", "ExpOp")
LogOp = _make_unary(OperatorType.OP_LOG, "jnp.log", "LogOp")
SinOp = _make_unary(OperatorType.OP_SIN, "jnp.sin", "SinOp")
CosOp = _make_unary(OperatorType.OP_COS, "jnp.cos", "CosOp")
SqrtOp = _make_unary(OperatorType.OP_SQRT, "jnp.sqrt", "SqrtOp")
CeilOp = _make_unary(OperatorType.OP_CEIL, "jnp.ceil", "CeilOp")
RoundOp = _make_unary(OperatorType.OP_ROUND, "jnp.round", "RoundOp")


@register_op(OperatorType.OP_IDENTITY)
class IdentityOp(_UnaryOp):
    def _apply(self, x):
        return x


@register_op(OperatorType.OP_RSQRT)
class RsqrtOp(_UnaryOp):
    def _apply(self, x):
        import jax.lax as lax

        return lax.rsqrt(x)


@register_op(OperatorType.OP_POW)
class PowOp(_UnaryOp):
    def _apply(self, x):
        import jax.numpy as jnp

        return jnp.power(x, self.attrs["exponent"])


@register_op(OperatorType.OP_SCALAR_MULTIPLY)
class ScalarMultiplyOp(_UnaryOp):
    def _apply(self, x):
        return x * self.attrs["scalar"]


@register_op(OperatorType.OP_SCALAR_ADD)
class ScalarAddOp(_UnaryOp):
    def _apply(self, x):
        return x + self.attrs["scalar"]


@register_op(OperatorType.OP_SCALAR_SUB)
class ScalarSubOp(_UnaryOp):
    def _apply(self, x):
        return x - self.attrs["scalar"]


@register_op(OperatorType.OP_SCALAR_TRUE_DIV)
class ScalarTrueDivOp(_UnaryOp):
    def _apply(self, x):
        return x / self.attrs["scalar"]


@register_op(OperatorType.OP_CAST)
class CastOp(Op):
    """reference: src/ops/cast.cc."""

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def output_dtype(self, input_dtypes):
        return self.attrs["target_dtype"]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0].astype(dtype_to_jnp(self.attrs["target_dtype"]))]


@register_op(OperatorType.OP_DROPOUT)
class DropoutOp(Op):
    """reference: src/ops/dropout.cc (cuDNN dropout state -> jax.random here)."""

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        import jax

        (x,) = inputs
        rate = float(self.attrs.get("rate", 0.5))
        if not ctx.training or rate <= 0.0:
            return [x]
        keep = 1.0 - rate
        mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
        return [(x * mask) / keep]
