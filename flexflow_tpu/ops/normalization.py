"""LayerNorm, RMSNorm, Softmax.

Reference: src/ops/layer_norm.cc (601 LoC, custom kernels), softmax.cc (cuDNN).
RMSNorm is a TPU-native extension (no reference analog; standard for LLM
parity). XLA fuses these; a Pallas fused-softmax lives in kernels/ for the
attention path.
"""
from __future__ import annotations

from ..ffconst import OperatorType
from .base import Op, OpContext, register_op


@register_op(OperatorType.OP_LAYERNORM)
class LayerNormOp(Op):
    """attrs: axes (list of ints), elementwise_affine, eps
    (reference builder: FFModel::layer_norm, src/ops/layer_norm.cc)."""

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def _norm_shape(self, ishape):
        axes = [a % len(ishape) for a in self.attrs.get("axes", [len(ishape) - 1])]
        return tuple(ishape[a] for a in sorted(axes))

    def weight_specs(self, input_shapes):
        from ..execution.initializers import ConstantInitializer, ZeroInitializer

        if not self.attrs.get("elementwise_affine", True):
            return {}
        nshape = self._norm_shape(input_shapes[0])
        return {
            "scale": (nshape, self.data_type, ConstantInitializer(1.0)),
            "bias": (nshape, self.data_type, ZeroInitializer()),
        }

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        ndim = x.ndim
        axes = tuple(sorted(a % ndim for a in self.attrs.get("axes", [ndim - 1])))
        eps = self.attrs.get("eps", 1e-5)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = (xf - mean) / jnp.sqrt(var + eps)
        if "scale" in params:
            bshape = [x.shape[a] if a in axes else 1 for a in range(ndim)]
            y = y * params["scale"].reshape(bshape) + params["bias"].reshape(bshape)
        return [y.astype(x.dtype)]


@register_op(OperatorType.OP_RMSNORM)
class RMSNormOp(Op):
    """attrs: axes, eps. TPU-native extension for LLM blocks."""

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import ConstantInitializer

        ishape = input_shapes[0]
        axes = [a % len(ishape) for a in self.attrs.get("axes", [len(ishape) - 1])]
        nshape = tuple(ishape[a] for a in sorted(axes))
        return {"scale": (nshape, self.data_type, ConstantInitializer(1.0))}

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (x,) = inputs
        ndim = x.ndim
        axes = tuple(sorted(a % ndim for a in self.attrs.get("axes", [ndim - 1])))
        eps = self.attrs.get("eps", 1e-6)
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=axes, keepdims=True)
        y = xf / jnp.sqrt(ms + eps)
        bshape = [x.shape[a] if a in axes else 1 for a in range(ndim)]
        return [(y * params["scale"].reshape(bshape)).astype(x.dtype)]


@register_op(OperatorType.OP_SOFTMAX)
class SoftmaxOp(Op):
    """attrs: axis (reference: src/ops/softmax.cc; -1 default like
    FFModel::softmax), use_pallas (opt-in: route MXU-aligned last-dim rows
    through the Pallas row-softmax kernel, kernels/softmax.py — the cuDNN
    softmax analog; XLA's fusion measured at parity on v5e, so the default
    path stays jax.nn.softmax)."""

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        import jax.nn as jnn

        (x,) = inputs
        axis = self.attrs.get("axis", -1)
        from ..kernels.softmax import (pallas_softmax,
                                       should_use_pallas_softmax)

        if should_use_pallas_softmax(
                x, axis, opt_in=bool(self.attrs.get("use_pallas"))):
            return [pallas_softmax(x)]
        return [jnn.softmax(x, axis=axis)]
