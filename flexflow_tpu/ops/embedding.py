"""Embedding lookup with sum/avg/none aggregation.

Reference: src/ops/embedding.cc (1205 LoC) + kernels/embedding_kernels.cu —
DLRM's key op, table-sharded for parameter parallelism. TPU-native: jnp.take
(XLA gather, which the SPMD partitioner turns into a sharded gather +
collective when the table dim is sharded over the mesh).
"""
from __future__ import annotations

from ..ffconst import AggrMode, OperatorType
from .base import Op, OpContext, register_op


@register_op(OperatorType.OP_EMBEDDING)
class EmbeddingOp(Op):
    """attrs: num_entries, out_dim, aggr (AggrMode), kernel_initializer.

    input: int ids of shape (batch,) or (batch, bag); output:
    (batch, out_dim) for SUM/AVG aggregation over the bag dim, or
    (batch, bag, out_dim) for AGGR_MODE_NONE (reference: embedding.cc,
    AggrMode at ffconst.h:18).
    """

    def infer_output_shapes(self, input_shapes):
        s = input_shapes[0]
        aggr = self.attrs.get("aggr", AggrMode.AGGR_MODE_NONE)
        if aggr == AggrMode.AGGR_MODE_NONE:
            return [tuple(s) + (self.attrs["out_dim"],)]
        return [(s[0], self.attrs["out_dim"])]

    def output_dtype(self, input_dtypes):
        return self.data_type

    def weight_specs(self, input_shapes):
        from ..execution.initializers import NormInitializer

        return {
            "weight": ((self.attrs["num_entries"], self.attrs["out_dim"]),
                       self.data_type,
                       self.attrs.get("kernel_initializer") or NormInitializer(
                           stddev=0.05)),
        }

    def forward(self, params, inputs, ctx: OpContext):
        import jax.numpy as jnp

        (ids,) = inputs
        table = params["weight"]
        out = jnp.take(table, ids.astype(jnp.int32), axis=0)
        aggr = self.attrs.get("aggr", AggrMode.AGGR_MODE_NONE)
        if aggr == AggrMode.AGGR_MODE_SUM:
            out = jnp.sum(out, axis=1)
        elif aggr == AggrMode.AGGR_MODE_AVG:
            out = jnp.mean(out, axis=1)
        return [out]

    def parallelizable_dims(self, input_shapes):
        return {
            "batch": True,
            # table (parameter) parallelism: shard the vocab dim of the weight;
            # XLA handles the masked-gather + psum (reference: DLRM strategies)
            "channel_out": {"output_dim": -1, "weights": {"weight": 1}},
            "table": {"weights": {"weight": 0}, "reduces_output": True},
        }
