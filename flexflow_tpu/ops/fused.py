"""Operator fusion: FusedOp regions + the apply_fusion compile pass.

Reference: ``FFModel::apply_fusion`` (src/runtime/model.cc:2495) merges
consecutive ops with the same MachineView into one ``FusedOp`` leaf task
(src/ops/fused.cc:117) whose forward is an interpreter dispatching over
sub-op types (src/ops/fused.cu:~70-500) — the win there is cutting Legion
per-task launch overhead.

TPU-native: XLA already fuses elementwise chains into matmuls, so there is no
launch overhead to cut. The region concept is kept because (a) it is part of
the reference surface (``--fusion`` flag, config.h:133), and (b) the cost
model benefits from region granularity — a fused region is costed as one
roofline evaluation over the summed FLOPs/bytes instead of per-op memory
round-trips, matching what XLA actually emits.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..ffconst import DataType, OperatorType
from .base import Op, OpContext, register_op

# wiring entry: ("ext", input_idx, 0) region input | ("sub", pos, out_idx)
WireT = Tuple[str, int, int]


@register_op(OperatorType.OP_FUSED)
class FusedOp(Op):
    """A region of sub-ops executed as one node.

    attrs:
      sub_ops:  List[Op] in execution order
      wiring:   List[List[WireT]] — per sub-op, where each input comes from
    """

    def __init__(self, name: str, attrs: Dict[str, Any], dtype: DataType,
                 num_inputs: int = 1):
        super().__init__(name, attrs, dtype, num_inputs)
        self.sub_ops: List[Op] = list(attrs["sub_ops"])
        self.wiring: List[List[WireT]] = [list(w) for w in attrs["wiring"]]

    # one weight namespace per sub-op position (reference: FusedOp aggregates
    # sub-op weights into its own region list, fused.cc:117)
    @staticmethod
    def _prefix(i: int, sub: Op) -> str:
        return f"sub{i}:{sub.name}:"

    def params_key(self) -> Tuple:
        return (self.op_type, self.data_type,
                tuple(sub.params_key() for sub in self.sub_ops),
                tuple(tuple(w) for ws in self.wiring for w in ws))

    # -- shape plumbing through the region --------------------------------------
    def _sub_in_shapes(self, input_shapes, sub_out_shapes, i):
        out = []
        for kind, j, k in self.wiring[i]:
            out.append(input_shapes[j] if kind == "ext"
                       else sub_out_shapes[j][k])
        return out

    def _trace_shapes(self, input_shapes):
        sub_out_shapes: List[List[Tuple[int, ...]]] = []
        for i, sub in enumerate(self.sub_ops):
            ins = self._sub_in_shapes(input_shapes, sub_out_shapes, i)
            sub_out_shapes.append(
                [tuple(s) for s in sub.infer_output_shapes(ins)])
        return sub_out_shapes

    def infer_output_shapes(self, input_shapes):
        return self._trace_shapes(input_shapes)[-1]

    def output_dtype(self, input_dtypes):
        return self.sub_ops[-1].data_type

    def weight_specs(self, input_shapes):
        sub_out_shapes = self._trace_shapes(input_shapes)
        specs = {}
        for i, sub in enumerate(self.sub_ops):
            ins = self._sub_in_shapes(input_shapes, sub_out_shapes, i)
            for wname, spec in sub.weight_specs(ins).items():
                specs[self._prefix(i, sub) + wname] = spec
        return specs

    def forward(self, params, inputs, ctx: OpContext):
        import jax

        sub_outs: List[List[Any]] = []
        for i, sub in enumerate(self.sub_ops):
            ins = [inputs[j] if kind == "ext" else sub_outs[j][k]
                   for kind, j, k in self.wiring[i]]
            pfx = self._prefix(i, sub)
            sub_params = {k[len(pfx):]: v for k, v in params.items()
                          if k.startswith(pfx)}
            sub_ctx = OpContext(
                training=ctx.training,
                rng=(jax.random.fold_in(ctx.rng, i)
                     if ctx.rng is not None else None),
                seq_length=ctx.seq_length, mesh=ctx.mesh,
                profiling=ctx.profiling, aux_losses=ctx.aux_losses,
                cache_in=ctx.cache_in, cache_out=ctx.cache_out,
                serving=ctx.serving)
            # sub-op named scope: xprof attributes work inside the region
            # to the member ops, not just the FusedOp node
            with jax.named_scope(sub.name):
                sub_outs.append(sub.forward(sub_params, ins, sub_ctx))
        return sub_outs[-1]

    # -- cost model: one roofline over the region --------------------------------
    def flops(self, input_shapes, output_shapes):
        sub_out_shapes = self._trace_shapes(input_shapes)
        total = 0
        for i, sub in enumerate(self.sub_ops):
            ins = self._sub_in_shapes(input_shapes, sub_out_shapes, i)
            total += sub.flops(ins, sub_out_shapes[i])
        return total

    def memory_bytes(self, input_shapes, output_shapes):
        # region boundary traffic only — intermediates stay in registers/VMEM
        # (this is exactly the fusion win the cost model should see)
        from ..ffconst import size_of_datatype

        el = size_of_datatype(self.data_type)
        return el * (sum(int(np.prod(s)) for s in input_shapes)
                     + sum(int(np.prod(s)) for s in output_shapes))


# ------------------------------------------------------------------ the pass
_FUSE_EXCLUDED = {
    OperatorType.OP_INPUT, OperatorType.OP_WEIGHT, OperatorType.OP_FUSED,
    OperatorType.OP_CACHE,  # stateful across iterations
    OperatorType.OP_REPARTITION, OperatorType.OP_COMBINE,
    OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION,
    OperatorType.OP_FUSED_PARALLEL, OperatorType.OP_PIPELINE,
    OperatorType.OP_ALLTOALL,
}


def _eligible(node, strategy) -> bool:
    if node.op.op_type in _FUSE_EXCLUDED:
        return False
    if len(node.out_shapes) != 1:
        return False
    ns = strategy.node_strategies.get(node.guid) if strategy else None
    # only fuse nodes the strategy doesn't pin (no sharded weights, no output
    # constraint) — the reference requires identical MachineViews
    # (model.cc:2970); unpinned nodes all share the default view
    if ns is not None and (ns.weight_specs or ns.output_spec is not None
                           or ns.extra):
        return False
    return True


def apply_fusion(pcg, strategy=None, max_region: int = 16,
                 barrier_guids=()):
    """Merge single-consumer chains of same-view ops into FusedOp nodes.

    Returns (new_pcg, n_fused_regions, remap) where remap maps old guid ->
    (new guid, out idx) — out idx -1 meaning "original indices preserved".
    ``strategy`` (if given) is updated in place: chain members' entries are
    dropped (they had none of interest — _eligible guarantees it).
    ``barrier_guids``: nodes whose outputs must stay addressable (e.g. the
    compile final anchor) — a chain never extends past them, so they end up
    either unfused or as a region tail (whose output is the FusedOp's).

    Reference: FFModel::apply_fusion loop (model.cc:2965-3040).
    """
    from ..parallel.pcg import PCG, PCGNode, _node_guid

    barriers = set(barrier_guids)
    consumers: Dict[int, List[int]] = {}
    for n in pcg.topo_order():
        for g, _ in n.inputs:
            consumers.setdefault(g, []).append(n.guid)

    # build chains greedily along sole-consumer edges
    in_chain: Dict[int, int] = {}  # guid -> chain id
    chains: List[List[int]] = []
    for node in pcg.topo_order():
        if node.guid in in_chain or not _eligible(node, strategy):
            continue
        chain = [node.guid]
        cur = node
        while len(chain) < max_region and cur.guid not in barriers:
            cons = consumers.get(cur.guid, [])
            if len(cons) != 1:
                break
            nxt = pcg.nodes[cons[0]]
            # `nxt` must consume cur exactly once and be eligible
            if not _eligible(nxt, strategy) or nxt.guid in in_chain:
                break
            if sum(1 for g, _ in nxt.inputs if g == cur.guid) != 1:
                break
            chain.append(nxt.guid)
            cur = nxt
        if len(chain) >= 2:
            cid = len(chains)
            chains.append(chain)
            for g in chain:
                in_chain[g] = cid

    if not chains:
        return pcg, 0, {g: (g, -1) for g in pcg.nodes}

    # rebuild the graph, replacing each chain with one FusedOp node
    new = PCG()
    remap: Dict[int, Tuple[int, int]] = {}  # old guid -> (new guid, out idx)
    for node in pcg.topo_order():
        cid = in_chain.get(node.guid)
        if cid is None:
            # non-fused producers keep their output indices (-1 marker);
            # fused producers collapse to output 0
            nn = PCGNode(guid=node.guid, op=node.op,
                         inputs=[(remap[g][0],
                                  i if remap[g][1] < 0 else remap[g][1])
                                 for g, i in node.inputs],
                         out_shapes=list(node.out_shapes),
                         out_dtypes=list(node.out_dtypes),
                         machine_view=node.machine_view)
            new.nodes[nn.guid] = nn
            new._order.append(nn.guid)
            remap[node.guid] = (node.guid, -1)  # -1: keep original out idx
            continue
        chain = chains[cid]
        if node.guid != chain[-1]:
            # emit the region at its LAST member: every external producer of
            # every member is topologically earlier, so remap is complete
            continue
        members = [pcg.nodes[g] for g in chain]
        member_pos = {g: i for i, g in enumerate(chain)}
        ext_inputs: List[Tuple[int, int]] = []  # (old guid, out idx)
        ext_index: Dict[Tuple[int, int], int] = {}
        wiring: List[List[WireT]] = []
        for m in members:
            ws: List[WireT] = []
            for g, i in m.inputs:
                if g in member_pos:
                    ws.append(("sub", member_pos[g], i))
                else:
                    key = (g, i)
                    if key not in ext_index:
                        ext_index[key] = len(ext_inputs)
                        ext_inputs.append(key)
                    ws.append(("ext", ext_index[key], 0))
            wiring.append(ws)
        tail = members[-1]
        fused = FusedOp(
            name="fused_" + "+".join(m.name for m in members),
            attrs={"sub_ops": [m.op for m in members], "wiring": wiring},
            dtype=tail.op.data_type, num_inputs=len(ext_inputs))
        guid = next(_node_guid)
        nn = PCGNode(
            guid=guid, op=fused,
            inputs=[(remap[g][0], i if remap[g][1] < 0 else remap[g][1])
                    for g, i in ext_inputs],
            out_shapes=list(tail.out_shapes),
            out_dtypes=list(tail.out_dtypes),
            machine_view=tail.machine_view)
        new.nodes[guid] = nn
        new._order.append(guid)
        for g in chain:
            remap[g] = (guid, 0)
        if strategy is not None:
            for g in chain:
                strategy.node_strategies.pop(g, None)
    return new, len(chains), remap
