"""Recurrent ops: LSTM.

The reference ships LSTM only as hand-written CUDA in the legacy NMT app
(nmt/lstm.cu — cuDNN RNN descriptors over LSTM_PER_NODE_LENGTH=10 chunks,
nmt/rnn.h:242) that predates the FFModel op set. Here LSTM is a first-class
op, TPU-native: one fused gate matmul per step under ``lax.scan`` — the
(batch, 4*hidden) GEMM rides the MXU, scan keeps the trace size constant
regardless of sequence length, and the op is differentiable through scan for
free (the reference hand-writes the backward pass in lstm.cu).

Layout: input (batch, seq, in_dim) -> outputs (batch, seq, hidden).
Optional second input: initial state (batch, 2*hidden) = [h, c] concatenated
(how an NMT decoder receives the encoder's final state).
Outputs: [sequence_outputs, final_state(batch, 2*hidden)].
"""
from __future__ import annotations

from ..ffconst import OperatorType
from .base import Op, OpContext, register_op


@register_op(OperatorType.OP_LSTM)
class LSTMOp(Op):
    """attrs: hidden_size; optional 2nd input = initial [h, c]."""

    def infer_output_shapes(self, input_shapes):
        b, s, _ = input_shapes[0]
        h = self.attrs["hidden_size"]
        return [(b, s, h), (b, 2 * h)]

    def weight_specs(self, input_shapes):
        from ..execution.initializers import (GlorotUniformInitializer,
                                              ZeroInitializer)

        in_dim = input_shapes[0][-1]
        h = self.attrs["hidden_size"]
        glorot = GlorotUniformInitializer()
        zero = ZeroInitializer()
        return {
            "wx": ((in_dim, 4 * h), self.data_type, glorot),
            "wh": ((h, 4 * h), self.data_type, glorot),
            "bias": ((4 * h,), self.data_type, zero),
        }

    def forward(self, params, inputs, ctx: OpContext):
        import jax.lax as lax
        import jax.numpy as jnp

        x = inputs[0]  # (b, s, d)
        b = x.shape[0]
        h = self.attrs["hidden_size"]
        sv = ctx.serving  # serving engine prefill/decode (ISSUE 6)
        if sv is not None and sv.mode == "chunk":
            # chunked/prefix-cached prefill (ISSUE 14) is an
            # attention-only feature: the LSTM carry is a summary, not
            # per-token pool rows — there is no block to share or chunk.
            # The engine disables the prefix cache and refuses
            # --prefill-chunk-tokens for LSTM graphs at construction;
            # this raise is the defense-in-depth backstop.
            raise NotImplementedError(
                f"{self.name}: chunked/prefix-cached prefill supports "
                "attention-only stateful graphs; LSTM recurrence has no "
                "chunk path (serve without --prefill-chunk-tokens and "
                "with --prefix-cache off)")
        if sv is not None and sv.mode == "decode" and sv.cache_in is not None \
                and self.name in sv.cache_in:
            # the LSTM's recurrent carry IS its decode state: resume from
            # the cached [h, c] (which already folds any graph-provided
            # initial_state through the prefill scan)
            state = sv.cache_in[self.name]
            h0, c0 = state[:, :h], state[:, h:]
        elif len(inputs) > 1:
            h0, c0 = inputs[1][:, :h], inputs[1][:, h:]
        else:
            h0 = jnp.zeros((b, h), x.dtype)
            c0 = jnp.zeros((b, h), x.dtype)
        wx, wh, bias = params["wx"], params["wh"], params["bias"]

        # precompute input projections for ALL steps in one big MXU-friendly
        # GEMM: (b*s, d) @ (d, 4h); the scan then only does the (b,h)@(h,4h)
        # recurrent matmul per step
        xproj = jnp.einsum("bsd,dg->bsg", x, wx) + bias

        from jax.nn import sigmoid

        def step(carry, xp_t):
            h_t, c_t = carry
            gates = xp_t + h_t @ wh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_n = sigmoid(f) * c_t + sigmoid(i) * jnp.tanh(g)
            h_n = sigmoid(o) * jnp.tanh(c_n)
            return (h_n, c_n), (h_n, c_n)

        (h_f, c_f), (ys, cs) = lax.scan(step, (h0, c0),
                                        jnp.swapaxes(xproj, 0, 1))
        outputs = jnp.swapaxes(ys, 0, 1)  # (b, s, h)
        final_state = jnp.concatenate([h_f, c_f], axis=-1)
        if sv is not None:
            if sv.mode == "prefill" and sv.lengths is not None:
                # right-padded prompt: the carry to hand decode is the state
                # at the LAST REAL token (length-1), not at the padded tail
                # the scan kept marching through
                states = jnp.concatenate(
                    [jnp.swapaxes(ys, 0, 1), jnp.swapaxes(cs, 0, 1)],
                    axis=-1)  # (b, s, 2h)
                idx = jnp.clip(sv.lengths - 1, 0, states.shape[1] - 1)
                sv.cache_out[self.name] = jnp.take_along_axis(
                    states, idx[:, None, None], axis=1)[:, 0]
            else:
                sv.cache_out[self.name] = final_state
        return [outputs, final_state]

    def flops(self, input_shapes, output_shapes):
        b, s, d = input_shapes[0]
        h = self.attrs["hidden_size"]
        # per step: x@wx (shared precompute) + h@wh, 4 gates
        return 2 * b * s * (d * 4 * h + h * 4 * h)

    def parallelizable_dims(self, input_shapes):
        return {"batch": True}
