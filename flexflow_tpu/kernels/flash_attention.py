"""Pallas flash attention for TPU.

Replaces the reference's cuDNN ``cudnnMultiHeadAttnForward`` core
(src/ops/attention.cu:35-128) with a blockwise online-softmax kernel that never
materializes the (seq_q, seq_k) score matrix in HBM — the standard
FlashAttention recipe tiled for the MXU (128-aligned blocks) with VMEM
accumulators. Backward uses the recompute trick via ``jax.custom_vjp``: the
residuals are only (out, logsumexp), so long sequences fit in HBM.

Falls back transparently to the einsum core off-TPU (interpret mode is used in
tests)."""
from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                      seq_k: int, causal: bool, sm_scale: float):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q = q_ref[...].astype(jnp.float32) * sm_scale  # (block_q, d)
    block_q = q.shape[0]
    q_idx = pl.program_id(1)

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only key blocks up to the diagonal contribute
        last_kb = ((q_idx + 1) * block_q + block_k - 1) // block_k
        num_kb_eff = jnp.minimum(num_kb, last_kb)
        m, l, acc = jax.lax.fori_loop(0, num_kb_eff, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    sm_scale = 1.0 / np.sqrt(d)
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)

    qr = q.reshape(batch * heads, seq_q, d)
    kr = k.reshape(batch * heads, seq_k, d)
    vr = v.reshape(batch * heads, seq_k, d)

    grid = (batch * heads, seq_q // block_q)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               seq_k=seq_k, causal=causal, sm_scale=sm_scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((batch * heads, seq_q), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (out.reshape(batch, heads, seq_q, d),
            lse.reshape(batch, heads, seq_q))


def _reference_core(q, k, v, causal: bool):
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """q,k,v: (batch, heads, seq, head_dim) -> (batch, heads, seq_q, head_dim).

    seq_q/seq_k must be multiples of the block sizes (the attention op checks
    this before selecting the flash path, ops/attention.py)."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k,
                            _resolve_interpret(interpret))
    return out


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    import jax

    return jax.default_backend() != "tpu"


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              _resolve_interpret(interpret))
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, res, do):
    """Backward by recompute: with residuals (q,k,v,out,lse) the gradients are
    computed with the standard flash-attention backward identities; here we use
    jnp einsums (XLA tiles them) — a Pallas bwd kernel is a later optimization.
    """
    import jax
    import jax.numpy as jnp

    q, k, v, out, lse = res
    d = q.shape[-1]
    sm_scale = 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])  # exact softmax from stored lse
    do_f = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do_f)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do_f, v.astype(jnp.float32))
    delta = jnp.sum(do_f * out.astype(jnp.float32), axis=-1)  # (b,h,q)
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
