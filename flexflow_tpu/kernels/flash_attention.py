"""Pallas flash attention for TPU.

Replaces the reference's cuDNN ``cudnnMultiHeadAttnForward`` core
(src/ops/attention.cu:35-128) with a blockwise online-softmax kernel that never
materializes the (seq_q, seq_k) score matrix in HBM — the standard
FlashAttention recipe tiled for the MXU (128-aligned blocks) with VMEM
accumulators. Backward uses the recompute trick via ``jax.custom_vjp``: the
residuals are only (out, logsumexp), so long sequences fit in HBM.

Falls back transparently to the einsum core off-TPU (interpret mode is used in
tests)."""
from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def dropout_keep_scale(seed, bh, q_start, k_start, block_q, block_k,
                       rate: float):
    """Counter-based dropout mask for one (block_q, block_k) score tile:
    {0, 1/(1-rate)} as f32, a pure function of the GLOBAL (seed, batch*head,
    q_pos, k_pos) coordinates — so the forward kernel and both backward
    kernels regenerate the SAME mask regardless of block decomposition
    (reference analog: cuDNN's dropout descriptor inside the fused MHA,
    src/ops/attention.cu:225). One murmur3-finalizer round per element over
    a linear counter; plain uint32 ops, so it runs identically compiled on
    TPU and in interpret mode."""
    import jax
    import jax.numpy as jnp

    qpos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return dropout_keep_scale_nd(seed, jnp.asarray(bh, jnp.uint32),
                                 qpos, kpos, rate)


def dropout_keep_scale_nd(seed, bh, q_pos, k_pos, rate: float):
    """Vectorized twin of ``dropout_keep_scale`` for the non-Pallas paths
    (ring/Ulysses sequence parallelism): ``bh``/``q_pos``/``k_pos`` are
    broadcastable uint32 arrays of GLOBAL coordinates, so every chip of an
    SP group draws decorrelated masks from the same counter stream."""
    import jax.numpy as jnp

    x = (q_pos.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         + k_pos.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
         + bh.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
         + jnp.asarray(seed, jnp.uint32))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    threshold = jnp.uint32(min(int(rate * 2 ** 32), 2 ** 32 - 1))
    return (x >= threshold).astype(jnp.float32) / (1.0 - rate)


def coerce_dropout_seed(name: str, dropout: float, seed):
    """Shared validation + uint32 coercion for every dropout entry point
    (flash / ring / Ulysses) so the contract cannot drift."""
    import jax.numpy as jnp

    if not 0.0 <= float(dropout) < 1.0:
        raise ValueError(f"{name} dropout must be in [0, 1), got {dropout}")
    if dropout > 0.0 and seed is None:
        raise ValueError(f"{name} dropout requires a seed")
    return jnp.asarray(seed if seed is not None else 0, jnp.uint32)


def global_bh_indices(b_local: int, total_heads: int, h_local: int,
                      b_base, h_base):
    """(b_local, h_local) uint32 grid of GLOBAL batch*head indices for the
    dropout counter stream — one implementation shared by ring and Ulysses
    so their masks stay on the same stream as the flash kernel's."""
    import jax.numpy as jnp

    return ((b_base + jnp.arange(b_local))[:, None] * total_heads
            + h_base + jnp.arange(h_local)[None, :]).astype(jnp.uint32)


def _apply_causal_mask(s, q_start, k_start, offset, block_q, block_k):
    """Causal mask for one (block_q, block_k) score tile. ``offset`` aligns
    rectangular shapes the same way the einsum core's ``tril(k=sk-sq)`` does:
    query i attends keys j with j <= i + offset."""
    import jax
    import jax.numpy as jnp

    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1)
    return jnp.where(q_pos + offset >= k_pos, s, NEG_INF)


def _causal_num_kb(q_idx, block_q, block_k, num_kb, offset):
    """Number of leading key blocks that contribute to query block q_idx."""
    import jax.numpy as jnp

    last = ((q_idx + 1) * block_q + offset + block_k - 1) // block_k
    return jnp.clip(last, 0, num_kb)


def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, seq_k: int, causal: bool,
                      sm_scale: float, causal_offset: int = 0,
                      dropout: float = 0.0, num_heads: int = 1):
    # 4D blocks with grid (batch, head, q_block): no (b*h) merge reshape at
    # the kernel boundary — the profiled layout copies it forced (~8% of a
    # BERT-Large step) disappear
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q = q_ref[0, 0]  # (block_q, d) — kept in input dtype: bf16 feeds the MXU
    block_q = q.shape[0]
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    q_idx = pl.program_id(2)

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T,
                    preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        if causal:
            s = _apply_causal_mask(s, q_idx * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        # softmax normalizer from UNDROPPED p: dropout applies to the
        # normalized probabilities, and elementwise mask/scale commutes
        # with the 1/l normalization
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if dropout > 0.0:
            p_acc = p * dropout_keep_scale(seed_ref[0], bh,
                                           q_idx * block_q, kb * block_k,
                                           block_q, block_k, dropout)
        else:
            p_acc = p
        acc_new = acc * alpha[:, None] + jnp.dot(
            p_acc.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only key blocks up to the (offset-shifted) diagonal contribute
        num_kb_eff = _causal_num_kb(q_idx, block_q, block_k, num_kb,
                                    causal_offset)
        m, l, acc = jax.lax.fori_loop(0, num_kb_eff, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse block is (block_q, 1): TPU tiling wants >=2-D blocks whose minor dim
    # matches the array (a bare (block_q,) slice of (b, h, seq) is rejected)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, None].astype(lse_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool, dropout: float = 0.0, seed=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    sm_scale = 1.0 / np.sqrt(d)
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    seed_arr = jnp.reshape(jnp.asarray(
        seed if seed is not None else 0, jnp.uint32), (1,))

    grid = (batch, heads, seq_q // block_q)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               seq_k=seq_k, causal=causal, sm_scale=sm_scale,
                               causal_offset=seq_k - seq_q, dropout=dropout,
                               num_heads=heads)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, h, i: (0,)),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, seq_k, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, seq_k, d), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, q, k, v)
    return out, lse.reshape(batch, heads, seq_q)


def _flash_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, *, block_q: int,
                          seq_q: int, causal: bool, sm_scale: float,
                          causal_offset: int = 0, dropout: float = 0.0,
                          num_heads: int = 1):
    """Grid (batch, heads, seq_k//block_k): one (dk, dv) tile per k block,
    streaming q/do/lse/delta blocks — the FlashAttention-2 backward split.

    With dropout (mask D regenerated from the same counters as forward):
    dV = (P∘D)ᵀ dO and dS = P ∘ (D∘dP - δ) — δ = rowsum(dO∘O) already
    equals rowsum(P∘D ∘ dP), so the softmax-backward identity holds with
    the dropped probabilities folded in."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    k = k_ref[0, 0]  # (block_k, d)
    v = v_ref[0, 0]
    block_k = k.shape[0]
    d = k.shape[1]
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    kb = pl.program_id(2)

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)
    num_qb = seq_q // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q), :]  # (bq, 1) f32
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qb * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        p = jnp.exp(s - lse)  # exact softmax probabilities from stored lse
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = dropout_keep_scale(seed_ref[0], bh, qb * block_q,
                                      kb * block_k, block_q, block_k,
                                      dropout)
            pd = p * keep
            dp = dp * keep
        else:
            pd = p
        dv = dv + jnp.dot(pd.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # first q block with any q_pos + offset >= kb*block_k
        qb_start = jnp.maximum(kb * block_k - causal_offset, 0) // block_q
    else:
        qb_start = 0
    dk, dv = jax.lax.fori_loop(qb_start, num_qb, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, *, block_k: int, seq_k: int,
                         causal: bool, sm_scale: float,
                         causal_offset: int = 0, dropout: float = 0.0,
                         num_heads: int = 1):
    """Grid (batch, heads, seq_q//block_q): one dq tile per q block."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q = q_ref[0, 0]  # (block_q, d)
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]  # (block_q, 1)
    delta = delta_ref[0, 0]
    block_q = q.shape[0]
    d = q.shape[1]
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    qb = pl.program_id(2)

    dq = jnp.zeros((block_q, d), jnp.float32)
    num_kb = seq_k // block_k

    def body(kb, dq):
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qb * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp = dp * dropout_keep_scale(seed_ref[0], bh, qb * block_q,
                                         kb * block_k, block_q, block_k,
                                         dropout)
        ds = p * (dp - delta) * sm_scale
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    if causal:
        num_kb_eff = _causal_num_kb(qb, block_q, block_k, num_kb,
                                    causal_offset)
        dq = jax.lax.fori_loop(0, num_kb_eff, body, dq)
    else:
        dq = jax.lax.fori_loop(0, num_kb, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _flash_backward(q, k, v, out, lse, do, causal: bool, block_q: int,
                    block_k: int, interpret: bool, dropout: float = 0.0,
                    seed=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    sm_scale = 1.0 / np.sqrt(d)
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)

    dor = do.astype(q.dtype)
    lser = lse.reshape(batch, heads, seq_q, 1)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    seed_arr = jnp.reshape(jnp.asarray(
        seed if seed is not None else 0, jnp.uint32), (1,))

    seed_spec = pl.BlockSpec((1,), lambda b, h, i: (0,))
    full_q = pl.BlockSpec((1, 1, seq_q, d), lambda b, h, i: (b, h, 0, 0))
    full_q1 = pl.BlockSpec((1, 1, seq_q, 1), lambda b, h, i: (b, h, 0, 0))
    full_k = pl.BlockSpec((1, 1, seq_k, d), lambda b, h, i: (b, h, 0, 0))
    tile_q = pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0))
    tile_q1 = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0))
    tile_k = pl.BlockSpec((1, 1, block_k, d), lambda b, h, i: (b, h, i, 0))

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_q=block_q, seq_q=seq_q, causal=causal,
        sm_scale=sm_scale, causal_offset=seq_k - seq_q, dropout=dropout,
        num_heads=heads)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(batch, heads, seq_k // block_k),
        in_specs=[seed_spec, full_q, tile_k, tile_k, full_q, full_q1,
                  full_q1],
        out_specs=[tile_k, tile_k],
        out_shape=[jax.ShapeDtypeStruct((batch, heads, seq_k, d), k.dtype),
                   jax.ShapeDtypeStruct((batch, heads, seq_k, d), v.dtype)],
        interpret=interpret,
    )(seed_arr, q, k, v, dor, lser, delta)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_k=block_k, seq_k=seq_k, causal=causal,
        sm_scale=sm_scale, causal_offset=seq_k - seq_q, dropout=dropout,
        num_heads=heads)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(batch, heads, seq_q // block_q),
        in_specs=[seed_spec, tile_q, full_k, full_k, tile_q, tile_q1,
                  tile_q1],
        out_specs=tile_q,
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq_q, d), q.dtype),
        interpret=interpret,
    )(seed_arr, q, k, v, dor, lser, delta)

    return dq, dk, dv


def _reference_core(q, k, v, causal: bool):
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_p(q, k, v, seed, causal, block_q, block_k, interpret,
                       dropout):
    _check_causal_shape(q, k, causal)
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k,
                            _resolve_interpret(interpret),
                            dropout=dropout, seed=seed)
    return out


def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None,
                    dropout: float = 0.0, seed=None):
    """q,k,v: (batch, heads, seq, head_dim) -> (batch, heads, seq_q, head_dim).

    seq_q/seq_k must be multiples of the block sizes (the attention op checks
    this before selecting the flash path, ops/attention.py). Causal requires
    seq_q <= seq_k: with more queries than keys the leading queries attend an
    empty window, which only the einsum core's degenerate uniform-softmax
    handles — use mha_core for that case.

    ``dropout``/``seed``: in-kernel attention-probability dropout via a
    counter-based PRNG on global (batch*head, q_pos, k_pos) coordinates, so
    forward and both backward kernels regenerate identical masks without
    materializing them in HBM (the cuDNN-MHA dropout analog,
    reference src/ops/attention.cu:225). ``seed`` is a traced uint32 scalar
    — reseed per step without recompiling."""
    dropout = float(dropout)
    seed = coerce_dropout_seed("flash_attention", dropout, seed)
    return _flash_attention_p(q, k, v, seed, causal, block_q, block_k,
                              interpret, dropout)


def _check_causal_shape(q, k, causal: bool) -> None:
    if causal and q.shape[-2] > k.shape[-2]:
        raise ValueError(
            f"flash_attention causal requires seq_q <= seq_k, got "
            f"{q.shape[-2]} > {k.shape[-2]}; use the einsum core instead")


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    import jax

    return jax.default_backend() != "tpu"


def _fwd(q, k, v, seed, causal, block_q, block_k, interpret, dropout):
    _check_causal_shape(q, k, causal)
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              _resolve_interpret(interpret),
                              dropout=dropout, seed=seed)
    return out, (q, k, v, seed, out, lse)


def _bwd(causal, block_q, block_k, interpret, dropout, res, do):
    """Backward by recompute (never materializes the score matrix): blockwise
    Pallas kernels using the flash-attention backward identities, with exact
    probabilities reconstructed from the stored logsumexp (and the dropout
    mask regenerated from the same counters)."""
    q, k, v, seed, out, lse = res
    dq, dk, dv = _flash_backward(q, k, v, out, lse, do, causal, block_q,
                                 block_k, _resolve_interpret(interpret),
                                 dropout=dropout, seed=seed)
    return dq, dk, dv, None


_flash_attention_p.defvjp(_fwd, _bwd)
