"""Pallas flash attention for TPU.

Replaces the reference's cuDNN ``cudnnMultiHeadAttnForward`` core
(src/ops/attention.cu:35-128) with a blockwise online-softmax kernel that never
materializes the (seq_q, seq_k) score matrix in HBM — the standard
FlashAttention recipe tiled for the MXU (128-aligned blocks) with VMEM
accumulators. Backward uses the recompute trick via ``jax.custom_vjp``: the
residuals are only (out, logsumexp), so long sequences fit in HBM.

Streaming grids (round 5): every kernel walks K/V (or Q) tiles through a
Pallas grid dimension instead of holding the full sequence resident in VMEM,
so per-program VMEM is O(block) — Pallas double-buffers the tile DMAs against
compute automatically and max sequence length is bounded by HBM, not VMEM.
Backward has two schedules:

- **fused one-pass** (``seq_q * d * 10 ≤ FUSED_BWD_RESIDENT_BUDGET``): grid
  over K/V tiles,
  Q/dO resident, dq accumulated in a (seq_q, d) f32 scratch. Computes the
  probabilities ONCE per (q, k) tile and reuses them for dq, dk and dv —
  vs. the two-pass schedule this halves the exp/VPU work and drops two of
  the six MXU passes (score + dO·Vᵀ recomputation).
- **two-pass streaming** (arbitrary seq): FlashAttention-2-style separate
  dkv and dq kernels, each O(block) VMEM, for sequences whose Q residency
  would not fit VMEM.

Numerics note: q is PRE-SCALED by 1/sqrt(d) outside the kernels (XLA fuses
the multiply into the producing projection). The fold is bit-exact in bf16
only when the scale is a power of two (d = 4^k, e.g. d=64/256); at d=128 and
d=192 — both admitted by the d % 64 == 0 flash gate — each q element takes
one extra bf16 rounding versus scaling the f32 score tile in-kernel. The
error is bounded by one bf16 ulp per element ahead of the f32 accumulation
and sits inside the parity tests' bf16 tolerances; see _flash_forward.

Falls back transparently to the einsum core off-TPU (interpret mode is used in
tests)."""
from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
# Fused-backward residency budget: Q/dO/O/dq-out (bf16) + dq scratch (f32)
# come to ~10*seq_q*d bytes; past this the schedule no longer fits the 16 MB
# VMEM scope next to the in-flight score tiles -> two-pass streaming.
# (5 MB == seq_q 8192 at d=64, 4096 at d=128.)
FUSED_BWD_RESIDENT_BUDGET = 5 * 2 ** 20
# Unroll the fused backward's q loop with STATIC slices up to this many
# tiles (dynamic-slice reads defeat the Mosaic vectorizer, ~10% on v5e).
MAX_UNROLL_QB = 16
# Per-core VMEM scope the backward schedules must fit inside a full train
# step (v5e/v5p expose 16 MB to a Pallas kernel next to XLA's own buffers).
VMEM_SCOPE_BYTES = 16 * 2 ** 20
NEG_INF = -1e30


def _fused_bwd_vmem_bytes(seq_q: int, d: int, block_q: int,
                          block_k: int) -> int:
    """VMEM footprint of the fused one-pass backward at a given tiling:
    the resident Q/dO/O/dq-out (bf16) plus the (seq_q, d) f32 dq scratch
    (~10*seq_q*d bytes), three (block_q, block_k) f32 score-sized tiles in
    flight (s, p, dp), and the streamed K/V bf16 tiles. Used to decide when
    the k tile can be WIDER than the conservative 512 cap: short sequences
    leave most of the scope unused, and wider k tiles amortize the resident
    re-reads across fewer grid steps."""
    resident = 10 * seq_q * d
    score_tiles = 3 * block_q * block_k * 4
    kv_tiles = 2 * block_k * d * 2
    return resident + score_tiles + kv_tiles


def dropout_keep_scale(seed, bh, q_start, k_start, block_q, block_k,
                       rate: float):
    """Counter-based dropout mask for one (block_q, block_k) score tile:
    {0, 1/(1-rate)} as f32, a pure function of the GLOBAL (seed, batch*head,
    q_pos, k_pos) coordinates — so the forward kernel and both backward
    kernels regenerate the SAME mask regardless of block decomposition
    (reference analog: cuDNN's dropout descriptor inside the fused MHA,
    src/ops/attention.cu:225). One murmur3-finalizer round per element over
    a linear counter; plain uint32 ops, so it runs identically compiled on
    TPU and in interpret mode."""
    import jax
    import jax.numpy as jnp

    qpos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return dropout_keep_scale_nd(seed, jnp.asarray(bh, jnp.uint32),
                                 qpos, kpos, rate)


def dropout_keep_scale_nd(seed, bh, q_pos, k_pos, rate: float):
    """Vectorized twin of ``dropout_keep_scale`` for the non-Pallas paths
    (ring/Ulysses sequence parallelism): ``bh``/``q_pos``/``k_pos`` are
    broadcastable uint32 arrays of GLOBAL coordinates, so every chip of an
    SP group draws decorrelated masks from the same counter stream."""
    import jax.numpy as jnp

    x = (q_pos.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         + k_pos.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
         + bh.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
         + jnp.asarray(seed, jnp.uint32))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    threshold = jnp.uint32(min(int(rate * 2 ** 32), 2 ** 32 - 1))
    return (x >= threshold).astype(jnp.float32) / (1.0 - rate)


def coerce_dropout_seed(name: str, dropout: float, seed):
    """Shared validation + uint32 coercion for every dropout entry point
    (flash / ring / Ulysses) so the contract cannot drift."""
    import jax.numpy as jnp

    if not 0.0 <= float(dropout) < 1.0:
        raise ValueError(f"{name} dropout must be in [0, 1), got {dropout}")
    if dropout > 0.0 and seed is None:
        raise ValueError(f"{name} dropout requires a seed")
    return jnp.asarray(seed if seed is not None else 0, jnp.uint32)


def global_bh_indices(b_local: int, total_heads: int, h_local: int,
                      b_base, h_base):
    """(b_local, h_local) uint32 grid of GLOBAL batch*head indices for the
    dropout counter stream — one implementation shared by ring and Ulysses
    so their masks stay on the same stream as the flash kernel's."""
    import jax.numpy as jnp

    return ((b_base + jnp.arange(b_local))[:, None] * total_heads
            + h_base + jnp.arange(h_local)[None, :]).astype(jnp.uint32)


def _apply_causal_mask(s, q_start, k_start, offset, block_q, block_k):
    """Causal mask for one (block_q, block_k) score tile. ``offset`` aligns
    rectangular shapes the same way the einsum core's ``tril(k=sk-sq)`` does:
    query i attends keys j with j <= i + offset."""
    import jax
    import jax.numpy as jnp

    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1)
    return jnp.where(q_pos + offset >= k_pos, s, NEG_INF)


def _tile_contributes(q_idx, kb, block_q, block_k, offset):
    """Traced bool: does tile (q_idx, kb) intersect the causal band?
    True iff the tile's largest q_pos + offset reaches its smallest k_pos."""
    return q_idx * block_q + block_q - 1 + offset >= kb * block_k


def _first_contributing_qb(kb, block_q, block_k, offset):
    """Smallest q-block index intersecting the causal band for key block kb
    (tight: qb*block_q <= kb*block_k - offset < (qb+1)*block_q ⇒ the tile's
    last row reaches the band and qb-1's does not)."""
    import jax.numpy as jnp

    return jnp.maximum(kb * block_k - offset, 0) // block_q


def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *, num_kb: int, causal: bool,
                      causal_offset: int = 0,
                      dropout: float = 0.0, num_heads: int = 1):
    """Grid (batch, head, q_block, k_block), k innermost: one (q, k) score
    tile per program, online-softmax state (m, l, acc) carried across the k
    grid dimension in VMEM scratch (m/l lane-replicated to (block_q, 128)
    for layout). K/V tiles stream through the grid — Pallas double-buffers
    their DMAs — so VMEM residency is O(block), not O(seq_k). All tile
    accesses are static BlockSpec blocks: a register-carried
    fori_loop-over-pl.ds variant measured ~10% slower on v5e (dynamic-slice
    reads defeat the Mosaic vectorizer), so one tile per grid step it is.

    Q arrives PRE-SCALED by 1/sqrt(d) (folded into the projection by XLA),
    so no kernel multiplies the (block_q, block_k) score tile by sm_scale —
    that VPU pass (~270M multiplies/layer at seq 4096) is free."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(2)
    kb = pl.program_id(3)
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]

    if num_kb == 1:
        # single k block: the whole softmax row is in registers — skip the
        # scratch round-trip entirely (measured ~0.1 ms/layer at b8 s512)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _apply_causal_mask(s, q_idx * block_q, 0, causal_offset,
                                   block_q, block_k)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            p = p * dropout_keep_scale(seed_ref[0], bh, q_idx * block_q, 0,
                                       block_q, block_k, dropout)
        acc = jnp.dot(p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)
        return

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _tile():
        q = q_ref[0, 0]  # (block_q, d) — input dtype: bf16 feeds the MXU
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _apply_causal_mask(s, q_idx * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        m_prev = m_scr[...]  # (block_q, 128), lanes replicated
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        # softmax normalizer from UNDROPPED p: dropout applies to the
        # normalized probabilities, and elementwise mask/scale commutes
        # with the 1/l normalization
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            p = p * dropout_keep_scale(seed_ref[0], bh, q_idx * block_q,
                                       kb * block_k, block_q, block_k,
                                       dropout)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(_tile_contributes(q_idx, kb, block_q, block_k,
                                   causal_offset))
        def _run():
            _tile()
    else:
        _tile()

    @pl.when(kb == num_kb - 1)
    def _final():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        # lse block is (block_q, 1): TPU tiling wants >=2-D blocks whose
        # minor dim matches the array
        lse_ref[0, 0] = (m_scr[:, :1] + jnp.log(l_safe)).astype(lse_ref.dtype)


def _compiler_params(interpret: bool, semantics):
    if interpret:
        return None
    import jax.experimental.pallas.tpu as pltpu

    return pltpu.CompilerParams(dimension_semantics=semantics)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool, dropout: float = 0.0, seed=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    # pre-scale q outside the kernel: XLA fuses the multiply into the
    # producing projection, and the per-score-element sm_scale VPU pass
    # disappears from the kernel. Exact when 1/sqrt(d) is a power of two
    # (d = 4^k: 1/8 at d=64, 1/16 at d=256). For d=128 (1/(8*sqrt(2))) and
    # d=192 the scale is NOT a power of two, so rounding the scaled q back
    # to bf16 costs ONE extra bf16 rounding per q element versus applying
    # sm_scale to the f32 score tile in-kernel — bounded by bf16 eps
    # (~0.4%) per element, before the f32 accumulation; the parity tests'
    # bf16 tolerances cover it.
    q = (q * np.float32(1.0 / np.sqrt(d))).astype(q.dtype)
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    seed_arr = jnp.reshape(jnp.asarray(
        seed if seed is not None else 0, jnp.uint32), (1,))

    num_kb = seq_k // block_k
    grid = (batch, heads, seq_q // block_q, num_kb)
    kernel = functools.partial(_flash_fwd_kernel, num_kb=num_kb,
                               causal=causal,
                               causal_offset=seq_k - seq_q, dropout=dropout,
                               num_heads=heads)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, h, i, j: (0,)),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed_arr, q, k, v)
    return out, lse.reshape(batch, heads, seq_q)


def _flash_bwd_fused_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                            o_ref, dq_ref, dk_ref, dv_ref, dq_scr, *,
                            block_q: int, seq_q: int, num_kb: int,
                            causal: bool, sm_scale: float,
                            causal_offset: int = 0, dropout: float = 0.0,
                            num_heads: int = 1):
    """Fused one-pass backward, grid (batch, head, k_block): K/V tiles
    stream through the grid while Q/dO/lse/O stay resident per (b, h);
    dq accumulates in a (seq_q, d) f32 scratch carried across the k grid
    dimension and is flushed on the last k block. Each (q, k) tile computes
    the probabilities ONCE and derives dv, dk and dq from them — the
    two-pass schedule pays the score matmul, dO·Vᵀ matmul and the exp twice.
    δ = rowsum(dO∘O) is computed in-register from the resident tiles rather
    than as a separate HBM-roundtrip fusion before the kernel.

    Q arrives PRE-SCALED by 1/sqrt(d): s needs no scale, dk = dSᵀ·(q/√d)
    absorbs it exactly, and only the dq flush multiplies by sm_scale once.

    With dropout (mask D regenerated from the same counters as forward):
    dV = (P∘D)ᵀ dO and dS = P ∘ (D∘dP - δ) — δ = rowsum(dO∘O) already
    equals rowsum(P∘D ∘ dP), so the softmax-backward identity holds with
    the dropped probabilities folded in."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    k = k_ref[0, 0]  # (block_k, d)
    v = v_ref[0, 0]
    block_k = k.shape[0]
    d = k.shape[1]

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    num_qb = seq_q // block_q
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)

    def body(qb, carry, sl=None):
        """One (q, k) tile; ``sl`` carries static slices when unrolled —
        dynamic-slice reads measurably defeat the Mosaic vectorizer."""
        dk, dv = carry
        if sl is None:
            sl = pl.ds(qb * block_q, block_q)
        q = q_ref[0, 0, sl, :]
        do = do_ref[0, 0, sl, :]
        lse = lse_ref[0, 0, sl, :]  # (bq, 1) f32
        o = o_ref[0, 0, sl, :]
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _apply_causal_mask(s, qb * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        p = jnp.exp(s - lse)  # exact softmax probabilities from stored lse
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = dropout_keep_scale(seed_ref[0], bh, qb * block_q,
                                      kb * block_k, block_q, block_k,
                                      dropout)
            pd = p * keep
            dp = dp * keep
        else:
            pd = p
        dv = dv + jnp.dot(pd.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32)
        dq_scr[sl, :] = (dq_scr[sl, :]
                         + jnp.dot(ds.astype(k.dtype), k,
                                   preferred_element_type=jnp.float32))
        return dk, dv

    if causal:
        # the loop start is traced (depends on kb), so the static unroll
        # below does not apply; masked tiles would vanish numerically
        # (p == 0) but cost full compute, so keep the skip via fori_loop
        qb_start = _first_contributing_qb(kb, block_q, block_k,
                                          causal_offset)
        dk, dv = jax.lax.fori_loop(qb_start, num_qb, body, (dk0, dv0))
    elif num_qb <= MAX_UNROLL_QB:
        # non-causal: every tile contributes — unroll with static slices
        dk, dv = dk0, dv0
        for qb in range(num_qb):
            dk, dv = body(qb, (dk, dv),
                          sl=slice(qb * block_q, (qb + 1) * block_q))
    else:
        dk, dv = jax.lax.fori_loop(0, num_qb, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)

    @pl.when(kb == num_kb - 1)
    def _final():
        dq_ref[0, 0] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          num_qb: int, causal: bool,
                          causal_offset: int = 0, dropout: float = 0.0,
                          num_heads: int = 1):
    """Two-pass schedule, dkv kernel: grid (batch, head, k_block, q_block),
    q innermost. K/V tiles are resident per k block; Q/dO/lse/delta tiles
    stream through the q grid dimension; (dk, dv) accumulate in VMEM scratch
    carried across it (the FlashAttention-2 backward split, with O(block)
    VMEM for arbitrarily long sequences)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    qb = pl.program_id(3)
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    k = k_ref[0, 0]  # (block_k, d)
    v = v_ref[0, 0]
    block_k = k.shape[0]
    block_q = q_ref.shape[2]

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _tile():
        q = q_ref[0, 0]  # pre-scaled by 1/sqrt(d): dk = dSᵀ·(q/√d) exactly
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # (bq, 1) f32
        delta = delta_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _apply_causal_mask(s, qb * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = dropout_keep_scale(seed_ref[0], bh, qb * block_q,
                                      kb * block_k, block_q, block_k,
                                      dropout)
            pd = p * keep
            dp = dp * keep
        else:
            pd = p
        dv_scr[...] = dv_scr[...] + jnp.dot(
            pd.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[...] = dk_scr[...] + jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(_tile_contributes(qb, kb, block_q, block_k, causal_offset))
        def _run():
            _tile()
    else:
        _tile()

    @pl.when(qb == num_qb - 1)
    def _final():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, dq_scr, *, num_kb: int,
                         causal: bool, sm_scale: float,
                         causal_offset: int = 0, dropout: float = 0.0,
                         num_heads: int = 1):
    """Two-pass schedule, dq kernel: grid (batch, head, q_block, k_block),
    k innermost. Q/dO/lse/delta resident per q block; K/V tiles stream
    through the k grid dimension; dq accumulates in scratch."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qb = pl.program_id(2)
    kb = pl.program_id(3)
    bh = pl.program_id(0) * num_heads + pl.program_id(1)
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _tile():
        q = q_ref[0, 0]  # pre-scaled by 1/sqrt(d)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # (block_q, 1)
        delta = delta_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _apply_causal_mask(s, qb * block_q, kb * block_k,
                                   causal_offset, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp = dp * dropout_keep_scale(seed_ref[0], bh, qb * block_q,
                                         kb * block_k, block_q, block_k,
                                         dropout)
        ds = p * (dp - delta)
        dq_scr[...] = dq_scr[...] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(_tile_contributes(qb, kb, block_q, block_k, causal_offset))
        def _run():
            _tile()
    else:
        _tile()

    @pl.when(kb == num_kb - 1)
    def _final():
        dq_ref[0, 0] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _flash_backward(q, k, v, out, lse, do, causal: bool, block_q: int,
                    block_k: int, interpret: bool, dropout: float = 0.0,
                    seed=None, fused: Optional[bool] = None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    sm_scale = 1.0 / np.sqrt(d)
    # q pre-scaled as in the forward: the kernels recompute the identical s
    q = (q * np.float32(sm_scale)).astype(q.dtype)
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)

    dor = do.astype(q.dtype)
    lser = lse.reshape(batch, heads, seq_q, 1)
    seed_arr = jnp.reshape(jnp.asarray(
        seed if seed is not None else 0, jnp.uint32), (1,))

    seed_spec = pl.BlockSpec((1,), lambda *_: (0,))
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    if fused is None:
        fused = seq_q * d * 10 <= FUSED_BWD_RESIDENT_BUDGET

    if fused:
        # grid (b, h, kb): Q/dO/O resident, dq in (seq_q, d) scratch;
        # delta is computed in-kernel from the resident dO/O tiles
        full_q = pl.BlockSpec((1, 1, seq_q, d), lambda b, h, j: (b, h, 0, 0))
        full_q1 = pl.BlockSpec((1, 1, seq_q, 1), lambda b, h, j: (b, h, 0, 0))
        tile_k = pl.BlockSpec((1, 1, block_k, d), lambda b, h, j: (b, h, j, 0))
        kernel = functools.partial(
            _flash_bwd_fused_kernel, block_q=block_q, seq_q=seq_q,
            num_kb=num_kb, causal=causal, sm_scale=sm_scale,
            causal_offset=seq_k - seq_q, dropout=dropout, num_heads=heads)
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid=(batch, heads, num_kb),
            in_specs=[seed_spec, full_q, tile_k, tile_k, full_q, full_q1,
                      full_q],
            out_specs=[full_q, tile_k, tile_k],
            out_shape=[
                jax.ShapeDtypeStruct((batch, heads, seq_q, d), q.dtype),
                jax.ShapeDtypeStruct((batch, heads, seq_k, d), k.dtype),
                jax.ShapeDtypeStruct((batch, heads, seq_k, d), v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((seq_q, d), jnp.float32)],
            compiler_params=_compiler_params(
                interpret, ("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(seed_arr, q, k, v, dor, lser, out)
        return dq, dk, dv

    # two-pass streaming schedule: O(block) VMEM at any sequence length
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    tile_q_kv = pl.BlockSpec((1, 1, block_q, d),
                             lambda b, h, j, i: (b, h, i, 0))
    tile_q1_kv = pl.BlockSpec((1, 1, block_q, 1),
                              lambda b, h, j, i: (b, h, i, 0))
    res_k = pl.BlockSpec((1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0))
    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, num_qb=num_qb, causal=causal,
        causal_offset=seq_k - seq_q, dropout=dropout,
        num_heads=heads)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(batch, heads, num_kb, num_qb),
        in_specs=[seed_spec, tile_q_kv, res_k, res_k, tile_q_kv, tile_q1_kv,
                  tile_q1_kv],
        out_specs=[res_k, res_k],
        out_shape=[jax.ShapeDtypeStruct((batch, heads, seq_k, d), k.dtype),
                   jax.ShapeDtypeStruct((batch, heads, seq_k, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed_arr, q, k, v, dor, lser, delta)

    res_q = pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0))
    res_q1 = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))
    tile_k_q = pl.BlockSpec((1, 1, block_k, d),
                            lambda b, h, i, j: (b, h, j, 0))
    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, num_kb=num_kb, causal=causal,
        sm_scale=sm_scale, causal_offset=seq_k - seq_q, dropout=dropout,
        num_heads=heads)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(batch, heads, num_qb, num_kb),
        in_specs=[seed_spec, res_q, tile_k_q, tile_k_q, res_q, res_q1,
                  res_q1],
        out_specs=res_q,
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed_arr, q, k, v, dor, lser, delta)

    return dq, dk, dv


def _reference_core(q, k, v, causal: bool):
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_attention_p(q, k, v, seed, causal, block_q, block_k, interpret,
                       dropout, bwd_block_q, bwd_block_k):
    _check_causal_shape(q, k, causal)
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k,
                            _resolve_interpret(interpret),
                            dropout=dropout, seed=seed)
    return out


def _bwd_blocks(block_q: int, block_k: int, bwd_block_q, bwd_block_k,
                seq_q: int, seq_k: int, head_dim: Optional[int] = None):
    """Backward block defaults are SCHEDULE-AWARE (r18):

    - Fused one-pass (seq_q*d*10 <= FUSED_BWD_RESIDENT_BUDGET): keeps three
      (block_q, block_k) f32 score-sized tiles in flight NEXT TO the
      resident Q/dO/O/dq, so block_k defaults to the measured 512 cap —
      (512, 512) timed the same 2.16 ms/layer as (512, 1024) on v5e —
      UNLESS _fused_bwd_vmem_bytes says the forward-width tile still fits
      the 16 MB scope (short sequences), in which case the wider forward
      block wins back the resident re-read amortization.
    - Two-pass streaming (past the residency budget): VMEM is O(block),
      so the k tile defaults to the full forward block — 1024-wide k tiles
      are the forward sweet spot and the long-context (8k-32k) backward
      spends its time streaming K/V, where wider tiles cut grid overhead.

    Without head_dim (legacy callers) the conservative 512 cap applies.

    Divisibility is re-checked against the sequences: a default that no
    longer divides seq_k falls back to the (valid) forward block, and an
    EXPLICIT non-dividing override raises — the grid floor-divisions would
    otherwise silently drop the tail keys from dk/dv/dq."""
    bq = bwd_block_q if bwd_block_q is not None else block_q
    if seq_q % min(bq, seq_q) != 0:
        if bwd_block_q is not None:
            raise ValueError(
                f"flash_attention bwd_block_q={bq} does not divide "
                f"sequence length {seq_q}")
        bq = block_q  # forward block divides by the public contract

    k_default = min(block_k, 512)
    if head_dim is not None:
        fused = seq_q * head_dim * 10 <= FUSED_BWD_RESIDENT_BUDGET
        if not fused:
            k_default = block_k
        elif _fused_bwd_vmem_bytes(seq_q, head_dim, min(bq, seq_q),
                                   block_k) <= VMEM_SCOPE_BYTES:
            k_default = block_k

    bk = bwd_block_k if bwd_block_k is not None else k_default
    if seq_k % min(bk, seq_k) != 0:
        if bwd_block_k is not None:
            raise ValueError(
                f"flash_attention bwd_block_k={bk} does not divide "
                f"sequence length {seq_k}")
        bk = block_k
    return bq, bk


def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None,
                    dropout: float = 0.0, seed=None,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None):
    """q,k,v: (batch, heads, seq, head_dim) -> (batch, heads, seq_q, head_dim).

    seq_q/seq_k must be multiples of the block sizes (the attention op checks
    this before selecting the flash path, ops/attention.py). Causal requires
    seq_q <= seq_k: with more queries than keys the leading queries attend an
    empty window, which only the einsum core's degenerate uniform-softmax
    handles — use mha_core for that case.

    ``dropout``/``seed``: in-kernel attention-probability dropout via a
    counter-based PRNG on global (batch*head, q_pos, k_pos) coordinates, so
    forward and both backward schedules regenerate identical masks without
    materializing them in HBM (the cuDNN-MHA dropout analog,
    reference src/ops/attention.cu:225). ``seed`` is a traced uint32 scalar
    — reseed per step without recompiling."""
    dropout = float(dropout)
    seed = coerce_dropout_seed("flash_attention", dropout, seed)
    return _flash_attention_p(q, k, v, seed, causal, block_q, block_k,
                              interpret, dropout, bwd_block_q, bwd_block_k)


def _check_causal_shape(q, k, causal: bool) -> None:
    if causal and q.shape[-2] > k.shape[-2]:
        raise ValueError(
            f"flash_attention causal requires seq_q <= seq_k, got "
            f"{q.shape[-2]} > {k.shape[-2]}; use the einsum core instead")


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    import jax

    return jax.default_backend() != "tpu"


def _fwd(q, k, v, seed, causal, block_q, block_k, interpret, dropout,
         bwd_block_q, bwd_block_k):
    _check_causal_shape(q, k, causal)
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              _resolve_interpret(interpret),
                              dropout=dropout, seed=seed)
    return out, (q, k, v, seed, out, lse)


def _bwd(causal, block_q, block_k, interpret, dropout, bwd_block_q,
         bwd_block_k, res, do):
    """Backward by recompute (never materializes the score matrix): blockwise
    Pallas kernels using the flash-attention backward identities, with exact
    probabilities reconstructed from the stored logsumexp (and the dropout
    mask regenerated from the same counters)."""
    q, k, v, seed, out, lse = res
    bq, bk = _bwd_blocks(block_q, block_k, bwd_block_q, bwd_block_k,
                         q.shape[-2], k.shape[-2], q.shape[-1])
    dq, dk, dv = _flash_backward(q, k, v, out, lse, do, causal, bq,
                                 bk, _resolve_interpret(interpret),
                                 dropout=dropout, seed=seed)
    return dq, dk, dv, None


_flash_attention_p.defvjp(_fwd, _bwd)
