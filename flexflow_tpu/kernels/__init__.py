"""Pallas TPU kernels for the hot ops (SURVEY §7: attention, softmax, top-k,
MoE dispatch)."""
from .flash_attention import flash_attention  # noqa: F401
from .topk import pallas_topk  # noqa: F401
