"""All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

The second sequence-parallel schedule next to ring attention
(kernels/ring_attention.py). No reference analog (SURVEY §5: the reference
scales sequence only via head/sample sharding) — part of the long-context
extension. Inputs arrive sequence-sharded; two ``lax.all_to_all``s
re-partition (b, h, s/P, d) -> (b, h/P, s, d) so every chip computes FULL
attention for its head group, then the output transposes back. Comm is 4
all-to-alls of the activation volume regardless of P, vs ring's (P-1) k/v
rotations — cheaper for large P / short-ish sequences, while ring keeps the
O((s/P)^2) score-memory advantage for extreme context. Requires
heads % P == 0.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

NEG_INF = -1e30


def _full_attn(q, k, v, causal: bool, dropout: float = 0.0, seed=None,
               bh=None):
    """Full softmax attention in f32: q,k,v (b, h, s, d). ``bh``: (b, h)
    uint32 GLOBAL batch*head indices for the counter-based dropout mask
    (shared with the flash kernel) so head groups on different chips draw
    decorrelated masks."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout > 0.0:
        from .flash_attention import dropout_keep_scale_nd

        sq, sk = s.shape[-2], s.shape[-1]
        qp = jnp.arange(sq, dtype=jnp.int32)[:, None]
        kp = jnp.arange(sk, dtype=jnp.int32)[None, :]
        p = p * dropout_keep_scale_nd(seed, bh[..., None, None], qp, kp,
                                      dropout)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def ulysses_attention(q, k, v, mesh, seq_axis: str = "seq",
                      causal: bool = False,
                      data_axis: Optional[str] = "data",
                      dropout: float = 0.0, seed=None):
    """q,k,v: (batch, heads, seq, head_dim), seq sharded over ``seq_axis``.

    Must be called under jit with ``mesh``; returns the attention output
    with the same sharding as q. ``dropout``/``seed``: counter-based
    attention dropout (global coordinates — no silent drop on the SP path,
    VERDICT r3 item 3)."""
    from jax import lax
    try:
        from jax import shard_map  # jax >= 0.6 top-level alias
    except ImportError:  # older jax on pinned TPU stacks
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    import jax
    import jax.numpy as jnp

    n_seq = mesh.shape[seq_axis]
    heads = q.shape[1]
    assert heads % n_seq == 0, \
        f"ulysses needs heads ({heads}) divisible by |{seq_axis}| ({n_seq})"
    batch_spec = data_axis if (data_axis and data_axis in mesh.shape) else None
    spec = P(batch_spec, None, seq_axis, None)
    from .flash_attention import coerce_dropout_seed, global_bh_indices

    seed = coerce_dropout_seed("ulysses_attention", dropout, seed)

    def local(q_blk, k_blk, v_blk, seed_s):
        # (b, h, s/P, d) -> (b, h/P, s, d): each chip now owns h/P full-
        # sequence heads
        def fwd(x):
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        bh = None
        if dropout > 0.0:
            b_local = q_blk.shape[0]
            h_local = heads // n_seq
            b_base = (jax.lax.axis_index(data_axis) * b_local
                      if batch_spec else 0)
            h_base = jax.lax.axis_index(seq_axis) * h_local
            bh = global_bh_indices(b_local, heads, h_local, b_base, h_base)
        out = _full_attn(fwd(q_blk), fwd(k_blk), fwd(v_blk), causal,
                         dropout=dropout, seed=seed_s, bh=bh)
        # cast BEFORE the output all-to-all: accumulation is complete, and
        # moving bf16 instead of the f32 accumulator halves that
        # collective's bytes (sequence_schedule prices it at input width)
        out = out.astype(q_blk.dtype)
        # (b, h/P, s, d) -> (b, h, s/P, d)
        return lax.all_to_all(out, seq_axis, split_axis=2, concat_axis=1,
                              tiled=True)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec, P()),
                     out_specs=spec)(q, k, v, seed)
