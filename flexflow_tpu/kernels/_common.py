"""Shared routing/tiling helpers for the row-wise Pallas kernels
(softmax, top-k)."""
from __future__ import annotations

from typing import Optional

import jax

DEFAULT_BLOCK_ROWS = 8


def resolve_interpret(interpret: Optional[bool]) -> bool:
    """Default to interpret mode off-TPU (the CPU test mesh)."""
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def pick_block_rows(rows: int, dim: int) -> int:
    """Largest row block dividing ``rows`` whose f32 working set stays
    within a conservative VMEM budget — wide rows otherwise OOM the 16 MiB
    scoped vmem (observed at 64 x 32768 in the softmax backward, where
    input + probs + grad tiles are live at once)."""
    budget = 4 * 2 ** 20  # bytes per tile
    cap = max(budget // max(dim * 4, 1), 1)
    for b in (64, 32, 16, DEFAULT_BLOCK_ROWS, 4, 2, 1):
        if b <= cap and rows % b == 0:
            return b
    return 1
