"""Pallas split-K flash-decode kernel: single-token paged attention.

The serving decode step advances every slot one token; its attention read
is the decode hot loop's HBM bill. The ring layout paid O(max_len) per
slot per token (position-masked attention over the full ring); with the
paged layout (serving/kvcache.py) this kernel gathers ONLY the blocks a
slot actually occupies, so per-token traffic is O(true_length):

* grid = (n_slots, max_blocks_per_slot): the KV-block axis is the
  **split-K** dimension — each grid step folds one (heads, block_size)
  score tile into an online-softmax accumulator (m, l, acc scratch),
  exactly the FlashAttention recurrence restricted to a 1-row q.
* the pool block each step reads is resolved through the slot's block
  table by the BlockSpec index map (``PrefetchScalarGridSpec`` — the
  tables and per-slot key counts are scalar-prefetched, available before
  the kernel body). Steps past a slot's last occupied block CLAMP to the
  last occupied block: Pallas skips the DMA when the resolved index is
  unchanged, so dead steps move no HBM bytes, and the body masks them
  out by global key position anyway (the loaded data is never used).
* int8 KV (``kscale``/``vscale``): blocks are dequantized in-VMEM from
  the block-paged per-(token, head) scales — HBM moves ~1/el of the fp
  bytes plus the f32 scale vectors (the bandwidth the serving search's
  ``kv_dtype`` axis prices).

Tile tuning rides the per-generation FLASH_TUNING machinery
(``ops.attention._flash_tuning(kernel="flash_decode")`` at the routing
site — an unmeasured generation warns once per kernel, ISSUE 12
satellite). Off-TPU the op layer never routes here (the masked gather
path keeps tier-1 CPU-green); tests run the kernel in interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

NEG_INF = -1e30


def use_flash_decode(head_dim: int, block_size: int) -> bool:
    """Routing gate for the serving attention op: real-TPU platform and
    MXU/VPU-friendly dims (lane-padded head_dim, whole-sublane blocks).
    The CPU fallback (gather + masked einsum) is the correctness path —
    this kernel is the bandwidth path."""
    if block_size < 8 or block_size % 8 != 0 or head_dim % 64 != 0:
        return False
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _decode_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, block_size, n_blocks_grid,
                   kv_dtype, ks_ref=None, vs_ref=None):
    """One (slot, kv-block) grid step of the split-K recurrence."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    n_keys = len_ref[s]

    @pl.when(j * block_size < n_keys)
    def _step():
        q = q_ref[0].astype(jnp.float32)          # (h, hd), pre-scaled
        k = k_ref[0]                              # (h, bs, kd)
        v = v_ref[0]                              # (h, bs, vd)
        if kv_dtype == "int8":
            k = k.astype(jnp.float32) * ks_ref[0][..., None]
            v = v.astype(jnp.float32) * vs_ref[0][..., None]
        else:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
        # (h, bs) score tile: per-head q row against the block's keys
        s_tile = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        kpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s_tile.shape, 1)
        s_tile = jnp.where(kpos < n_keys, s_tile, NEG_INF)
        m_prev = m_ref[:, :1]                     # (h, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s_tile, axis=-1,
                                            keepdims=True))
        p = jnp.exp(s_tile - m_new)               # (h, bs)
        corr = jnp.exp(m_prev - m_new)            # (h, 1)
        pv = jax.lax.dot_general(
            p, v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # (h, vd)
        acc_ref[:] = acc_ref[:] * corr + pv
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_blocks_grid - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def flash_decode(q, kpool, vpool, block_tables, n_keys, *,
                 sm_scale: Optional[float] = None, kscale=None,
                 vscale=None, interpret: bool = False):
    """Single-token paged attention over a KV block pool.

    q            (n_slots, heads, head_dim) — this step's query rows
    kpool/vpool  (n_blocks, heads, block_size, kd|vd) — model dtype, or
                 int8 with ``kscale``/``vscale`` (n_blocks, heads,
                 block_size) f32 per-(token, head) scales
    block_tables (n_slots, max_blocks_per_slot) int32
    n_keys       (n_slots,) int32 — keys each slot attends (position + 1)

    Returns (n_slots, heads, vd) in q's dtype. ``interpret=True`` runs
    the Mosaic interpreter (the CPU test path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_slots, heads, head_dim = q.shape
    n_blocks, _h, block_size, kd = kpool.shape
    vd = vpool.shape[-1]
    mb = block_tables.shape[1]
    kv_dtype = "int8" if kpool.dtype == jnp.int8 else "native"
    if kv_dtype == "int8" and (kscale is None or vscale is None):
        raise ValueError("flash_decode: int8 pools need kscale/vscale")
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(head_dim)
    out_dtype = q.dtype
    q = (q.astype(jnp.float32) * jnp.float32(scale))
    tables = block_tables.astype(jnp.int32)
    n_keys = n_keys.astype(jnp.int32)

    def block_index(s, j, tab_ref, len_ref):
        # clamp steps past the slot's last occupied block to the last
        # occupied one: the resolved index repeats, Pallas skips the DMA,
        # and the body's position mask ignores the data
        used = (len_ref[s] + block_size - 1) // block_size
        jj = jnp.minimum(j, jnp.maximum(used - 1, 0))
        return (tab_ref[s, jj], 0, 0, 0)

    def scale_index(s, j, tab_ref, len_ref):
        return block_index(s, j, tab_ref, len_ref)[:3]

    in_specs = [
        pl.BlockSpec((1, heads, head_dim), lambda s, j, t, n: (s, 0, 0)),
        pl.BlockSpec((1, heads, block_size, kd), block_index),
        pl.BlockSpec((1, heads, block_size, vd), block_index),
    ]
    args = [q, kpool, vpool]
    ks_vs = None
    if kv_dtype == "int8":
        in_specs += [pl.BlockSpec((1, heads, block_size), scale_index),
                     pl.BlockSpec((1, heads, block_size), scale_index)]
        args += [kscale, vscale]
        ks_vs = True

    def kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, *rest):
        if ks_vs:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            (o_ref, m_ref, l_ref, acc_ref) = rest
            ks_ref = vs_ref = None
        _decode_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                       m_ref, l_ref, acc_ref, block_size=block_size,
                       n_blocks_grid=mb, kv_dtype=kv_dtype,
                       ks_ref=ks_ref, vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_slots, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, heads, vd), lambda s, j, t, n: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((heads, 128), jnp.float32),  # m
            pltpu.VMEM((heads, 128), jnp.float32),  # l
            pltpu.VMEM((heads, vd), jnp.float32),   # acc
        ],
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_slots, heads, vd), out_dtype),
        interpret=interpret,
    )
    return fn(tables, n_keys, *args)


@functools.lru_cache(maxsize=1)
def _reference_decode():
    """Masked-gather reference (the op layer's CPU path restated) for the
    kernel parity tests."""
    import jax.numpy as jnp

    from ..serving.kvcache import (dequantize_kv, gather_paged_kv,
                                   gather_paged_scales)

    def ref(q, kpool, vpool, tables, n_keys, sm_scale,
            kscale=None, vscale=None):
        if kscale is not None:
            kc = dequantize_kv(gather_paged_kv(kpool, tables),
                               gather_paged_scales(kscale, tables),
                               jnp.float32)
            vc = dequantize_kv(gather_paged_kv(vpool, tables),
                               gather_paged_scales(vscale, tables),
                               jnp.float32)
        else:
            kc = gather_paged_kv(kpool, tables).astype(jnp.float32)
            vc = gather_paged_kv(vpool, tables).astype(jnp.float32)
        logits = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32), kc,
                            preferred_element_type=jnp.float32) * sm_scale
        kpos = jnp.arange(kc.shape[2])
        logits = jnp.where(kpos[None, None, :] < n_keys[:, None, None],
                           logits, NEG_INF)
        import jax

        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhk,bhkd->bhd", probs, vc,
                          preferred_element_type=jnp.float32
                          ).astype(q.dtype)

    return ref
