"""Pallas row-wise top-k kernel for TPU.

Reference analog: the hand-written top-k GPU kernel behind src/ops/topk.cc
(kernels/topk_kernels.cu — per-thread heaps merged across the warp). SURVEY
§7 lists top-k among the ops worth a Pallas kernel. On TPU the natural
formulation for the small ``k`` MoE routing uses (k <= 4) is ``k`` unrolled
max+argmax sweeps over a row tile held in VMEM: one HBM read of the scores
per element total, versus lax.top_k's generic sort lowering. Ties resolve
to the lowest index, matching ``jax.lax.top_k``.

Backward matches lax.top_k's vjp: the value cotangent scatters to the
selected positions (indices are non-differentiable), done as an XLA
one-hot scatter — no kernel needed on the backward path.

Routing: ``TopKOp`` uses this only on explicit opt-in
(attrs["use_pallas"]) — like the softmax kernel, XLA's top-k lowering is
already competitive at MoE-routing sizes, and the kernel exists for parity
with the reference's dedicated kernel and as a fusion anchor for a future
router epilogue. Interpret mode serves the CPU test mesh."""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import numpy as np

from ._common import (pick_block_rows as _pick_block_rows,
                      resolve_interpret as _resolve_interpret)

MAX_PALLAS_K = 8  # the unrolled-sweep formulation only pays off for small k


def _topk_kernel(k: int, x_ref, vals_ref, idx_ref):
    import jax.numpy as jnp
    from jax import lax

    x = x_ref[...].astype(jnp.float32)  # (block_rows, dim)
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    neg_inf = jnp.float32(-np.inf)
    # selection key clamps -inf inputs to -FLT_MAX so -inf stays reserved
    # for "already taken": rows with fewer than k finite entries must still
    # return k DISTINCT indices (the lax.top_k contract; MoE routers mask
    # logits with -inf, so this path is live). A genuine -FLT_MAX input
    # ties with masked -inf entries — resolved by lowest index like any tie.
    key = jnp.maximum(x, jnp.float32(np.finfo(np.float32).min))
    for j in range(k):  # unrolled: k is static and small
        i = jnp.argmax(key, axis=-1).astype(jnp.int32)
        sel = cols == i[:, None]
        # original value at i (not the clamped key): x[row, i]
        vals_ref[:, j] = jnp.max(jnp.where(sel, x, neg_inf),
                                 axis=-1).astype(vals_ref.dtype)
        idx_ref[:, j] = i
        key = jnp.where(sel, neg_inf, key)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def pallas_topk(x, k: int, interpret: Optional[bool] = None
                ) -> Tuple[jax.Array, jax.Array]:
    """Top-k over the last dim of an arbitrary-rank array.

    Returns (values, indices) with values sorted descending — the
    ``jax.lax.top_k`` contract."""
    out, _ = _topk_fwd(x, k, interpret)
    return out


def _topk_call(x, k: int, interpret: bool):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    shape = x.shape
    dim = shape[-1]
    rows = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    xr = x.reshape(rows, dim)
    block_rows = _pick_block_rows(rows, dim)
    in_spec = pl.BlockSpec((block_rows, dim), lambda i: (i, 0))
    out_spec = pl.BlockSpec((block_rows, k), lambda i: (i, 0))
    vals, idx = pl.pallas_call(
        functools.partial(_topk_kernel, k),
        grid=(rows // block_rows,),
        in_specs=[in_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[jax.ShapeDtypeStruct((rows, k), x.dtype),
                   jax.ShapeDtypeStruct((rows, k), jnp.int32)],
        interpret=interpret,
    )(xr)
    out_shape = shape[:-1] + (k,)
    return vals.reshape(out_shape), idx.reshape(out_shape)


def _topk_fwd(x, k: int, interpret: Optional[bool]):
    vals, idx = _topk_call(x, k, _resolve_interpret(interpret))
    return (vals, idx), (idx, x.shape[-1])


def _topk_bwd(k: int, interpret: Optional[bool], res, cotangents):
    import jax.nn as jnn
    import jax.numpy as jnp

    idx, dim = res
    g_vals, _ = cotangents  # indices carry no cotangent
    onehot = jnn.one_hot(idx, dim, dtype=g_vals.dtype)  # (..., k, dim)
    dx = jnp.sum(onehot * g_vals[..., None], axis=-2)
    return (dx,)


pallas_topk.defvjp(_topk_fwd, _topk_bwd)


def should_use_pallas_topk(x, k: int, opt_in: bool = False) -> bool:
    """Opt-in only (attrs["use_pallas"]); requires TPU, small k, last-axis
    rows wide enough to amortize the sweep and lane-aligned for the VPU."""
    import jax.numpy as jnp

    if not opt_in:
        return False
    if k > MAX_PALLAS_K or k < 1:
        return False
    if x.ndim < 2 or x.shape[-1] < 128 or x.shape[-1] % 128 != 0:
        return False
    # the kernel computes in f32 with -inf masking: integer (and f64) inputs
    # would silently lose precision, so only sub-f32 floats route here
    if not jnp.issubdtype(x.dtype, jnp.floating) or \
            jnp.dtype(x.dtype).itemsize > 4:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
