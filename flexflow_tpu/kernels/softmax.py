"""Pallas row-softmax kernel for TPU.

Reference analog: the cuDNN softmax kernel behind src/ops/softmax.cc
(kernels/softmax_kernels.cu). SURVEY §7 lists softmax among the ops worth a
Pallas kernel: XLA's fused softmax materializes the row max/sum reductions
through HBM for large rows, while this kernel keeps one (block_rows, dim)
tile resident in VMEM per grid step — one HBM read + one write per element.
Backward uses the standard identity dsm = p * (g - sum(p * g)) as a second
rowwise kernel via ``jax.custom_vjp``.

Measured on v5e (fwd+bwd, bf16): 0.675 ms vs jax.nn.softmax's 0.694 ms at
(1024, 8192) and 0.789 vs 0.738 at (4096, 4096) — XLA's softmax fusion is
already at parity on TPU, so SoftmaxOp routes here only on explicit opt-in
(attrs["use_pallas"]); the kernel exists for parity with the reference's
dedicated softmax kernel and as the building block for fused epilogues.
Interpret mode serves the CPU test mesh."""
from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

from ._common import (pick_block_rows as _pick_block_rows,
                      resolve_interpret as _resolve_interpret)


def _softmax_fwd_kernel(x_ref, o_ref):
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.float32)  # (block_rows, dim)
    m = jnp.max(x, axis=-1, keepdims=True)
    p = jnp.exp(x - m)
    o_ref[...] = (p / jnp.sum(p, axis=-1, keepdims=True)).astype(o_ref.dtype)


def _softmax_bwd_kernel(p_ref, g_ref, o_ref):
    import jax.numpy as jnp

    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    inner = jnp.sum(p * g, axis=-1, keepdims=True)
    o_ref[...] = (p * (g - inner)).astype(o_ref.dtype)


def _rowwise_call(kernel, args, rows: int, dim: int, out_dtype,
                  block_rows: int, interpret: bool):
    from jax.experimental import pallas as pl

    spec = pl.BlockSpec((block_rows, dim), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[spec] * len(args),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, dim), out_dtype),
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def pallas_softmax(x, interpret: Optional[bool] = None):
    """Softmax over the last dim of an arbitrary-rank array."""
    out, _ = _fwd(x, interpret)
    return out


def _fwd(x, interpret):
    shape = x.shape
    dim = shape[-1]
    rows = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    xr = x.reshape(rows, dim)
    p = _rowwise_call(_softmax_fwd_kernel, [xr], rows, dim, x.dtype,
                      _pick_block_rows(rows, dim),
                      _resolve_interpret(interpret))
    return p.reshape(shape), p


def _bwd(interpret, p, g):
    shape = g.shape
    dim = shape[-1]
    rows = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    dx = _rowwise_call(_softmax_bwd_kernel, [p, g.reshape(rows, dim)],
                       rows, dim, g.dtype, _pick_block_rows(rows, dim),
                       _resolve_interpret(interpret))
    return (dx.reshape(shape),)


pallas_softmax.defvjp(_fwd, _bwd)


def should_use_pallas_softmax(x, axis: int, opt_in: bool = False) -> bool:
    """Valid only for last-axis softmax with MXU-aligned rows on TPU, and
    only on explicit opt-in: measured at parity with XLA's fused softmax on
    v5e (module docstring), so the default path stays jax.nn.softmax."""
    if not opt_in:
        return False
    if axis not in (-1, x.ndim - 1):
        return False
    if x.shape[-1] < 1024 or x.shape[-1] % 128 != 0:
        return False
    rows = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1
    if rows == 0 or x.shape[-1] == 0:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
