"""Ring attention: sequence/context parallelism over a mesh axis.

No reference analog (SURVEY §5: the reference has no sequence parallelism —
it scales sequence length only by sharding heads/samples); this is the
TPU-native extension that makes long-context first-class. The sequence dim of
q/k/v is sharded over the ``seq`` mesh axis; each chip holds one block of
queries and rotates k/v blocks around the ICI ring with
``lax.ppermute``, accumulating blockwise online-softmax partial results
(the RingAttention / blockwise-parallel-transformer recipe). Peak memory per
chip is O(s/P * s/P) per step instead of O(s^2); comm rides neighbor ICI
links and overlaps with the next block's compute (XLA schedules the
ppermute DMA asynchronously).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, causal: bool,
                dropout: float = 0.0, seed=None, bh=None):
    """One (q-block, k-block) partial: returns (m, l, acc) in f32.

    q: (b, h, sq, d), k/v: (b, h, sk, d); offsets are global positions of the
    blocks for causal masking — and for the counter-based dropout mask
    (``bh``: (b, h) uint32 global batch*head indices), which therefore
    decorrelates across every chip of the ring.
    """
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # (b,h,sq)
    p = jnp.exp(s - m[..., None])
    # normalizer from UNDROPPED p: dropout applies to the normalized probs
    # and the elementwise mask commutes with the final 1/l scaling
    l = jnp.sum(p, axis=-1)
    if dropout > 0.0:
        from .flash_attention import dropout_keep_scale_nd

        sq, sk = s.shape[-2], s.shape[-1]
        qp = q_off + jnp.arange(sq, dtype=jnp.int32)[:, None]
        kp = k_off + jnp.arange(sk, dtype=jnp.int32)[None, :]
        p = p * dropout_keep_scale_nd(seed, bh[..., None, None], qp, kp,
                                      dropout)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q, k, v, mesh, seq_axis: str = "seq",
                   causal: bool = False, data_axis: Optional[str] = "data",
                   dropout: float = 0.0, seed=None):
    """q,k,v: (batch, heads, seq, head_dim), seq sharded over ``seq_axis``.

    Must be called under jit with ``mesh``; returns the attention output with
    the same sharding as q. ``dropout``/``seed``: attention-probability
    dropout from the same global-coordinate counter PRNG the flash kernel
    uses (flash_attention.dropout_keep_scale_nd) — the SP path no longer
    silently drops the rate (VERDICT r3 item 3)."""
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map  # jax >= 0.6 top-level alias
    except ImportError:  # older jax on pinned TPU stacks
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_seq = mesh.shape[seq_axis]
    batch_spec = data_axis if (data_axis and data_axis in mesh.shape) else None
    spec = P(batch_spec, None, seq_axis, None)
    from .flash_attention import coerce_dropout_seed, global_bh_indices

    seed = coerce_dropout_seed("ring_attention", dropout, seed)

    def local(q_blk, k_blk, v_blk, seed_s):
        # q_blk: (b_local, h, s_local, d)
        b_local, heads, s_local, _ = q_blk.shape
        my = jax.lax.axis_index(seq_axis)
        perm = [(j, (j + 1) % n_seq) for j in range(n_seq)]
        bh = None
        if dropout > 0.0:
            b_base = (jax.lax.axis_index(data_axis) * b_local
                      if batch_spec else 0)
            bh = global_bh_indices(b_local, heads, heads, b_base, 0)

        # derive the carry init from q_blk so it carries the same
        # device-varying type under shard_map
        m0 = jnp.full_like(q_blk[..., 0], NEG_INF, dtype=jnp.float32)
        l0 = jnp.zeros_like(q_blk[..., 0], dtype=jnp.float32)
        a0 = jnp.zeros_like(q_blk, dtype=jnp.float32)

        def step(carry, i):
            m, l, acc, k_cur, v_cur = carry
            src = (my - i) % n_seq  # whose k/v block we currently hold
            bm, bl, bacc = _block_attn(q_blk, k_cur, v_cur,
                                       my * s_local, src * s_local, causal,
                                       dropout=dropout, seed=seed_s, bh=bh)
            m_new = jnp.maximum(m, bm)
            scale_old = jnp.exp(m - m_new)
            scale_new = jnp.exp(bm - m_new)
            l_new = l * scale_old + bl * scale_new
            acc_new = acc * scale_old[..., None] + bacc * scale_new[..., None]
            k_next = jax.lax.ppermute(k_cur, seq_axis, perm)
            v_next = jax.lax.ppermute(v_cur, seq_axis, perm)
            return (m_new, l_new, acc_new, k_next, v_next), None

        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (m0, l0, a0, k_blk, v_blk), jnp.arange(n_seq))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        return (acc / l_safe[..., None]).astype(q_blk.dtype)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec, P()),
                     out_specs=spec)(q, k, v, seed)
