"""Sequence-parallel decode: shard-local flash partials + priced combine.

ISSUE 18 (ROADMAP item 3, the capacity half of the long-context story):
a context whose paged KV exceeds one chip's HBM cannot decode on a
single chip no matter how fast the kernel is. Ring Attention (Liu et
al.) and DeepSpeed-Ulysses shard the sequence axis; for *decode* the
paged block tables (ISSUE 12) make that a block-table partition, not a
new runtime — each of ``seq_shards`` chips owns a CONTIGUOUS run of a
slot's KV blocks in its local pool, the single query token of a decode
step is allgathered to every shard, each shard runs the flash-decode
split-K recurrence over its own blocks producing a partial online-
softmax state ``(m, l, acc)``, and one priced combine merges the
partials:

    m*   = max_s m_s
    l*   = sum_s l_s * exp(m_s - m*)
    out  = sum_s acc_s * exp(m_s - m*) / l*

— exactly the flash-attention segment-merge identity, so the combined
result equals the unsharded online softmax up to fp reassociation
(~1 ulp, the same order as the engine's fast-vs-exact decode delta).
A shard whose entire segment is masked (the slot's write cursor has not
reached its block range) contributes ``m_s = -1e30``; its combine
weight ``exp(m_s - m*)`` underflows to exactly 0.0, so never-written
shards add exact zeros — the garbage-block safety argument, lifted to
whole shards.

On the CPU tier (and on a single chip) the shards are emulated locally:
the decomposition is a compute-path reshape of the one gathered extent,
which is what lets tier-1 pin the seq-parallel exact path BITWISE
against the single-shard reference (ops/attention.py routes exact mode
through per-shard full-extent score GEMMs whose concatenation feeds the
single unsharded softmax — the key axis is never reduced by the score
product, so per-shard score columns are elementwise the unsharded
ones). On a real mesh the per-shard partials are chip-local and only
``(m, l, acc)`` crosses ICI; ``combine_bytes_per_step`` below is the
closed form ``serving_search`` prices that traffic with, next to
kv_fill/prefill_reuse.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

#: additive mask value — must match ops/attention.py's decode mask so a
#: fully-masked shard's combine weight underflows to exactly 0.0
MASK_NEG = -1e30


def shard_segment(extent: int, seq_shards: int) -> int:
    """Tokens per shard of a gathered KV extent partitioned into
    ``seq_shards`` contiguous runs. The extent (``max_blocks_per_slot *
    block_size``) must split evenly — FF006's seq-shard law validates
    ``max_blocks_per_slot % seq_shards == 0`` at engine construction,
    so by the time a decode step runs this cannot raise."""
    if seq_shards < 1:
        raise ValueError(f"seq_shards must be >= 1, got {seq_shards}")
    if extent % seq_shards:
        raise ValueError(
            f"KV extent {extent} does not split into {seq_shards} "
            "contiguous sequence shards (FF006: max_blocks_per_slot "
            "must be divisible by seq_shards)")
    return extent // seq_shards


def decode_shard_partial(q, k_seg, v_seg, mask_seg, sm_scale: float):
    """One shard's online-softmax partial over its contiguous key
    segment: ``q`` (b, h, 1, d), ``k_seg``/``v_seg`` (b, h, seg, d),
    ``mask_seg`` (b, 1, 1, seg) bool. Returns f32 ``(m, l, acc)`` with
    shapes (b, h, 1), (b, h, 1), (b, h, 1, vd) — the same state triple
    the flash-decode kernel's VMEM scratch carries per grid step."""
    import jax.numpy as jnp

    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_seg,
                        preferred_element_type=jnp.float32) * sm_scale
    logits = jnp.where(mask_seg, logits, MASK_NEG)
    m = jnp.max(logits, axis=-1)                      # (b, h, 1)
    p = jnp.exp(logits - m[..., None])                # (b, h, 1, seg)
    l = jnp.sum(p, axis=-1)                           # noqa: E741
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_seg.dtype), v_seg,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def combine_partials(partials: Sequence[Tuple]):
    """The priced combine: merge per-shard ``(m, l, acc)`` into the
    decoded attention output (b, h, 1, vd) f32 — the flash segment-merge
    identity. On a real mesh this is the one cross-shard collective of
    a decode step (an allgather of the partial triples); here it is the
    arithmetic both the emulated path and the pricing agree on."""
    import jax.numpy as jnp

    ms: List = [m for m, _l, _a in partials]
    m_star = ms[0]
    for m in ms[1:]:
        m_star = jnp.maximum(m_star, m)
    l_star = None
    out = None
    for m, l, acc in partials:
        w = jnp.exp(m - m_star)                       # 0.0 exactly for
        lw = l * w                                    # never-written shards
        aw = acc * w[..., None]
        l_star = lw if l_star is None else l_star + lw
        out = aw if out is None else out + aw
    return out / l_star[..., None]


def combine_bytes_per_step(heads: int, vdim: int, slots: int,
                           seq_shards: int, el: int = 4) -> int:
    """Per-chip allgather payload bytes of ONE decode step's partial
    combine for one attention node: each shard contributes, per slot
    per head, the f32 triple ``m`` + ``l`` (2 scalars) and the f32
    ``acc`` row (vdim). This is what ``serving_search`` feeds the ICI
    allgather closed form — per STEP, so it is priced next to the
    per-step KV stream it buys down."""
    if seq_shards <= 1:
        return 0
    return slots * heads * (2 + vdim) * el


def query_bytes_per_step(heads: int, kdim: int, slots: int,
                         el: int) -> int:
    """Per-chip bytes of the single-query-token allgather that starts a
    sequence-parallel decode step: every shard needs the step's q rows
    (slots x heads x kdim at the model element size) before it can score
    its local blocks."""
    return slots * heads * kdim * el
