"""Runtime configuration and CLI flag parsing.

TPU-native analog of the reference's ``FFConfig`` (include/flexflow/config.h:93-162)
and ``FFConfig::parse_args`` (src/runtime/model.cc:~3530-3700). Flag names are kept
reference-compatible, including the Legion-style ``-ll:*`` resource flags, which here
select TPU devices instead of GPUs.
"""
from __future__ import annotations

import dataclasses
import os
import sys
from typing import List, Optional, Sequence

from .ffconst import CompMode, DataType


def _pin_platform_from_env(jax) -> None:
    """Honor JAX_PLATFORMS even when a site hook registered an accelerator
    plugin: the env var alone doesn't stop the hook from dialing the device
    client on the first backend query (which hangs if the device tunnel is
    down); the config update does."""
    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        try:
            jax.config.update("jax_platforms", plats)
        except Exception:
            pass


@dataclasses.dataclass
class FFIterationConfig:
    """Per-iteration knobs (reference: config.h:164-169)."""

    seq_length: int = -1

    def reset(self) -> None:
        self.seq_length = -1


@dataclasses.dataclass
class FFConfig:
    """All runtime configuration (reference: config.h:93-162).

    Device terminology: ``workers_per_node`` counts accelerator chips per host
    (the reference's GPUs-per-node); on TPU a "worker" is one chip.
    """

    # training loop
    epochs: int = 1
    batch_size: int = 64
    print_freq: int = 10
    dataset_path: str = ""

    # devices / topology
    num_nodes: int = 1
    workers_per_node: int = 0  # 0 = use all visible devices
    cpus_per_node: int = 1
    device_memory_mb: int = 0  # analog of -ll:fsize; 0 = query from device

    # auto-parallelization search (Unity)
    search_budget: int = -1
    search_alpha: float = 1.05
    search_overlap_backward_update: bool = False
    computation_mode: CompMode = CompMode.COMP_MODE_TRAINING
    only_data_parallel: bool = False
    enable_sample_parallel: bool = True
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    # TPU-native extension: sequence/context parallelism (ring attention) in
    # the search space; no reference analog (SURVEY §5 long-context)
    enable_sequence_parallel: bool = True
    # TPU-native extension: GPipe (pp, dp) grids as search candidates;
    # the reference reserves OP_PIPELINE but ships no schedule
    enable_pipeline_parallel: bool = True
    enable_inplace_optimizations: bool = True
    search_num_nodes: int = -1
    search_num_workers: int = -1
    base_optimize_threshold: int = 10
    enable_control_replication: bool = True
    python_data_loader_type: int = 2

    # fusion & memory search
    perform_fusion: bool = False
    perform_memory_search: bool = False
    # activation rematerialization (--remat): "" lets the Unity memory
    # search choose the level; "none"/"selective"/"full" force one —
    # Executor remat blocks and PipelineTrainer stages alike
    # (execution/remat.py, docs/remat.md)
    remat: str = ""
    # target compute nodes per remat block (blocks cut at graph
    # bottlenecks; ~one transformer layer at the default)
    remat_segment_size: int = 8
    # pipeline schedule (--schedule, ISSUE 10; docs/pipeline.md): "" lets
    # the Unity search sweep the schedule axis; "gpipe"/"1f1b"/
    # "interleaved" force one — the same flag-beats-searched precedence
    # as --remat (parallel.pipeline.resolve_schedule)
    schedule: str = ""
    # virtual stage chunks per pipeline device for the interleaved
    # schedule (Megatron interleaved-1F1B's v); 0 = default (2 when
    # interleaved is chosen)
    pipeline_virtual_stages: int = 0
    # SPMD collective-compute overlap (--collective-overlap, ISSUE 10):
    # "on" splits the step's gradient synchronization into per-remat-block
    # psums issued as each block's backward completes (bitwise-identical
    # loss/grads to the synchronous path — executor._blockwise_value_and_
    # grad); "off" keeps the synchronous all-reduces at step end
    collective_overlap: str = "off"

    # multi-pod topology + hierarchical search (docs/multipod.md;
    # ISSUE 15). --pods N splits the machine into N DCN-connected pods
    # (each one ICI domain; 0 = keep the detected/parsed topology);
    # --dcn-gbps overrides the per-pod DCN bandwidth in GB/s
    num_pods: int = 0
    dcn_gbps: float = 0.0
    # two-level DCN x ICI strategy search: "auto" (default — on for
    # multi-pod machines at >= 64 chips), "on" (force the decomposition),
    # "off" (always the flat factorization sweep)
    search_hierarchical: str = "auto"

    # machine model for the simulator
    machine_model_version: int = 0
    machine_model_file: str = ""
    simulator_work_space_size: int = 2 * 1024 * 1024 * 1024
    simulator_segment_size: int = 16777216
    simulator_max_num_segments: int = 1

    # strategy import/export (reference: config.h:143-148)
    import_strategy_file: str = ""
    export_strategy_file: str = ""
    export_strategy_task_graph_file: str = ""
    export_strategy_computation_graph_file: str = ""
    include_costs_dot_graph: bool = False
    substitution_json_path: Optional[str] = None

    # observability
    profiling: bool = False
    # Legion Prof analog (-lg:prof / -lg:prof_logfile): when set, fit() runs
    # under jax.profiler.trace writing an XLA/TensorBoard trace here
    profiler_trace_dir: str = ""
    # obs subsystem (flexflow_tpu/obs): Chrome trace-event JSON of host-side
    # phases (compile / step / epoch / eval / search), Perfetto-loadable
    trace_file: str = ""
    # per-run training telemetry JSON (step walls, loss history, compile vs
    # steady split, samples/sec, estimated MFU, XLA peak memory)
    telemetry_file: str = ""
    # Unity/MCMC per-iteration JSONL log (candidate cost, accept/reject,
    # temperature, best-so-far) — mirrors the strategy-export workflow
    search_log_file: str = ""
    perform_auto_mapping: bool = False
    # numerical-safety checks — the TPU analog of the reference's reliance on
    # Legion region coherence for race freedom (SURVEY §5: XLA purity plays
    # that role; this adds jax_debug_nans on top)
    debug_nans: bool = False

    # fault tolerance (flexflow_tpu/resilience, docs/fault_tolerance.md).
    # The reference inherits resilience from Legion's task runtime; here it
    # is a first-class subsystem: preemption-safe async checkpoints,
    # divergence sentinels with rollback, elastic degraded-mesh restart.
    checkpoint_dir: str = ""     # atomic committed checkpoints land here
    checkpoint_every: int = 0    # steps between async checkpoints; 0 = off
    keep_checkpoints: int = 3    # retention: newest N committed kept
    # divergence sentinel: after this many CONSECUTIVE non-finite steps
    # (NaN/Inf loss or grad) auto-restore the last committed checkpoint;
    # 0 disables guarding (no per-step scalar transfer)
    max_bad_steps: int = 0
    # "auto" resumes from the newest committed checkpoint in
    # checkpoint_dir; a path resumes from exactly that checkpoint
    resume: str = ""
    # reduced-LR escape hatch: LR multiplier applied when divergence
    # persists past the first rollback; hard stop after max_rollbacks
    rollback_lr_factor: float = 0.5
    max_rollbacks: int = 3

    # strategy safety (flexflow_tpu/resilience/fallback.py + audit.py,
    # docs/strategy_safety.md). "on" lets a failed strategy degrade through
    # the search's ranked candidates -> dp+full-remat; "off" turns any
    # verification failure into an immediate error. The verification pass
    # only runs when it has something to check (audit / memory budget /
    # chaos injection), so plain fits pay nothing.
    strategy_fallback: str = "on"
    # parallel-correctness audit: one probe batch under the live strategy
    # vs a single-device reference; loss and grad-norm must agree within
    # audit_tol relative error
    audit_strategy: bool = False
    audit_tol: float = 0.05
    # compile-time OOM gate: XLA's compiled peak for the train step must
    # fit this many MiB (0 = disabled; the -ll:fsize analog for the
    # fallback cascade rather than the search)
    memory_budget_mb: int = 0
    # ShardLint static analysis (flexflow_tpu/analysis,
    # docs/static_analysis.md; ISSUE 7). "on" (default): stage 0 of the
    # fallback cascade, candidate pruning in the Unity search, and the
    # pre-serve FF005 check. "strict": additionally analyze EVERY compiled
    # strategy (explicit/imported/searched) and refuse on errors. "off":
    # dynamic checks only (the pre-ISSUE 7 behavior).
    static_analysis: str = "on"

    # closed-loop calibration (flexflow_tpu/obs/drift.py +
    # search/calibration.py, docs/calibration.md; ISSUE 8).
    # --profile-ops PATH arms the ProfiledStep pass: fit() times every
    # distinct op shape on device, streams OpRecords to PATH (JSONL) and
    # feeds the drift sentinel (sim-vs-measured per op-cost cache key)
    profile_ops: str = ""
    # drift band half-width: a key whose rolling measured/predicted ratio
    # leaves [1/(1+tol), 1+tol] raises calibration_drift events and counts
    # in the telemetry "calibration" block
    drift_tolerance: float = 0.25
    # opt-in closed loop: out-of-band drift triggers
    # Simulator.calibrate_from_profile (per-key repair, exact delta-cost
    # cache invalidation), table persistence, and a top-K re-rank
    auto_recalibrate: bool = False
    # replay a --profile-ops JSONL into the search simulator's calibration
    # before searching (and into the fit sentinel's sim)
    calibrate_from_trace: str = ""
    # persistent calibration store: one JSON table per (chip generation,
    # compute dtype), merged across runs so a fleet shares measurements
    calibration_dir: str = ""

    # serving engine (flexflow_tpu/serving, docs/serving.md; ISSUE 6).
    # The reference's only inference artifact is an incomplete Triton
    # prototype — these knobs drive the JAX serving path instead.
    serve: bool = False          # run the examples' serve mode after compile
    # decode-state ring-buffer capacity per slot: prompt + generated tokens
    # must fit; also the largest prefill bucket
    max_decode_len: int = 128
    # continuous-batching decode slots (the in-flight request ceiling);
    # also the serving search's total-slot budget
    max_inflight: int = 8
    # serving-objective SLO: simulated p99 per-token latency bound (ms) for
    # search_all(objective="serving"); 0 = throughput-only
    slo_p99_ms: float = 0.0
    # paged KV cache (flexflow_tpu/serving/kvcache.py, docs/serving.md
    # "Paged KV cache" + docs/decode_perf.md; ISSUE 12).
    # KV-cache layout: "paged" (block pool + per-slot block tables —
    # slot recycling is pointer bookkeeping, decode attention reads
    # O(true_length) through the flash-decode kernel) or "ring" (the
    # legacy per-slot max_len buffers)
    kv_cache: str = "paged"
    # tokens per KV block of the paged layout
    kv_block_size: int = 16
    # paged pool size in blocks (incl. the reserved garbage block);
    # 0 = auto (every slot can hold max_decode_len). Setting it smaller
    # decouples pool occupancy from max_decode_len: admission then waits
    # on free BLOCKS, not just free slots
    kv_pool_blocks: int = 0
    # KV storage dtype: "native" (model dtype; also lets the serving
    # search sweep the int8 axis) or "int8" (pin symmetric per-(token,
    # head) int8 with f32 scales — ~1/el the decode KV bandwidth, judged
    # against a pinned tolerance band instead of the bitwise contract)
    kv_dtype: str = "native"
    # prefix cache + chunked prefill (flexflow_tpu/serving/prefix.py,
    # docs/serving.md "Prefix cache & chunked prefill"; ISSUE 14).
    # Radix-tree prefix reuse over the paged pool: requests sharing a
    # cached prompt prefix (>= one full KV block) map its blocks into
    # their block table with zero prefill compute and prefill only the
    # suffix. "on" (default; paged, attention-only graphs) or "off".
    # The hit path is bitwise the cold path, so enabling it changes no
    # emitted token.
    prefix_cache: str = "on"
    # chunked prefill: prompts/suffixes longer than this many tokens
    # prefill in fixed chunks co-scheduled with decode iterations, so a
    # long prompt stops head-of-line-blocking the continuous batch.
    # Must be a whole number of KV blocks (FF006); 0 = off (one-shot
    # prefill, the legacy behavior).
    prefill_chunk_tokens: int = 0
    # steady-state cap (in pool blocks) on what the prefix trie may
    # retain; 0 = unbounded (LRU eviction still runs under pool
    # pressure either way)
    prefix_cache_blocks: int = 0
    # serving resilience (flexflow_tpu/serving/resilience.py,
    # docs/serving.md "Serving under failure"; ISSUE 9).
    # Per-request completion deadline (ms from submission) defaulted onto
    # every request without an explicit Request.deadline_ms; expired
    # requests are evicted (outcome deadline_exceeded). 0 = no deadline.
    request_timeout_ms: float = 0.0
    # load shedding at admission: "off" (bounded queue only), "deadline"
    # (shed when the EWMA completion estimate blows the request deadline),
    # "queue" (shed at the max_queue//2 high-water mark). Shed requests get
    # a typed OverloadError with a retry_after_ms hint.
    shed_policy: str = "off"
    # graceful SIGTERM drain: in-flight requests may finish for this many
    # seconds before stragglers are evicted as preempted; queued requests
    # are handed back for re-submission either way
    drain_grace_s: float = 5.0
    # decode-health sentinel: retries per request after a quarantined
    # (non-finite) decode slot before the request aborts as decode_fault
    decode_retry_budget: int = 1
    # serve-loop runtime (ISSUE 17, docs/serving.md "Async runtime"):
    # "sync" (reference: block on step k's tokens before dispatching
    # k+1) or "async" (double-buffered: dispatch k+1 while k's transfer
    # is in flight, commit at arrival — bitwise the sync streams under
    # exact decode, at a lower host_overhead_fraction)
    serve_loop: str = "sync"
    # sequence-parallel decode (flexflow_tpu/kernels/seqpar_decode.py,
    # docs/decode_perf.md "Sequence-parallel decode"; ISSUE 18): number
    # of contiguous block-table shards a slot's KV extent is scored
    # across per decode step — the capacity axis for contexts whose
    # paged KV exceeds one chip's HBM. 1 = unsharded (the reference
    # path); requires the paged layout; refused by speculative decoding
    # (SeqShardsError)
    seq_shards: int = 1
    # context-length buckets the serving search prices seq_shards for
    # ("1024,4096,16384" — strictly ascending token counts; admission
    # routes each request to the smallest covering bucket). Empty = no
    # bucketing (one shard width for everything)
    context_buckets: str = ""
    # serving fleet (flexflow_tpu/serving/fleet.py, docs/fleet.md;
    # ISSUE 11). Replica count of the multi-replica router: N independent
    # fault domains behind load-aware dispatch with health-checked
    # failover; 0 = single-engine serving (no fleet layer)
    fleet_replicas: int = 0
    # hedged retries: launch a bounded hedge on a second replica once a
    # request's wait exceeds this percent of its EWMA-predicted service
    # time (first new committed token wins, loser cancelled); 0 = off
    hedge_after_pctl: float = 0.0
    # active health probes: probe-decode every live replica every N fleet
    # ticks (half-open circuit probes run on their own backoff schedule
    # regardless); 0 disables the periodic probe
    health_probe_every: int = 16
    # circuit breaker: consecutive per-replica failures (decode
    # quarantines, dispatch timeouts, failed probes) before the
    # replica's circuit opens and it stops receiving dispatches
    circuit_open_after: int = 3
    # multi-tenant SLO tiers (flexflow_tpu/serving/tenancy.py,
    # docs/multitenant.md; ISSUE 19): override/extend the built-in
    # interactive|standard|batch registry with comma-separated
    # NAME:WEIGHT[:DEADLINE_MS[:QUOTA_TOKENS_PER_S]] entries; empty =
    # the built-in tiers
    tenant_tiers: str = ""
    # backlog-forecast autoscaler on the serving fleet: "on" grows the
    # replica pool when the backlog-EWMA forecast blows the tightest
    # tier SLO and shrinks through migrate-and-drain; "off" (default)
    # keeps the pool fixed
    autoscale: str = "off"
    # autoscaler pool bounds (only meaningful with --autoscale on):
    # 0 = default to the initial fleet size / twice it
    min_replicas: int = 0
    max_replicas: int = 0
    # crash-durable serving (flexflow_tpu/serving/journal.py,
    # docs/durability.md; ISSUE 20). Directory for the fleet door's
    # write-ahead request journal: submits/progress/outcomes survive a
    # process crash and ServingFleet.recover() replays the unfinished
    # backlog. Empty (default) = journal off, allocation-free hot path
    request_journal: str = ""
    # group-commit window in ms: buffered journal records are
    # flushed+fsynced at most once per window (0 = every record is its
    # own fsync — maximum durability, maximum overhead)
    journal_sync_ms: float = 0.0
    # journal a progress record once a stream accumulates this many
    # committed tokens (0 = submits/outcomes only; recovery restarts
    # unfinished streams from token zero)
    journal_commit_every: int = 0

    # TPU-native knobs (no reference analog)
    mesh_shape: Optional[Sequence[int]] = None  # e.g. (8,) or (4, 2)
    mesh_axis_names: Sequence[str] = ("data", "model")
    allow_mixed_precision: bool = True  # bf16 compute where safe
    # compute (activation/matmul) dtype for the jitted step; DT_NONE = follow
    # tensor dtypes. Master weights, loss, and normalization stay float32 —
    # the standard TPU mixed-precision recipe (bf16 on the MXU).
    compute_dtype: DataType = DataType.DT_NONE
    seed: int = 42

    iteration_config: FFIterationConfig = dataclasses.field(
        default_factory=FFIterationConfig
    )

    def __post_init__(self) -> None:
        # under pytest the process argv belongs to the test runner, whose
        # flags collide with ours (pytest's ``-p no:cacheprovider`` would be
        # read as ``--print-freq``); argv[0] basename alone misses
        # ``python -m pytest`` (argv[0] is .../pytest/__main__.py). Only
        # argv[0] is consulted — env markers (PYTEST_CURRENT_TEST) inherit
        # into subprocesses a test launches, and those are real production
        # processes whose flags must parse; same for ``"pytest" in
        # sys.modules``, true in anything that imports pytest transitively
        a0 = sys.argv[0]
        under_pytest = ("pytest" in os.path.basename(a0)
                        or a0.replace(os.sep, "/").endswith(
                            ("pytest/__main__.py", "py.test")))
        argv = sys.argv[1:] if not under_pytest else []
        self.parse_args(argv)
        if self.workers_per_node == 0:
            try:
                import jax

                _pin_platform_from_env(jax)
                self.workers_per_node = max(1, len(jax.devices()) // self.num_nodes)
            except Exception:
                self.workers_per_node = 1

    # -- reference-compatible flag parsing (model.cc:~3530-3700) ---------------
    def parse_args(self, argv: List[str]) -> None:
        seen = set()  # our recognized flags present in THIS argv, for the
        # cross-flag validation below (order-independent, and programmatic
        # attribute assignment stays unvalidated-by-parse on purpose)
        i = 0
        while i < len(argv):
            a = argv[i]
            if a.startswith("-"):
                seen.add(a)

            def _next() -> str:
                nonlocal i
                i += 1
                if i >= len(argv):
                    raise ValueError(f"flag {a} expects a value")
                return argv[i]

            if a in ("-e", "--epochs"):
                self.epochs = int(_next())
            elif a in ("-b", "--batch-size"):
                self.batch_size = int(_next())
            elif a in ("-p", "--print-freq"):
                self.print_freq = int(_next())
            elif a in ("-d", "--dataset"):
                self.dataset_path = _next()
            elif a == "--budget" or a == "--search-budget":
                self.search_budget = int(_next())
            elif a == "--alpha" or a == "--search-alpha":
                self.search_alpha = float(_next())
            elif a == "--only-data-parallel":
                self.only_data_parallel = True
            elif a == "--enable-parameter-parallel":
                self.enable_parameter_parallel = True
            elif a == "--enable-attribute-parallel":
                self.enable_attribute_parallel = True
            elif a == "--disable-sequence-parallel":
                self.enable_sequence_parallel = False
            elif a == "--disable-pipeline-parallel":
                self.enable_pipeline_parallel = False
            elif a == "--fusion":
                self.perform_fusion = True
            elif a == "--memory-search":
                self.perform_memory_search = True
            elif a == "--remat":
                v = _next()
                if v not in ("none", "selective", "full"):
                    raise ValueError(
                        f"--remat expects none|selective|full, got {v!r}")
                self.remat = v
            elif a == "--remat-segment-size":
                self.remat_segment_size = int(_next())
            elif a == "--schedule":
                v = _next()
                if v not in ("gpipe", "1f1b", "interleaved"):
                    raise ValueError(
                        f"--schedule expects gpipe|1f1b|interleaved, "
                        f"got {v!r}")
                self.schedule = v
            elif a == "--virtual-stages":
                self.pipeline_virtual_stages = int(_next())
            elif a == "--collective-overlap":
                v = _next()
                if v not in ("on", "off"):
                    raise ValueError(
                        f"--collective-overlap expects on|off, got {v!r}")
                self.collective_overlap = v
            elif a == "--overlap":
                self.search_overlap_backward_update = True
            elif a == "--import" or a == "--import-strategy":
                self.import_strategy_file = _next()
            elif a == "--export" or a == "--export-strategy":
                self.export_strategy_file = _next()
            elif a == "--pods":
                self.num_pods = int(_next())
            elif a == "--dcn-gbps":
                self.dcn_gbps = float(_next())
            elif a == "--hierarchical-search":
                v = _next()
                if v not in ("auto", "on", "off"):
                    raise ValueError(
                        f"--hierarchical-search expects auto|on|off, "
                        f"got {v!r}")
                self.search_hierarchical = v
            elif a == "--machine-model-version":
                self.machine_model_version = int(_next())
            elif a == "--machine-model-file":
                self.machine_model_file = _next()
            elif a == "--simulator-workspace-size":
                self.simulator_work_space_size = int(_next())
            elif a == "--substitution-json":
                self.substitution_json_path = _next()
            elif a == "--search-num-nodes":
                self.search_num_nodes = int(_next())
            elif a == "--search-num-workers":
                self.search_num_workers = int(_next())
            elif a == "--base-optimize-threshold":
                self.base_optimize_threshold = int(_next())
            elif a == "--compute-dtype":
                from .ffconst import str_to_dtype

                self.compute_dtype = str_to_dtype(_next())
            elif a == "--enable-propagation":
                pass  # legacy MCMC propagation; accepted for compatibility
            elif a == "--disable-control-replication":
                self.enable_control_replication = False
            elif a == "--nodes":
                self.num_nodes = int(_next())
            elif a == "--profiling":
                self.profiling = True
            elif a == "--debug-nans":
                self.debug_nans = True
            elif a == "--checkpoint-dir":
                self.checkpoint_dir = _next()
            elif a == "--checkpoint-every":
                self.checkpoint_every = int(_next())
            elif a == "--keep-checkpoints":
                self.keep_checkpoints = int(_next())
            elif a == "--max-bad-steps":
                self.max_bad_steps = int(_next())
            elif a == "--resume":
                self.resume = _next()
            elif a == "--strategy-fallback":
                v = _next()
                if v not in ("on", "off"):
                    raise ValueError(
                        f"--strategy-fallback expects on|off, got {v!r}")
                self.strategy_fallback = v
            elif a == "--audit-strategy":
                self.audit_strategy = True
            elif a == "--audit-tol":
                self.audit_tol = float(_next())
            elif a == "--memory-budget-mb":
                self.memory_budget_mb = int(_next())
            elif a == "--static-analysis":
                v = _next()
                if v not in ("on", "off", "strict"):
                    raise ValueError(
                        f"--static-analysis expects on|off|strict, got "
                        f"{v!r}")
                self.static_analysis = v
            elif a == "--profile-ops":
                self.profile_ops = _next()
            elif a == "--drift-tolerance":
                self.drift_tolerance = float(_next())
            elif a == "--auto-recalibrate":
                self.auto_recalibrate = True
            elif a == "--calibrate-from-trace":
                self.calibrate_from_trace = _next()
            elif a == "--calibration-dir":
                self.calibration_dir = _next()
            elif a == "--serve":
                self.serve = True
            elif a == "--max-decode-len":
                self.max_decode_len = int(_next())
            elif a == "--max-inflight":
                self.max_inflight = int(_next())
            elif a == "--slo-p99-ms":
                self.slo_p99_ms = float(_next())
            elif a == "--kv-cache":
                v = _next()
                if v not in ("paged", "ring"):
                    raise ValueError(
                        f"--kv-cache expects paged|ring, got {v!r}")
                self.kv_cache = v
            elif a == "--kv-block-size":
                self.kv_block_size = int(_next())
            elif a == "--kv-pool-blocks":
                self.kv_pool_blocks = int(_next())
            elif a == "--kv-dtype":
                v = _next()
                if v not in ("native", "int8"):
                    raise ValueError(
                        f"--kv-dtype expects native|int8, got {v!r}")
                self.kv_dtype = v
            elif a == "--prefix-cache":
                v = _next()
                if v not in ("on", "off"):
                    raise ValueError(
                        f"--prefix-cache expects on|off, got {v!r}")
                self.prefix_cache = v
            elif a == "--prefill-chunk-tokens":
                self.prefill_chunk_tokens = int(_next())
            elif a == "--prefix-cache-blocks":
                self.prefix_cache_blocks = int(_next())
            elif a == "--request-timeout-ms":
                self.request_timeout_ms = float(_next())
            elif a == "--shed-policy":
                v = _next()
                if v not in ("off", "deadline", "queue"):
                    raise ValueError(
                        f"--shed-policy expects off|deadline|queue, got "
                        f"{v!r}")
                self.shed_policy = v
            elif a == "--drain-grace-s":
                self.drain_grace_s = float(_next())
            elif a == "--decode-retry-budget":
                self.decode_retry_budget = int(_next())
            elif a == "--serve-loop":
                v = _next()
                if v not in ("sync", "async"):
                    raise ValueError(
                        f"--serve-loop expects sync|async, got {v!r}")
                self.serve_loop = v
            elif a == "--seq-shards":
                self.seq_shards = int(_next())
                if self.seq_shards < 1:
                    raise ValueError(
                        f"--seq-shards expects an integer >= 1, got "
                        f"{self.seq_shards}")
            elif a == "--context-buckets":
                from .serving.kvcache import parse_context_buckets

                v = _next()
                parse_context_buckets(v)  # fail fast at parse time
                self.context_buckets = v
            elif a == "--fleet-replicas":
                self.fleet_replicas = int(_next())
            elif a == "--hedge-after-pctl":
                self.hedge_after_pctl = float(_next())
            elif a == "--health-probe-every":
                self.health_probe_every = int(_next())
            elif a == "--circuit-open-after":
                self.circuit_open_after = int(_next())
            elif a == "--tenant-tiers":
                from .serving.tenancy import parse_tenant_tiers

                v = _next()
                parse_tenant_tiers(v)  # fail fast at parse time
                self.tenant_tiers = v
            elif a == "--autoscale":
                v = _next()
                if v not in ("on", "off"):
                    raise ValueError(
                        f"--autoscale expects on|off, got {v!r}")
                self.autoscale = v
            elif a == "--min-replicas":
                self.min_replicas = int(_next())
            elif a == "--max-replicas":
                self.max_replicas = int(_next())
            elif a == "--request-journal":
                self.request_journal = _next()
            elif a == "--journal-sync-ms":
                v = float(_next())
                if v < 0:
                    raise ValueError(
                        f"--journal-sync-ms must be >= 0, got {v:g}")
                self.journal_sync_ms = v
            elif a == "--journal-commit-every":
                v = int(_next())
                if v < 0:
                    raise ValueError(
                        f"--journal-commit-every must be >= 0, got {v}")
                self.journal_commit_every = v
            elif a == "--rollback-lr-factor":
                self.rollback_lr_factor = float(_next())
            elif a == "--max-rollbacks":
                self.max_rollbacks = int(_next())
            elif a == "--taskgraph":
                self.export_strategy_task_graph_file = _next()
            elif a == "--include-costs-dot-graph":
                self.include_costs_dot_graph = True
            elif a == "--compgraph":
                self.export_strategy_computation_graph_file = _next()
            elif a == "-ll:gpu" or a == "-ll:tpu":
                self.workers_per_node = int(_next())
            elif a == "-ll:cpu":
                self.cpus_per_node = int(_next())
            elif a == "-ll:fsize":
                self.device_memory_mb = int(_next())
            elif a in ("-ll:zsize", "-ll:util", "-ll:py", "-lg:prof"):
                _next()  # accepted and ignored on TPU
            elif a in ("--profiler-trace", "-lg:prof_logfile"):
                # Legion Prof analog: dump a jax.profiler (XLA/TensorBoard)
                # trace of the training loop to this directory
                self.profiler_trace_dir = _next()
            elif a == "--trace-file":
                self.trace_file = _next()
            elif a == "--telemetry-file":
                self.telemetry_file = _next()
            elif a in ("--search-log", "--search-log-file"):
                self.search_log_file = _next()
            elif a == "--seed":
                self.seed = int(_next())
            elif a == "--mesh-shape":
                self.mesh_shape = tuple(int(x) for x in _next().split("x"))
            # unrecognized flags are ignored, matching the reference's behavior
            i += 1
        self._validate_flag_combos(seen)

    def _validate_flag_combos(self, seen: set) -> None:
        """Fail fast at parse time on flag combinations that would
        otherwise die mid-run with a far worse error (ISSUE 5 satellite).
        Only flags present in the parsed argv are judged — programmatic
        attribute assignment is validated later by
        ``resilience.preflight.preflight_config`` at compile."""
        if "--audit-tol" in seen and not self.audit_strategy:
            raise ValueError(
                "--audit-tol is only meaningful with --audit-strategy; add "
                "--audit-strategy or drop --audit-tol")
        if "--audit-tol" in seen and self.audit_tol <= 0:
            raise ValueError(
                f"--audit-tol must be > 0 (got {self.audit_tol}): it is "
                "the relative loss/grad-norm error budget of the audit")
        if "--keep-checkpoints" in seen and self.keep_checkpoints < 1:
            raise ValueError(
                f"--keep-checkpoints must keep at least 1 committed "
                f"checkpoint (got {self.keep_checkpoints}); retention 0 "
                "would delete the checkpoint --resume and rollback need")
        if "--memory-budget-mb" in seen and self.memory_budget_mb < 0:
            raise ValueError(
                f"--memory-budget-mb must be >= 0 (got "
                f"{self.memory_budget_mb}); 0 disables the check")
        if "--max-decode-len" in seen and self.max_decode_len < 1:
            raise ValueError(
                f"--max-decode-len must be >= 1 (got "
                f"{self.max_decode_len}): it is the decode ring-buffer "
                "capacity every prompt + generation must fit")
        if "--max-inflight" in seen and self.max_inflight < 1:
            raise ValueError(
                f"--max-inflight must be >= 1 (got {self.max_inflight}): "
                "the serving engine needs at least one decode slot")
        if "--slo-p99-ms" in seen and self.slo_p99_ms < 0:
            raise ValueError(
                f"--slo-p99-ms must be >= 0 (got {self.slo_p99_ms}); "
                "0 disables the latency bound")
        if "--kv-block-size" in seen and self.kv_block_size < 1:
            raise ValueError(
                f"--kv-block-size must be >= 1 (got "
                f"{self.kv_block_size}): it is the token granularity of "
                "the paged KV pool")
        if "--kv-pool-blocks" in seen and self.kv_pool_blocks < 0:
            raise ValueError(
                f"--kv-pool-blocks must be >= 0 (got "
                f"{self.kv_pool_blocks}); 0 sizes the pool automatically "
                "(every slot can hold max_decode_len)")
        if "--kv-pool-blocks" in seen and self.kv_cache == "ring":
            raise ValueError(
                "--kv-pool-blocks is only meaningful with --kv-cache "
                "paged; drop it or switch the layout")
        if "--kv-dtype" in seen and self.kv_dtype != "native" and \
                self.kv_cache == "ring":
            raise ValueError(
                "--kv-dtype int8 requires --kv-cache paged (the ring "
                "layout stores the model dtype only)")
        if "--prefix-cache" in seen and self.prefix_cache == "on" and \
                self.kv_cache == "ring":
            raise ValueError(
                "--prefix-cache on requires --kv-cache paged (the ring "
                "layout has no shared block pool to map a cached prefix "
                "into)")
        if "--prefill-chunk-tokens" in seen:
            if self.prefill_chunk_tokens < 0:
                raise ValueError(
                    f"--prefill-chunk-tokens must be >= 0 (got "
                    f"{self.prefill_chunk_tokens}); 0 disables chunked "
                    "prefill (one-shot prompts)")
            if self.prefill_chunk_tokens and self.kv_cache == "ring":
                raise ValueError(
                    "--prefill-chunk-tokens requires --kv-cache paged "
                    "(chunks write into the block pool)")
            if self.prefill_chunk_tokens % max(self.kv_block_size, 1):
                raise ValueError(
                    f"--prefill-chunk-tokens ({self.prefill_chunk_tokens}"
                    f") must be a multiple of --kv-block-size "
                    f"({self.kv_block_size}) — a chunk boundary inside a "
                    "KV block would split one block's rows across two "
                    "chunk programs (FF006)")
        if "--prefix-cache-blocks" in seen:
            if self.prefix_cache_blocks < 0:
                raise ValueError(
                    f"--prefix-cache-blocks must be >= 0 (got "
                    f"{self.prefix_cache_blocks}); 0 leaves trie "
                    "retention unbounded (pressure eviction still runs)")
            if self.prefix_cache_blocks and self.prefix_cache == "off":
                raise ValueError(
                    "--prefix-cache-blocks is only meaningful with "
                    "--prefix-cache on; drop it or enable the cache")
        if "--request-timeout-ms" in seen and self.request_timeout_ms < 0:
            raise ValueError(
                f"--request-timeout-ms must be >= 0 (got "
                f"{self.request_timeout_ms}); 0 disables per-request "
                "deadlines")
        if "--drain-grace-s" in seen and self.drain_grace_s < 0:
            raise ValueError(
                f"--drain-grace-s must be >= 0 (got {self.drain_grace_s}): "
                "it bounds how long in-flight requests may finish after "
                "SIGTERM (0 = evict immediately)")
        if "--decode-retry-budget" in seen and self.decode_retry_budget < 0:
            raise ValueError(
                f"--decode-retry-budget must be >= 0 (got "
                f"{self.decode_retry_budget}); 0 aborts a poisoned "
                "request on its first quarantined decode")
        if "--seq-shards" in seen and self.seq_shards > 1 and \
                self.kv_cache == "ring":
            raise ValueError(
                "--seq-shards > 1 requires --kv-cache paged (the ring "
                "layout has no block tables to partition into per-shard "
                "contiguous runs)")
        if "--context-buckets" in seen and self.context_buckets and \
                self.kv_cache == "ring":
            raise ValueError(
                "--context-buckets requires --kv-cache paged (buckets "
                "route requests to sequence-sharded block-table "
                "partitions)")
        if "--fleet-replicas" in seen and self.fleet_replicas < 0:
            raise ValueError(
                f"--fleet-replicas must be >= 0 (got "
                f"{self.fleet_replicas}); 0 serves through a single "
                "engine with no fleet layer")
        if "--hedge-after-pctl" in seen and self.hedge_after_pctl < 0:
            raise ValueError(
                f"--hedge-after-pctl must be >= 0 (got "
                f"{self.hedge_after_pctl}): it is the percent of the "
                "EWMA-predicted service time a request may wait before "
                "it is hedged on a second replica (0 disables hedging)")
        if "--health-probe-every" in seen and self.health_probe_every < 0:
            raise ValueError(
                f"--health-probe-every must be >= 0 (got "
                f"{self.health_probe_every}); 0 disables the periodic "
                "probe (half-open circuit probes still run)")
        if "--circuit-open-after" in seen and self.circuit_open_after < 1:
            raise ValueError(
                f"--circuit-open-after must be >= 1 (got "
                f"{self.circuit_open_after}): the circuit opens after "
                "this many consecutive per-replica failures")
        if "--min-replicas" in seen and self.min_replicas < 1:
            raise ValueError(
                f"--min-replicas must be >= 1 (got "
                f"{self.min_replicas}): the autoscaler never shrinks "
                "below this pool size")
        if "--max-replicas" in seen and self.max_replicas < 1:
            raise ValueError(
                f"--max-replicas must be >= 1 (got "
                f"{self.max_replicas}): the autoscaler never grows "
                "past this pool size")
        if ("--min-replicas" in seen or "--max-replicas" in seen) \
                and self.autoscale != "on":
            raise ValueError(
                "--min-replicas/--max-replicas bound the autoscaler's "
                "pool and are only meaningful with --autoscale on")
        if "--min-replicas" in seen and "--max-replicas" in seen \
                and self.max_replicas < self.min_replicas:
            raise ValueError(
                f"--max-replicas ({self.max_replicas}) must be >= "
                f"--min-replicas ({self.min_replicas})")
        if "--request-journal" in seen and not self.request_journal:
            raise ValueError(
                "--request-journal needs a directory path: it is where "
                "the fleet door's write-ahead request journal lives "
                "(docs/durability.md)")
        if ("--journal-sync-ms" in seen or
                "--journal-commit-every" in seen) \
                and not self.request_journal:
            raise ValueError(
                "--journal-sync-ms/--journal-commit-every tune the "
                "write-ahead request journal and are only meaningful "
                "with --request-journal DIR")
        if "--virtual-stages" in seen:
            if self.pipeline_virtual_stages < 2:
                raise ValueError(
                    f"--virtual-stages must be >= 2 (got "
                    f"{self.pipeline_virtual_stages}): v=1 IS the 1f1b "
                    "schedule — drop the flag and use --schedule 1f1b")
            if self.schedule != "interleaved":
                raise ValueError(
                    "--virtual-stages only applies to the interleaved "
                    "schedule; add --schedule interleaved or drop "
                    "--virtual-stages")
        if "--pods" in seen and self.num_pods < 1:
            raise ValueError(
                f"--pods must be >= 1 (got {self.num_pods}): it is the "
                "number of DCN-connected ICI domains the machine is "
                "split into (1 = single pod)")
        if "--dcn-gbps" in seen and self.dcn_gbps <= 0:
            raise ValueError(
                f"--dcn-gbps must be > 0 (got {self.dcn_gbps}): it is "
                "the per-pod cross-DCN bandwidth in GB/s the cost model "
                "prices cross-pod collectives with")
        if "--dcn-gbps" in seen and self.num_pods < 2 and \
                not self.machine_model_file:
            raise ValueError(
                "--dcn-gbps needs a multi-pod topology to apply to: add "
                "--pods N with N >= 2 (or a --machine-model-file with "
                "num_pods)")
        if "--drift-tolerance" in seen and self.drift_tolerance <= 0:
            raise ValueError(
                f"--drift-tolerance must be > 0 (got "
                f"{self.drift_tolerance}): it is the half-width of the "
                "sim-vs-measured band [1/(1+tol), 1+tol] the drift "
                "sentinel alerts on")
        if "--drift-tolerance" in seen and not (self.profile_ops or
                                                self.auto_recalibrate):
            raise ValueError(
                "--drift-tolerance is only meaningful with --profile-ops "
                "(the drift sentinel judges profiled passes); add "
                "--profile-ops PATH or drop --drift-tolerance")
        if "--auto-recalibrate" in seen and not self.profile_ops:
            raise ValueError(
                "--auto-recalibrate needs --profile-ops PATH: the closed "
                "loop repairs calibration from the profiled pass's "
                "measurements")
        if "--calibrate-from-trace" in seen and \
                not os.path.isfile(self.calibrate_from_trace):
            raise ValueError(
                f"--calibrate-from-trace {self.calibrate_from_trace!r}: "
                "no such profile file (produce one with --profile-ops)")
        if "--resume" in seen:
            if self.resume == "auto" and not self.checkpoint_dir:
                raise ValueError(
                    "--resume auto needs --checkpoint-dir to know where "
                    "committed checkpoints live; pass --checkpoint-dir DIR "
                    "or give --resume an explicit step_N checkpoint path")
            if self.resume != "auto" and not os.path.isdir(self.resume):
                raise ValueError(
                    f"--resume {self.resume!r}: no such checkpoint "
                    "directory; pass 'auto' (with --checkpoint-dir) or an "
                    "existing step_N path")

    # -- derived properties -----------------------------------------------------
    def get_current_time(self) -> float:
        """Microsecond wall clock (reference: flexflow_cffi.py:559, the
        Realm timer the examples use for ELAPSED TIME prints)."""
        import time

        return time.perf_counter() * 1e6

    @property
    def total_workers(self) -> int:
        return self.num_nodes * self.workers_per_node

    def numpy_seed(self) -> int:
        return self.seed
