"""Loss functions.

Reference: src/loss_functions/loss_functions.cc — ``Loss::backward`` seeds
dLoss/dlogits for 4 loss types (enum ffconst.h:39-45) with hand-written CUDA
kernels. TPU-native: the loss is a scalar-valued pure function; sharded
autodiff derives the seed, and when the batch dim is sharded XLA inserts the
cross-shard mean (the reference's scale-by-1/batch + PS/NCCL reduction).
"""
from __future__ import annotations

from ..ffconst import LossType


class Loss:
    """API-parity wrapper (reference: include/flexflow/loss_functions.h)."""

    def __init__(self, loss_type: LossType, repl_labels: bool = False):
        self.loss_type = loss_type
        # replicate labels when final op is AGG_SPEC (reference model.cc:2875-2877)
        self.repl_labels = repl_labels

    def __call__(self, logits, labels):
        return loss_value(self.loss_type, logits, labels, self.repl_labels)


def loss_value(loss_type: LossType, logits, labels, repl_labels: bool = False):
    import jax.numpy as jnp
    import jax.nn as jnn

    if repl_labels:
        k = logits.shape[0] // labels.shape[0]
        labels = jnp.repeat(labels, k, axis=0)

    if loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
        # logits here are post-softmax probabilities (the reference applies
        # softmax as a graph op and the loss consumes probs, loss_functions.cu)
        # Token-level targets (causal LM: (b, s, vocab) probs vs (b, s)
        # labels) flatten to one class axis — same math as the (b, vocab)
        # classification case.
        labels = labels.reshape(-1)
        logp = jnp.log(jnp.clip(
            logits.reshape(-1, logits.shape[-1]), 1e-12, 1.0))
        nll = -jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[:, None], axis=-1)
        return jnp.mean(nll)
    if loss_type == LossType.LOSS_CATEGORICAL_CROSSENTROPY:
        logp = jnp.log(jnp.clip(logits, 1e-12, 1.0))
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))
    if loss_type == LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE:
        return jnp.mean(jnp.square(logits - labels))
    if loss_type == LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE:
        # sum over features, mean over batch (reference: mse sum-reduce kernel)
        return jnp.mean(jnp.sum(jnp.square(logits - labels),
                                axis=tuple(range(1, logits.ndim))))
    if loss_type == LossType.LOSS_IDENTITY:
        return jnp.mean(logits)
    raise ValueError(f"unknown loss {loss_type}")
