"""Dynamic recompilation: re-shape the model mid-training on a trigger.

Rebuild of the reference's RecompileState (include/flexflow/recompile.h:26-41,
FFModel::recompile_on_condition model.cc:2422; used by the MoE cache example
moe.cc:180,204): a user ``trigger`` function inspects training state each
iteration; when it fires, ``alter`` mutates the model (e.g. change MoE
capacity) and the graph is recompiled. TPU-native: altering attrs and calling
``FFModel.recompile()`` rebuilds the jitted step — jax recompiles only the
changed computation (cache keyed by the new graph).
"""
from __future__ import annotations

from typing import Callable


class RecompileState:
    """reference: recompile.h:26-41."""

    def __init__(self, trigger: Callable[["RecompileState"], bool],
                 alter: Callable[["RecompileState"], None], ffmodel=None):
        self._trigger = trigger
        self._alter = alter
        self.ffmodel = ffmodel
        self.recompilations = 0

    def trigger(self) -> bool:
        return bool(self._trigger(self))

    def alter(self, ffmodel=None) -> None:
        self._alter(self)
        self.recompilations += 1


def recompile(ffmodel) -> None:
    """Rebuild executor + jitted steps after attrs/graph edits, keeping the
    current parameter values where names and shapes still match."""
    old_params = ffmodel.params
    old_opt = ffmodel.opt_state
    # strategy is re-selected: the altered graph has fresh node ids
    ffmodel.compile(optimizer=ffmodel.optimizer,
                    loss_type=ffmodel.loss_type,
                    metrics=ffmodel.metrics_obj.measures
                    if ffmodel.metrics_obj else None)
    if old_params:
        import jax

        for lname, ws in old_params.items():
            if lname not in ffmodel.params:
                continue
            for wname, arr in ws.items():
                cur = ffmodel.params[lname].get(wname)
                if cur is not None and cur.shape == arr.shape:
                    ffmodel.params[lname][wname] = arr
    del old_opt
