"""Activation rematerialization: leveled ``jax.checkpoint`` policies as a
first-class, *searched* training knob.

The reference trades activation memory for recompute only implicitly (Legion
instance eviction); modern practice makes it a planned decision — Checkmate
(Jain et al., MLSys'20) optimizes what to recompute jointly with the
schedule, and selective recomputation (Korthikanti et al., 2022) recovers
most transformer activation memory for a few percent extra flops. JAX ships
the mechanism (``jax.checkpoint`` with save policies); this module makes it
a plan the Unity memory search can choose per strategy instead of the
all-or-nothing full remat previously hard-coded in ``PipelineTrainer``:

* ``none``       — save every residual (the default training regime).
* ``selective``  — save matmul/contraction outputs, recompute the cheap
  elementwise/norm/softmax tail (``jax.checkpoint_policies.dots_saveable``).
* ``full``       — save only remat-block boundaries, recompute everything
  (``nothing_saveable``) — the classic GPipe/full-remat trade.

One accounting contract, three consumers: ``remat_segments`` below is the
single segmentation used by the Executor's checkpointed forward, by
``Simulator.simulate``'s analytic peak (boundary + recompute transient), and
— via ``Simulator.remat_keep_fraction`` — by ``unity``'s DP tables and
pipeline stage-memory estimate, so the search prices exactly what the
executor runs. See ``docs/remat.md``.
"""
from __future__ import annotations

import dataclasses
from typing import List

from ..ffconst import OperatorType

# the searched axis, in preference order for cost ties (none is fastest)
REMAT_LEVELS = ("none", "selective", "full")

# ops whose outputs the `selective` policy keeps resident (MXU-bound
# contractions — recomputing them would double the expensive flops; the
# elementwise/norm/softmax/gather tail between them is the cheap recompute).
# THE single source: simulator._MATMUL_OPS aliases this set, so the MXU
# roofline classification and the analytic keep-fraction always match what
# the dots_saveable policy actually saves (dot_general outputs; an
# embedding gather is NOT a dot and is recomputed).
REMAT_SAVEABLE_OPS = {
    OperatorType.OP_LINEAR, OperatorType.OP_CONV2D,
    OperatorType.OP_BATCHMATMUL, OperatorType.OP_MULTIHEAD_ATTENTION,
    OperatorType.OP_GROUP_BY, OperatorType.OP_AGGREGATE,
    OperatorType.OP_AGG_SPEC, OperatorType.OP_EXPERTS,
}


@dataclasses.dataclass(frozen=True)
class RematPlan:
    """A rematerialization plan for one training step.

    ``level`` is one of REMAT_LEVELS; ``segment_size`` is the target number
    of compute nodes per remat block (blocks cut at graph bottlenecks, so a
    transformer layer's ~8-node body lands in one block by default)."""

    level: str = "none"
    segment_size: int = 8

    def __post_init__(self):
        if self.level not in REMAT_LEVELS:
            raise ValueError(
                f"remat level {self.level!r} not in {REMAT_LEVELS}")


def checkpoint_policy(level: str):
    """The jax.checkpoint save policy for a remat level (None = do not wrap:
    the ``none`` level must stay zero-overhead, not an everything_saveable
    wrapper XLA still has to look through)."""
    if level == "none":
        return None
    import jax

    if level == "selective":
        return jax.checkpoint_policies.dots_saveable
    if level == "full":
        return jax.checkpoint_policies.nothing_saveable
    raise ValueError(f"unknown remat level {level!r}")


def wrap_remat(fn, level: str):
    """Wrap a pure forward function in jax.checkpoint at ``level``
    (identity for ``none``). Used by PipelineTrainer's stage functions —
    the leveled replacement for its previous hand-rolled full-remat VJP."""
    policy = checkpoint_policy(level)
    if policy is None:
        return fn
    import jax

    return jax.checkpoint(fn, policy=policy)


def remat_segments(pcg, segment_size: int = 8) -> List[List[int]]:
    """Contiguous remat blocks over the PCG's compute nodes, cut at graph
    bottlenecks (a bottleneck's output is the only live tensor at the cut,
    so block boundaries are the cheapest tensors to save). Falls back to a
    forced cut at 4x segment_size when a graph has no bottlenecks (e.g.
    dense residual meshes), bounding the recompute transient.

    This is THE segmentation: the Executor checkpoints exactly these blocks
    and the Simulator's full-remat memory model prices exactly these
    boundaries, so analytic deltas track XLA's."""
    nodes = pcg.compute_nodes()
    if not nodes:
        return []
    bns = set(pcg.bottlenecks())
    segs: List[List[int]] = [[]]
    count = 0
    for n in nodes:
        segs[-1].append(n.guid)
        count += 1
        if count >= max(segment_size, 1) and n.guid in bns \
                or count >= 4 * max(segment_size, 1):
            segs.append([])
            count = 0
    if not segs[-1]:
        segs.pop()
    return segs


def resolve_remat_plan(config, strategy) -> RematPlan:
    """The executor's plan: the ``--remat`` flag wins, then the searched
    strategy's level, then none. Strategy.remat == "" means UNSET (an
    imported/unsearched strategy), distinct from a searched "none".
    ``remat_segment_size`` (config attr) sizes the blocks."""
    level = (getattr(config, "remat", "") or "").strip() \
        or getattr(strategy, "remat", "") or "none"
    return RematPlan(level=level,
                     segment_size=int(getattr(config, "remat_segment_size",
                                              8) or 8))


def resolve_stage_remat(config, strategy) -> str:
    """The pipeline trainer's stage-level remat: flag > searched level >
    ``full`` (the pre-leveled PipelineTrainer behavior — stages always
    rematerialized their forward, and an UNSEARCHED pipeline strategy
    (remat == "") keeps that; only an explicit searched/forced "none"
    turns stage remat off)."""
    level = (getattr(config, "remat", "") or "").strip() \
        or getattr(strategy, "remat", "") or "full"
    if level not in REMAT_LEVELS:
        raise ValueError(f"remat level {level!r} not in {REMAT_LEVELS}")
    return level
