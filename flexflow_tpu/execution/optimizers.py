"""Optimizers: SGD (momentum/nesterov) and Adam.

Reference: src/runtime/optimizer.cc (608 LoC) + optimizer_kernel.cu — per-weight
update tasks in two sync modes (parameter-server and NCCL allreduce,
optimizer_kernel.cu:88,196). TPU-native: a pure ``(params, grads, state) ->
(params, state)`` pytree transform; gradient synchronization disappears into
sharded autodiff (psum on the data axis), so both reference sync modes collapse
into the same code path. The FlexFlow class surface (SGDOptimizer/AdamOptimizer
with ``next()`` per-step hyperparameter schedule, optimizer.h:27-96) is kept.
"""
from __future__ import annotations



class Optimizer:
    def init_state(self, params):
        raise NotImplementedError

    def next(self, state):
        """Per-step hyperparameter advance (reference: AdamOptimizer::next,
        optimizer.cc — updates alpha_t); returns new state."""
        return state

    def update(self, params, grads, state):
        raise NotImplementedError

    def set_learning_rate(self, lr: float) -> None:
        """reference: optimizer.h set_learning_rate (used by the Keras
        LearningRateScheduler callback). The jitted train step bakes the
        rate in as a constant, so callers must rebuild it — the keras fit
        loop watches ``_lr_changed``."""
        if hasattr(self, "lr"):
            self.lr = float(lr)
        else:
            self.alpha = float(lr)
        self._lr_changed = True


class SGDOptimizer(Optimizer):
    """reference: optimizer.h:36-60 (lr, momentum, nesterov, weight_decay)."""

    def __init__(self, ffmodel=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def init_state(self, params):
        import jax
        import jax.numpy as jnp

        if self.momentum == 0.0:
            return {"step": 0}
        return {"step": 0,
                "velocity": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, params, grads, state):
        import jax

        lr, mom, wd = self.lr, self.momentum, self.weight_decay

        if mom == 0.0:
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * (g + wd * p), params, grads)
            return new_params, {"step": state["step"] + 1}

        def upd(p, g, v):
            g = g + wd * p
            v_new = mom * v + g
            step = (g + mom * v_new) if self.nesterov else v_new
            return p - lr * step, v_new

        flat = jax.tree_util.tree_map(upd, params, grads, state["velocity"])
        new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                            is_leaf=lambda t: isinstance(t, tuple))
        new_vel = jax.tree_util.tree_map(lambda t: t[1], flat,
                                         is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": state["step"] + 1, "velocity": new_vel}


class AdamOptimizer(Optimizer):
    """reference: optimizer.h:77-96 (alpha, beta1, beta2, weight_decay,
    epsilon; alpha_t bias-corrected schedule via ``next()``, optimizer.cc).

    ``moment_dtype``: TPU-native extension beyond the reference — store the
    m/v moments in a reduced dtype (e.g. ``jnp.bfloat16``). The update math
    stays f32 (moments are upcast, the fresh values rounded once at store),
    but the optimizer's HBM traffic drops from ~28 to ~16 bytes/param —
    Adam is HBM-bound at double-digit % of a BERT-Large step (BASELINE.md
    breakdown), so this is a measured throughput knob. None (default) keeps
    exact reference numerics; the bench's headline always uses None and
    reports the extension as a separate leg."""

    def __init__(self, ffmodel=None, alpha: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0,
                 epsilon: float = 1e-8, moment_dtype=None):
        self.alpha = alpha
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon
        self.moment_dtype = moment_dtype

    def init_state(self, params):
        import jax
        import jax.numpy as jnp

        dt = self.moment_dtype

        def zeros(p):
            return jnp.zeros_like(p, dtype=dt) if dt is not None \
                else jnp.zeros_like(p)

        return {"step": 0,
                "m": jax.tree_util.tree_map(zeros, params),
                "v": jax.tree_util.tree_map(zeros, params)}

    def update(self, params, grads, state):
        import jax
        import jax.numpy as jnp

        step = state["step"] + 1
        b1, b2, eps, wd = self.beta1, self.beta2, self.epsilon, self.weight_decay
        # bias-corrected alpha_t exactly as the reference's next() computes it
        alpha_t = self.alpha * jnp.sqrt(1.0 - b2 ** step) / (1.0 - b1 ** step)
        dt = self.moment_dtype

        def upd(p, g, m, v):
            g = g + wd * p
            if dt is not None:  # f32 math over reduced-precision storage
                # explicitly f32, NOT p.dtype: with bf16 params the (1-b2)
                # g^2 contributions would fall below bf16's mantissa and v
                # would stop accumulating
                m = m.astype(jnp.float32)
                v = v.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            p_new = p - alpha_t * m_new / (jnp.sqrt(v_new) + eps)
            if dt is not None:
                m_new = m_new.astype(dt)
                v_new = v_new.astype(dt)
            return p_new, m_new, v_new

        trip = jax.tree_util.tree_map(upd, params, grads, state["m"], state["v"])
        is_leaf = lambda t: isinstance(t, tuple)
        new_params = jax.tree_util.tree_map(lambda t: t[0], trip, is_leaf=is_leaf)
        new_m = jax.tree_util.tree_map(lambda t: t[1], trip, is_leaf=is_leaf)
        new_v = jax.tree_util.tree_map(lambda t: t[2], trip, is_leaf=is_leaf)
        return new_params, {"step": step, "m": new_m, "v": new_v}
