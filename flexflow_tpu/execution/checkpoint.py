"""Checkpoint / resume: sharded pytree checkpoints + strategy file.

The reference has no model checkpoint format (SURVEY §5) — only
get_tensor/set_tensor weight access (parallel_tensor.cc:650,698) and strategy
export (--export-strategy). This module supplies the TPU-native equivalent and
the natural extension: orbax checkpoints of the sharded (params, opt_state)
pytree plus the strategy JSON, restoring each shard directly to its owner
device (no host gather).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional


def save_checkpoint(ffmodel, directory: str, step: int = 0) -> str:
    """Save params + optimizer state + strategy + metadata."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    path = os.path.join(directory, f"step_{step}")
    os.makedirs(directory, exist_ok=True)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(path, "params"), ffmodel.params, force=True)
    ckptr.save(os.path.join(path, "opt_state"), ffmodel.opt_state, force=True)
    with open(os.path.join(path, "strategy.json"), "w") as f:
        f.write(ffmodel.strategy.to_json(ffmodel.pcg))
    meta = {"step": step,
            "mesh_shape": list(ffmodel.strategy.mesh_shape),
            "axis_names": list(ffmodel.strategy.axis_names)}
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    return path


def restore_checkpoint(ffmodel, path: str) -> int:
    """Restore into a compiled model; shards land on their owner devices via
    restore_args built from the model's current shardings."""
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    ffmodel.params = ckptr.restore(os.path.join(path, "params"),
                                   item=ffmodel.params)
    ffmodel.opt_state = ckptr.restore(os.path.join(path, "opt_state"),
                                      item=ffmodel.opt_state)
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)["step"]


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for d in os.listdir(directory):
        if d.startswith("step_"):
            try:
                steps.append((int(d.split("_")[1]), d))
            except ValueError:
                pass
    if not steps:
        return None
    return os.path.join(directory, max(steps)[1])
