"""Preemption-safe checkpoint / resume: atomic sharded checkpoints.

The reference leans on Legion's resilient task runtime and ships no model
checkpoint format (SURVEY §5) — only get_tensor/set_tensor weight access
(parallel_tensor.cc:650,698) and strategy export (--export-strategy). This
module is the TPU-native resilience equivalent (ISSUE 4), built for training
on *preemptible* TPU pools where a SIGTERM can land at any step:

* **Atomic commit**: every checkpoint is staged in a ``step_N.tmp.<pid>``
  directory, fsynced, stamped with a ``COMMIT`` marker (carrying the
  checksum of ``meta.json``), and renamed into place. A killed writer can
  only ever leave a ``.tmp`` directory behind; ``latest_checkpoint`` ignores
  anything without a valid marker, so resume never reads a torn checkpoint.
* **Content checksums**: ``meta.json`` records a crc32 per payload file;
  ``restore_checkpoint`` verifies them before touching model state and
  raises ``CheckpointCorruptError`` on any mismatch (bit rot, truncation,
  a half-copied rsync).
* **Background async save**: ``CheckpointManager`` snapshots the
  params/opt_state pytrees with cheap *device-side copies* (donation-safe:
  the jitted step donates its input buffers, so holding references to the
  live trees across a step would read freed buffers) and serializes them on
  a worker thread — the step loop never blocks on host transfer or disk.
  The hand-off queue is bounded; when serialization falls behind, the next
  ``save_async`` blocks (backpressure) instead of accumulating unbounded
  snapshot memory.
* **Retention**: ``prune_checkpoints`` keeps the newest N committed
  checkpoints (``--keep-checkpoints``) and sweeps stale ``.tmp`` staging
  dirs.
* **Exact resume**: ``train_state.json`` carries the data-pipeline cursor
  (epoch, batch-in-epoch, rng counter, global step) so ``--resume auto``
  continues the exact sample stream and dropout key sequence.

Tensor payloads go through orbax; ``restore_checkpoint`` builds orbax
``restore_args`` from the compiled model's *current* shardings (each shard
lands directly on its owner device, no host gather) and accepts a ``mesh=``
override — the degraded-topology path (``resilience/elastic.py``) restores
host-staged onto a freshly searched strategy. See ``docs/fault_tolerance.md``.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import threading
from queue import Queue
from typing import Any, Dict, List, Optional, Tuple

# low-level durable-io idioms extracted to utils/durable_io.py (ISSUE 20:
# one implementation shared with the serving request journal); the old
# underscore names stay importable — they are this module's API to the
# chaos harness and the resilience tests
from ..utils.durable_io import (STALE_TMP_AGE_S,  # noqa: F401
                                crc_file as _crc_file,
                                fsync_path as _fsync_path,
                                write_json as _write_json)

COMMIT_MARKER = "COMMIT"
_STEP_RE = re.compile(r"^step_(\d+)$")
_FORMAT_VERSION = 1


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed commit-marker or checksum validation."""


def _payload_files(root: str) -> List[str]:
    """Relative paths of every checksummed file under a staged checkpoint
    (everything except meta.json and the commit marker, which carry the
    checksums / the checksum-of-checksums)."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            if rel in ("meta.json", COMMIT_MARKER):
                continue
            out.append(rel)
    return sorted(out)


def _dir_checksums(root: str) -> Dict[str, List[int]]:
    return {rel: list(_crc_file(os.path.join(root, rel)))
            for rel in _payload_files(root)}


# ----------------------------------------------------------------- snapshots
def _device_snapshot(tree):
    """Donation-safe snapshot: a device-side copy of every jax array leaf.

    The training step is jitted with ``donate_argnums=(0, 1)`` — the params
    and opt_state buffers handed to the *next* step are invalidated by it, so
    a checkpoint writer cannot hold references to the live trees across
    steps. A device copy is cheap (HBM bandwidth, dispatched async) and the
    copy is never fed back into the step, so the background writer can read
    it whenever the disk catches up (Check-N-Run's decoupled-snapshot idea,
    NSDI'22)."""
    import jax
    import jax.numpy as jnp

    def snap(x):
        if isinstance(x, jax.Array):
            return jnp.copy(x)
        return x

    return jax.tree_util.tree_map(snap, tree)


# The FF002 donation-aliasing contract (analysis/rules.
# donation_spec_for_training) reads this flag rather than hardcoding it:
# it is True because CheckpointManager.save_async routes every retained
# tree through _device_snapshot above. Bypass the snapshot (or flip this
# without doing so) and ShardLint flags the post-step reference to a
# donated buffer — the PR 4 bug class.
SNAPSHOT_DEVICE_COPY = True


# -------------------------------------------------------------------- saving
def save_checkpoint(ffmodel, directory: str, step: int = 0,
                    train_state: Optional[Dict[str, Any]] = None,
                    params=None, opt_state=None) -> str:
    """Atomically save params + optimizer state + strategy + metadata.

    Protocol: stage everything under ``step_N.tmp.<pid>``, fsync the
    payloads, write ``meta.json`` (step, mesh topology, per-file crc32s),
    write the ``COMMIT`` marker (crc of meta.json), fsync, then rename the
    staging dir to ``step_N`` and fsync the parent. A crash at any point
    leaves either the previous committed ``step_N`` or an ignorable
    ``.tmp`` dir — never a torn checkpoint.

    ``params``/``opt_state`` default to the live model trees; the async
    manager passes donation-safe snapshots instead. ``train_state`` is the
    exact-resume cursor (epoch, batch_in_epoch, rng_counter, step).
    """
    import orbax.checkpoint as ocp

    params = ffmodel.params if params is None else params
    opt_state = ffmodel.opt_state if opt_state is None else opt_state
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"step_{int(step)}")
    tmp = f"{final}.tmp.{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(tmp, "params"), params, force=True)
        ckptr.save(os.path.join(tmp, "opt_state"), opt_state, force=True)
        with open(os.path.join(tmp, "strategy.json"), "w") as f:
            f.write(ffmodel.strategy.to_json(ffmodel.pcg))
        if train_state is not None:
            _write_json(os.path.join(tmp, "train_state.json"),
                        train_state, fsync=False)
        for rel in _payload_files(tmp):
            _fsync_path(os.path.join(tmp, rel))
        import numpy as np

        meta = {
            "format_version": _FORMAT_VERSION,
            "step": int(step),
            "mesh_shape": list(ffmodel.strategy.mesh_shape),
            "axis_names": list(ffmodel.strategy.axis_names),
            "n_devices": int(np.prod(ffmodel.strategy.mesh_shape)),
            "checksums": _dir_checksums(tmp),
        }
        _write_json(os.path.join(tmp, "meta.json"), meta)
        meta_crc, _ = _crc_file(os.path.join(tmp, "meta.json"))
        _write_json(os.path.join(tmp, COMMIT_MARKER),
                    {"meta_crc32": meta_crc})
        _fsync_path(tmp)
        if os.path.isdir(final):  # overwrite semantics (re-save of a step)
            shutil.rmtree(final)
        os.replace(tmp, final)
        _fsync_path(directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


# ----------------------------------------------------------------- inspection
def read_meta(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)


def read_train_state(path: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(path, "train_state.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def restore_train_cursor(ffmodel, path: str) -> Dict[str, Any]:
    """Apply the exact-resume cursor recorded in ``train_state.json`` to the
    model (today: the rng counter, so dropout key streams replay) and return
    the cursor dict ({} when the checkpoint has none). THE single
    implementation — resume, rollback and elastic restart all go through
    here, so a new cursor field is restored on every path at once."""
    ts = read_train_state(path) or {}
    if "rng_counter" in ts:
        ffmodel._rng_counter = int(ts["rng_counter"])
    return ts


def is_committed(path: str) -> bool:
    """Commit-marker check: the marker must exist and its recorded crc must
    match the on-disk ``meta.json`` (a marker copied next to a torn meta
    does not count).

    Migration: checkpoints written by the pre-atomic format carry no
    marker (and no ``format_version``/``checksums`` in meta) — an intact
    legacy checkpoint is accepted as committed rather than mislabeled a
    partial write; torn legacy writes were never detectable, which is
    unchanged. Anything whose meta declares ``format_version`` REQUIRES
    its marker."""
    marker = os.path.join(path, COMMIT_MARKER)
    meta = os.path.join(path, "meta.json")
    if not os.path.isfile(meta):
        return False
    if not os.path.isfile(marker):
        try:
            with open(meta) as f:
                m = json.load(f)
            return "format_version" not in m and "step" in m
        except (OSError, ValueError):
            return False
    try:
        with open(marker) as f:
            want = json.load(f)["meta_crc32"]
        got, _ = _crc_file(meta)
        return int(want) == got
    except (OSError, ValueError, KeyError):
        return False


def verify_checkpoint(path: str) -> List[str]:
    """Re-checksum every payload file against ``meta.json``. Returns the
    list of bad entries (missing / size or crc mismatch); empty = intact."""
    try:
        sums = read_meta(path).get("checksums", {})
    except (OSError, ValueError):
        return ["meta.json"]
    bad = []
    for rel, (crc, size) in sums.items():
        fp = os.path.join(path, rel)
        if not os.path.isfile(fp):
            bad.append(rel)
            continue
        got_crc, got_size = _crc_file(fp)
        if got_crc != int(crc) or got_size != int(size):
            bad.append(rel)
    return bad


def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """Committed checkpoints as sorted [(step, path)]; uncommitted or
    garbage directories (``.tmp`` staging, partial writes, stray names)
    are skipped."""
    if not os.path.isdir(directory):
        return []
    out = []
    for d in os.listdir(directory):
        m = _STEP_RE.match(d)
        if not m:
            continue
        path = os.path.join(directory, d)
        if os.path.isdir(path) and is_committed(path):
            out.append((int(m.group(1)), path))
    return sorted(out)


def latest_checkpoint(directory: str, verify: bool = False
                      ) -> Optional[str]:
    """Newest *committed* checkpoint, or None. Partially written
    directories (no/bad commit marker) are skipped, not selected and not
    crashed on. With ``verify=True`` checksums are also required, so a
    corrupted-latest falls back to the previous good checkpoint."""
    for _step, path in reversed(list_checkpoints(directory)):
        if verify and verify_checkpoint(path):
            continue
        return path
    return None


def prune_checkpoints(directory: str, keep: int) -> List[str]:
    """Delete all but the newest ``keep`` committed checkpoints; also sweeps
    ``.tmp`` staging dirs from dead writers (other pids, untouched for
    ``STALE_TMP_AGE_S``) via the shared ``utils.durable_io`` sweep.
    Returns removed paths."""
    from ..utils.durable_io import sweep_stale_tmp

    removed = []
    if keep <= 0 or not os.path.isdir(directory):
        return removed
    commits = list_checkpoints(directory)
    for _step, path in commits[:-keep] if len(commits) > keep else []:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    removed.extend(sweep_stale_tmp(directory))
    return removed


# ------------------------------------------------------------------ restoring
def _leaf_restore_args(leaf, mesh=None):
    import jax
    import orbax.checkpoint as ocp

    if isinstance(leaf, jax.Array):
        sh = leaf.sharding
        if mesh is not None:
            from jax.sharding import NamedSharding

            if isinstance(sh, NamedSharding) and sh.mesh is not mesh:
                sh = NamedSharding(mesh, sh.spec)
        return ocp.ArrayRestoreArgs(sharding=sh, global_shape=leaf.shape,
                                    dtype=leaf.dtype)
    return ocp.RestoreArgs()


def _host_staged_restore(ckptr, subdir: str, template):
    """Topology-changing restore: read every leaf to host numpy, then
    ``device_put`` it onto the *template's* sharding (the freshly searched
    strategy's placement). The host bounce is the price of resharding onto
    a mesh the checkpoint was not written for."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    import warnings

    ra = jax.tree_util.tree_map(
        lambda l: (ocp.RestoreArgs(restore_type=np.ndarray)
                   if isinstance(l, jax.Array) else ocp.RestoreArgs()),
        template)
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=".*sharding info.*")
        host = ckptr.restore(subdir, item=template, restore_args=ra)

    def put(h, t):
        if isinstance(t, jax.Array):
            return jax.device_put(np.asarray(h), t.sharding)
        if isinstance(h, jax.Array):
            # scalar leaves the template holds as python numbers (a fresh
            # optimizer step counter) may come back as device arrays pinned
            # to the CHECKPOINT's topology — strip the stale placement so
            # the jitted step re-places them on the new mesh
            return np.asarray(h)
        return h

    return jax.tree_util.tree_map(put, host, template)


def restore_checkpoint(ffmodel, path: str, mesh=None,
                       verify: bool = True) -> int:
    """Restore into a compiled model; shards land directly on their owner
    devices via orbax ``restore_args`` built from the model's current
    shardings (params from the executor's strategy placement, opt_state
    from its live leaves).

    ``mesh=`` overrides the target mesh for every NamedSharding (the
    elastic-restart path); when the checkpoint's recorded topology differs
    from the target, the pytree is restored host-staged and resharded onto
    the current strategy instead (``resilience/elastic.py`` drives the
    re-search that makes that strategy). ``verify`` checks content
    checksums first — a corrupt checkpoint raises before any model state
    is touched. Returns the checkpoint's step."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not is_committed(path):
        raise CheckpointCorruptError(
            f"{path}: no valid commit marker (partial write or not a "
            "checkpoint) — refusing to restore")
    if verify:
        bad = verify_checkpoint(path)
        if bad:
            raise CheckpointCorruptError(
                f"{path}: checksum mismatch in {bad} — checkpoint is "
                "corrupt; restore from an earlier committed step")
    meta = read_meta(path)
    target_mesh = mesh if mesh is not None else ffmodel.mesh
    same_topology = (ffmodel.strategy is not None and
                     list(meta.get("mesh_shape", [])) ==
                     list(ffmodel.strategy.mesh_shape) and mesh is None)
    ckptr = ocp.PyTreeCheckpointer()
    import jax

    import warnings

    if same_topology or mesh is not None:
        try:
            for attr, subdir in (("params", "params"),
                                 ("opt_state", "opt_state")):
                template = getattr(ffmodel, attr)
                ra = jax.tree_util.tree_map(
                    lambda l: _leaf_restore_args(l, mesh), template)
                with warnings.catch_warnings():
                    # scalar opt-state leaves (a fresh template's python-int
                    # step vs the saved device scalar) make orbax read the
                    # sharding from file — correct, just chatty
                    warnings.filterwarnings(
                        "ignore", message=".*sharding info.*")
                    setattr(ffmodel, attr,
                            ckptr.restore(os.path.join(path, subdir),
                                          item=template, restore_args=ra))
            return int(meta["step"])
        except (ValueError, KeyError) as e:
            # a mesh= override whose axes don't exist in the saved specs
            # (or vice versa) falls back to the host-staged path
            if mesh is None:
                raise CheckpointCorruptError(
                    f"{path}: sharded restore failed: {e}") from e
    try:
        ffmodel.params = _host_staged_restore(
            ckptr, os.path.join(path, "params"), ffmodel.params)
        ffmodel.opt_state = _host_staged_restore(
            ckptr, os.path.join(path, "opt_state"), ffmodel.opt_state)
    except Exception as e:
        # a topology-changing restore that still fails must name the two
        # topologies and the way out, not surface a bare orbax/sharding
        # exception (ISSUE 5 satellite)
        import numpy as np

        saved_ndev = int(meta.get("n_devices")
                         or np.prod(meta.get("mesh_shape", [1]) or [1]))
        live_ndev = len(jax.devices())
        live_mesh = (list(ffmodel.strategy.mesh_shape)
                     if ffmodel.strategy is not None else "?")
        raise RuntimeError(
            f"{path}: restore failed while resharding a checkpoint saved "
            f"on {saved_ndev} device(s) (mesh "
            f"{meta.get('mesh_shape', '?')}) onto the live {live_ndev}-"
            f"device topology (mesh {live_mesh}): {type(e).__name__}: {e}. "
            "For a changed topology use resilience.elastic_restore("
            "ffmodel, path) — it re-runs the strategy search on the "
            "surviving devices and reshards host-staged — or --resume on "
            "the original topology.") from e
    return int(meta["step"])


# ------------------------------------------------------------- async manager
class CheckpointManager:
    """Background checkpoint writer with bounded-queue backpressure.

    ``save_async`` snapshots the live trees with device-side copies
    (donation-safe; the dispatch is async so the step loop keeps going) and
    enqueues them for the worker thread, which serializes, commits and
    prunes. The queue holds at most ``queue_depth`` pending snapshots —
    when the disk can't keep up, ``save_async`` blocks until a slot frees,
    bounding snapshot memory at ``queue_depth + 1`` copies of the model.

    Worker failures never kill training: they are recorded in ``errors``
    and surfaced as a warning; the previous committed checkpoint stays the
    restore target.
    """

    def __init__(self, ffmodel, directory: str, keep: int = 3,
                 queue_depth: int = 2):
        self.ffmodel = ffmodel
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = max(int(keep), 1)
        self.saved = 0
        self.errors: List[str] = []
        self.last_committed_path: Optional[str] = latest_checkpoint(
            self.directory)
        self.last_committed_step: Optional[int] = None
        if self.last_committed_path is not None:
            try:
                self.last_committed_step = int(
                    read_meta(self.last_committed_path)["step"])
            except (OSError, ValueError, KeyError):
                self.last_committed_path = None
        self._q: Queue = Queue(maxsize=max(int(queue_depth), 1))
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="ckpt-writer")
        self._worker.start()

    # -- producer side -----------------------------------------------------
    def save_async(self, step: int,
                   train_state: Optional[Dict[str, Any]] = None) -> None:
        """Snapshot and enqueue; blocks only when the writer is
        ``queue_depth`` checkpoints behind (backpressure)."""
        snap_p = _device_snapshot(self.ffmodel.params)
        snap_o = _device_snapshot(self.ffmodel.opt_state)
        self._q.put((int(step), snap_p, snap_o, train_state))

    def save_sync(self, step: int,
                  train_state: Optional[Dict[str, Any]] = None
                  ) -> Optional[str]:
        """Drain pending async saves, then write ``step`` in the calling
        thread (the preemption-flush path: the checkpoint must be durable
        before the process exits the grace window). Skips the write when
        ``step`` is already the last committed one."""
        self.flush()
        if self.last_committed_step == int(step):
            return self.last_committed_path
        try:
            path = save_checkpoint(self.ffmodel, self.directory, step=step,
                                   train_state=train_state)
        except Exception as e:  # pragma: no cover - disk-full etc.
            self._note_error(step, e)
            return None
        self._committed(step, path)
        return path

    def flush(self) -> None:
        """Block until every enqueued snapshot is committed (or failed)."""
        self._q.join()

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._worker.join(timeout=60.0)

    # -- worker side -------------------------------------------------------
    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            step, snap_p, snap_o, train_state = item
            try:
                path = save_checkpoint(self.ffmodel, self.directory,
                                       step=step, train_state=train_state,
                                       params=snap_p, opt_state=snap_o)
                self._committed(step, path)
            except Exception as e:
                self._note_error(step, e)
            finally:
                self._q.task_done()

    def _committed(self, step: int, path: str) -> None:
        self.saved += 1
        self.last_committed_step = int(step)
        self.last_committed_path = path
        prune_checkpoints(self.directory, self.keep)

    def _note_error(self, step: int, e: Exception) -> None:
        import warnings

        msg = f"checkpoint step {step} failed: {type(e).__name__}: {e}"
        self.errors.append(msg)
        warnings.warn(msg)
