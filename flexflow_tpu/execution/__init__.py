from .optimizers import SGDOptimizer, AdamOptimizer, Optimizer  # noqa: F401
from .metrics import Metrics, PerfMetrics  # noqa: F401
from .losses import Loss, loss_value  # noqa: F401
