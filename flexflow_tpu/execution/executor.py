"""Executor: lowers a PCG + Strategy into jitted JAX train/eval steps.

This replaces the reference's entire task-launch machinery: FFModel::forward/
backward/update index launches (model.cc:2415-2469), the FFMapper
(src/mapper/mapper.cc), Legion trace capture (begin/end_trace), and the NCCL
bootstrap (model.cc:3129-3166). One ``jax.jit`` over the whole training step
with NamedShardings plays all those roles: tracing ≙ Legion trace replay,
SPMD partitioning ≙ mapper + parallel-op partitions, sharded autodiff ≙ NCCL
allreduce in the optimizer (SURVEY §7 architecture mapping).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..ffconst import DataType, OperatorType, dtype_to_jnp
from ..ops.base import OpContext
from ..parallel.pcg import PCG, PCGNode
from ..parallel.strategy import Strategy
from .losses import loss_value
from .metrics import Metrics


class Executor:
    def __init__(self, pcg: PCG, mesh, strategy: Strategy, loss_type,
                 metrics: Metrics, optimizer, config, final_guid: int,
                 label_dtype: DataType, repl_labels: bool = False,
                 final_out_idx: int = 0):
        self.final_out_idx = final_out_idx
        self.pcg = pcg
        self.mesh = mesh
        self.strategy = strategy
        self.loss_type = loss_type
        self.metrics = metrics
        self.optimizer = optimizer
        self.config = config
        self.final_guid = final_guid
        self.label_dtype = label_dtype
        self.repl_labels = repl_labels

        self._train_step = None
        self._guarded_train_step = None
        self._eval_step = None
        self._forward_jit = None
        self._probe_step = None
        # serving engine jits (ISSUE 6): {("prefill", bucket_len, max_len) |
        # ("decode", max_len): jitted fn} — one compile per prefill bucket
        # plus ONE decode compile, the engine's recompile-free contract
        self._serving_jits: Dict[Tuple, Any] = {}
        # the RematPlan make_train_step resolved and applied (None until
        # built, and None when remat is off/ineligible) — telemetry reads it
        self.remat_plan = None
        # cache-op state (reference: src/ops/cache.cc — cached intermediate
        # tensors across iterations, host-scored, paired with recompile)
        self.cache_nodes = [n for n in pcg.compute_nodes()
                            if n.op.op_type == OperatorType.OP_CACHE]

        # apply strategy op-attr overrides (e.g. ring-attention seq axis)
        for guid, ns in strategy.node_strategies.items():
            if ns.extra and guid in pcg.nodes:
                pcg.nodes[guid].op.attrs.update(ns.extra)

    # ------------------------------------------------------------------ sharding
    def _named_sharding(self, spec_entries):
        from jax.sharding import NamedSharding, PartitionSpec

        if self.mesh is None:
            return None
        if spec_entries is None:
            return NamedSharding(self.mesh, PartitionSpec())
        entries = list(spec_entries)
        while entries and entries[-1] is None:
            entries.pop()
        return NamedSharding(self.mesh, PartitionSpec(*entries))

    def batch_sharding(self, ndim: int):
        from jax.sharding import NamedSharding, PartitionSpec

        if self.mesh is None:
            return None
        axis = self.strategy.data_axis
        if axis not in self.mesh.shape:
            return NamedSharding(self.mesh, PartitionSpec())
        return NamedSharding(self.mesh,
                             PartitionSpec(*([axis] + [None] * (ndim - 1))))

    def param_shardings(self):
        """Pytree of NamedShardings matching init_params output."""
        out: Dict[str, Dict[str, Any]] = {}
        for node in self.pcg.compute_nodes():
            in_shapes = self._node_input_shapes(node)
            specs = node.op.weight_specs(in_shapes)
            if not specs:
                continue
            ns = self.strategy.node_strategies.get(node.guid)
            d = {}
            for wname, (shape, dtype, init) in specs.items():
                entries = (ns.weight_specs.get(wname) if ns else None)
                d[wname] = self._named_sharding(entries)
            out[node.name] = d
        return out

    # ------------------------------------------------------------------- params
    def _node_input_shapes(self, node: PCGNode) -> List[Tuple[int, ...]]:
        return [self.pcg.nodes[g].out_shapes[i] for g, i in node.inputs]

    def weight_entries(self):
        """[(node, wname, shape, dtype, init)] in topo order."""
        entries = []
        for node in self.pcg.compute_nodes():
            in_shapes = self._node_input_shapes(node)
            for wname, (shape, dtype, init) in node.op.weight_specs(
                    in_shapes).items():
                entries.append((node, wname, shape, dtype, init))
        return entries

    def init_params(self, seed: int = 0):
        """Sharded weight init: one jitted function with out_shardings, so big
        tables initialize directly on their owner shards (the reference runs
        per-shard Legion init tasks, initializer.cc)."""
        import jax

        entries = self.weight_entries()

        def init_fn(key):
            params: Dict[str, Dict[str, Any]] = {}
            for i, (node, wname, shape, dtype, init) in enumerate(entries):
                sub = jax.random.fold_in(key, i)
                params.setdefault(node.name, {})[wname] = init(
                    sub, shape, dtype_to_jnp(dtype))
            return params

        key = jax.random.PRNGKey(seed)
        if self.mesh is not None:
            shardings = self.param_shardings()
            return jax.jit(init_fn, out_shardings=shardings)(key)
        return jax.jit(init_fn)(key)

    # --------------------------------------------------------- mixed precision
    def _compute_jnp_dtype(self):
        """jnp dtype for forward compute, or None for full precision.

        Master weights, the loss, and normalization statistics stay float32;
        only the forward/backward compute (matmuls on the MXU) runs in the
        reduced dtype. The cast happens inside the differentiated function, so
        gradients flow back to the float32 master params.
        """
        cd = getattr(self.config, "compute_dtype", None)
        if cd is None or cd == DataType.DT_NONE:
            return None
        return dtype_to_jnp(cd)

    @staticmethod
    def _cast_floats(tree, dtype):
        import jax
        import jax.numpy as jnp

        def cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(dtype)
            return x

        return jax.tree.map(cast, tree)

    def _cast_for_compute(self, params, xs):
        cdtype = self._compute_jnp_dtype()
        if cdtype is None:
            return params, xs
        return (self._cast_floats(params, cdtype),
                self._cast_floats(xs, cdtype))

    @staticmethod
    def _logits_f32(logits):
        import jax.numpy as jnp

        if jnp.issubdtype(logits.dtype, jnp.floating):
            return logits.astype(jnp.float32)
        return logits

    # ------------------------------------------------------------------ forward
    def _exec_node(self, node: PCGNode, node_params, inputs,
                   ctx: OpContext) -> List[Any]:
        """Run ONE node: per-node OpContext (guid-folded rng), per-op
        named scope (op names become HLO metadata, so XLA/xprof timelines
        attribute fused kernels back to PCG nodes — the reference gets
        this from per-op Legion task names; here it is free at trace
        time), and the strategy's output sharding constraint. The single
        recipe both the plain forward and the remat blocks execute."""
        import jax
        import jax.lax as lax

        node_ctx = OpContext(
            training=ctx.training,
            rng=(jax.random.fold_in(ctx.rng, node.guid)
                 if ctx.rng is not None else None),
            seq_length=ctx.seq_length, mesh=ctx.mesh,
            profiling=ctx.profiling, aux_losses=ctx.aux_losses,
            cache_in=ctx.cache_in, cache_out=ctx.cache_out,
            serving=ctx.serving)
        with jax.named_scope(node.name):
            outs = node.op.forward(node_params, inputs, node_ctx)
        # apply the strategy's output sharding constraint (parallel ops and
        # any node the search pinned)
        ns = self.strategy.node_strategies.get(node.guid)
        if ns is not None and ns.output_spec is not None \
                and self.mesh is not None:
            sh = self._named_sharding(ns.output_spec)
            outs = [lax.with_sharding_constraint(outs[0], sh)] + outs[1:]
        return outs

    def forward_outputs(self, params, bound_inputs: Dict[int, Any],
                        ctx: OpContext,
                        overrides: Optional[Dict[int, List[Any]]] = None
                        ) -> Dict[int, List[Any]]:
        """Run the graph; returns {node_guid: [outputs]}.

        ``overrides`` substitutes the outputs of specific compute nodes
        without executing them — the serving engine's hook for replacing
        baked position-id constants with the live per-slot positions
        (serving/kvcache.is_position_constant). None on every training
        path."""
        values: Dict[int, List[Any]] = {}
        for node in self.pcg.topo_order():
            op = node.op
            if op.op_type in (OperatorType.OP_INPUT,
                              OperatorType.OP_WEIGHT):
                values[node.guid] = [bound_inputs[node.guid]]
                continue
            if overrides is not None and node.guid in overrides:
                values[node.guid] = overrides[node.guid]
                continue
            inputs = [values[g][i] for g, i in node.inputs]
            values[node.guid] = self._exec_node(
                node, params.get(node.name, {}), inputs, ctx)
        return values

    def _bind_inputs(self, xs: List[Any]) -> Dict[int, Any]:
        input_nodes = self.pcg.input_nodes()
        assert len(xs) == len(input_nodes), \
            f"model has {len(input_nodes)} inputs, got {len(xs)}"
        return {n.guid: x for n, x in zip(input_nodes, xs)}

    # ------------------------------------------------- rematerialized forward
    def _build_remat_program(self, plan):
        """Compile the PCG into checkpointed remat blocks for ``plan``
        (execution/remat.py — the SAME segmentation the Simulator's memory
        model prices). Each block becomes a pure function
        ``(block_params, boundary_values, rng) -> (exposed_outputs, aux)``
        wrapped in ``jax.checkpoint`` with the plan's save policy, so the
        backward pass recomputes the block's interior instead of saving it.
        Per-op ``jax.named_scope`` is preserved inside the blocks (the
        recompute shows up attributed in xprof timelines)."""
        import jax

        from ..ops.base import OpContext
        from .remat import checkpoint_policy, remat_segments

        policy = checkpoint_policy(plan.level)
        segments = remat_segments(self.pcg, plan.segment_size)
        seg_of = {g: k for k, seg in enumerate(segments) for g in seg}
        # every (guid, out_idx) consumed across a block boundary (or the
        # loss anchor) must be exposed as a block output — these are the
        # only activations `full` remat keeps
        needed = {(self.final_guid, self.final_out_idx)}
        for node in self.pcg.compute_nodes():
            for pg, i in node.inputs:
                if pg in seg_of and seg_of[pg] != seg_of[node.guid]:
                    needed.add((pg, i))

        mesh = self.mesh
        profiling = bool(getattr(self.config, "profiling", False))
        program = []
        for k, seg in enumerate(segments):
            seg_set = set(seg)
            ext_refs: List[Tuple[int, int]] = []
            seen = set()
            for g in seg:
                for pg, i in self.pcg.nodes[g].inputs:
                    if pg in seg_set or (pg, i) in seen:
                        continue
                    seen.add((pg, i))
                    ext_refs.append((pg, i))
            out_refs = [(g, i) for g in seg
                        for i in range(len(self.pcg.nodes[g].out_shapes))
                        if (g, i) in needed]
            names = [self.pcg.nodes[g].name for g in seg]

            # cache-stateful nodes of this block (reference: cache.cc):
            # their fresh values leave the block as EXPLICIT outputs —
            # the same no-host-side-mutation rule as aux losses. This is
            # the ISSUE 6 inversion of the old "CacheOp graphs opt out of
            # remat" rule: cache state threads through jax.checkpoint like
            # any other block boundary value.
            cache_names = [self.pcg.nodes[g].name for g in seg
                           if self.pcg.nodes[g].op.op_type ==
                           OperatorType.OP_CACHE]

            def make_fn(seg=seg, ext_refs=ext_refs, out_refs=out_refs,
                        cache_names=cache_names):
                def fn(block_params, ext_vals, rng, cache_in):
                    import jax.numpy as jnp

                    values = dict(zip(ext_refs, ext_vals))
                    aux: List[Any] = []
                    cache_out: Dict[str, Any] = {}
                    # block-local ctx: _exec_node folds the rng per node,
                    # exactly as the plain forward does (recompute replays
                    # identical dropout masks)
                    block_ctx = OpContext(training=True, rng=rng,
                                          mesh=mesh, profiling=profiling,
                                          aux_losses=aux,
                                          cache_in=cache_in,
                                          cache_out=cache_out)
                    for g in seg:
                        node = self.pcg.nodes[g]
                        inputs = [values[(pg, i)] for pg, i in node.inputs]
                        outs = self._exec_node(
                            node, block_params.get(node.name, {}), inputs,
                            block_ctx)
                        for i, v in enumerate(outs):
                            values[(g, i)] = v
                    # aux losses leave the block as an explicit output —
                    # appending traced interiors to a host-side list from
                    # inside jax.checkpoint would leak residual tracers
                    aux_sum = sum(aux) if aux else jnp.zeros((), jnp.float32)
                    return (tuple(values[r] for r in out_refs), aux_sum,
                            tuple(cache_out[n] for n in cache_names))
                return fn

            fn = make_fn()
            if policy is not None:
                fn = jax.checkpoint(fn, policy=policy)
            program.append((fn, ext_refs, out_refs, names, k, cache_names))
        return program

    def _forward_remat(self, params, bound_inputs: Dict[int, Any],
                       ctx: OpContext, program):
        """Run the checkpointed block program; returns the loss-anchor
        logits. Boundary values flow block to block; everything interior is
        recomputed in backward per the plan's policy."""
        import jax

        values = {(g, 0): v for g, v in bound_inputs.items()}
        for fn, ext_refs, out_refs, names, k, cache_names in program:
            block_params = {n: params[n] for n in names if n in params}
            ext_vals = tuple(values[r] for r in ext_refs)
            with jax.named_scope(f"remat_block_{k}"):
                outs, aux, cache_vals = fn(block_params, ext_vals, ctx.rng,
                                           ctx.cache_in)
            if ctx.aux_losses is not None:
                ctx.aux_losses.append(aux)
            if ctx.cache_out is not None:
                ctx.cache_out.update(zip(cache_names, cache_vals))
            values.update(zip(out_refs, outs))
        return values[(self.final_guid, self.final_out_idx)]

    # --------------------------------------------- collective-compute overlap
    def _blockwise_value_and_grad(self, program, params, xs, labels, rng,
                                  cache):
        """Forward + loss + grads over the remat block program with the
        gradient synchronization SPLIT per block (``--collective-overlap
        on``, ISSUE 10): each block's backward runs through its own
        ``jax.vjp``, and as it completes its weight grads are (a) pinned to
        their final shardings via ``with_sharding_constraint`` — the SPMD
        partitioner materializes that block's grad all-reduce at this
        program point instead of deferring every psum to the step tail —
        and (b) coupled to the outgoing boundary cotangents through
        ``lax.optimization_barrier``, so upstream blocks' backward compute
        cannot be scheduled before the block's reduction is issuable: the
        collectives hide behind the remaining backward instead of
        serializing after it.

        Numerics are IDENTICAL to the synchronous ``value_and_grad`` path:
        the same block functions run in the same order, cotangents
        accumulate in the same reverse-block order, the sharding
        constraint and the barrier are value-identities, and each psum
        happens exactly once on the same mesh — loss, grads, and the
        updated params are bitwise-equal (tests/test_pipeline_schedules).
        Returns ``((loss, (logits, cache_out)), grads)`` with ``grads``
        matching the ``params`` pytree (blocks partition the layers)."""
        import jax
        import jax.lax as lax
        import jax.numpy as jnp

        cdtype = self._compute_jnp_dtype()
        if cdtype is not None:
            xs = self._cast_floats(xs, cdtype)
        bound = self._bind_inputs(xs)
        values: Dict[Tuple[int, int], Any] = {(g, 0): v
                                              for g, v in bound.items()}
        shardings = self.param_shardings() if self.mesh is not None else {}
        tapes = []
        aux_primals = []
        cache_out: Dict[str, Any] = {}
        for fn, ext_refs, out_refs, names, k, cache_names in program:
            block_params = {n: params[n] for n in names if n in params}
            ext_vals = tuple(values[r] for r in ext_refs)

            def run(bp, ev, _fn=fn):
                # the mixed-precision cast lives INSIDE the vjp, exactly
                # as in the synchronous loss_fn: grads flow back to the
                # float32 master params
                if cdtype is not None:
                    bp = self._cast_floats(bp, cdtype)
                return _fn(bp, ev, rng, cache)

            with jax.named_scope(f"remat_block_{k}"):
                (outs, aux, cache_vals), vjp = jax.vjp(
                    run, block_params, ext_vals)
            aux_primals.append(aux)
            cache_out.update(zip(cache_names, cache_vals))
            values.update(zip(out_refs, outs))
            tapes.append((vjp, ext_refs, out_refs, outs, aux, cache_vals))

        raw = values[(self.final_guid, self.final_out_idx)]

        def tail(r):
            logits = self._logits_f32(r)
            from .losses import loss_value

            return loss_value(self.loss_type, logits, labels,
                              self.repl_labels), logits

        loss, tail_vjp, logits = jax.vjp(tail, raw, has_aux=True)
        # aux losses add in block order, matching the synchronous path's
        # `for aux in ctx.aux_losses: loss = loss + aux`
        for aux in aux_primals:
            loss = loss + aux

        cot: Dict[Tuple[int, int], Any] = {}
        (d_raw,) = tail_vjp(jnp.ones_like(loss))
        cot[(self.final_guid, self.final_out_idx)] = d_raw
        grads: Dict[str, Dict[str, Any]] = {}
        for vjp, ext_refs, out_refs, outs, aux, cache_vals in \
                reversed(tapes):
            cots_outs = tuple(
                cot.pop(r) if r in cot else jnp.zeros_like(o)
                for r, o in zip(out_refs, outs))
            dbp, dext = vjp((cots_outs, jnp.ones_like(aux),
                             tuple(jnp.zeros_like(c) for c in cache_vals)))
            # pin each weight grad to its final sharding — the psum
            # happens HERE, overlappable with the upstream backward ...
            if shardings:
                dbp = {n: {w: (lax.with_sharding_constraint(
                    g, shardings[n][w])
                    if shardings.get(n, {}).get(w) is not None else g)
                    for w, g in ws.items()} for n, ws in dbp.items()}
            # ... and order it before the upstream blocks consume the
            # boundary cotangents (a pure scheduling fence, value-identity)
            dbp, dext = lax.optimization_barrier((dbp, dext))
            grads.update(dbp)
            for r, d in zip(ext_refs, dext):
                prev = cot.get(r)
                cot[r] = d if prev is None else jax.tree_util.tree_map(
                    jnp.add, prev, d)
        return (loss, (logits, cache_out)), grads

    # ----------------------------------------------------------- cache state
    def init_cache(self):
        """Zeroed cache-state pytree for the graph's CacheOps:
        {"__use_cache__": False, op_name: zeros(input shape)}."""
        import jax.numpy as jnp

        cache = {"__use_cache__": jnp.asarray(False)}
        for node in self.cache_nodes:
            g, i = node.inputs[0]
            src = self.pcg.nodes[g]
            cache[node.name] = jnp.zeros(
                src.out_shapes[i], dtype_to_jnp(src.out_dtypes[i]))
        return cache

    # --------------------------------------------------------------- train step
    def invalidate_jit_cache(self) -> None:
        """Drop every cached jitted function. Required after anything the
        jits bake in as a constant changes — an optimizer learning-rate
        edit (keras LR scheduler, the sentinel's reduced-LR rollback) or
        an op-attr mutation outside recompile()."""
        self._train_step = None
        self._guarded_train_step = None
        self._eval_step = None
        self._forward_jit = None
        self._probe_step = None
        self._serving_jits = {}

    def make_train_step(self, guard: bool = False):
        """One fused jitted step: forward + loss + grad + metrics + update
        (SURVEY §7 hard-part 6 — the reference's separate
        zero_gradients/forward/backward/update phases collapse into this).

        With CacheOps in the graph the step takes the cache pytree as an
        extra trailing argument and returns the fresh cache values as an
        extra trailing result (reference: cache.cc's update/score tasks).

        Activation rematerialization (ISSUE 3): the resolved RematPlan
        (``--remat`` flag > searched ``strategy.remat`` > none) routes the
        forward through checkpointed remat blocks — ``jax.checkpoint``
        with the leveled save policy over bottleneck-cut segments — so the
        saved-for-backward set shrinks to what the plan keeps. Donation
        and the per-op named_scope observability are unchanged.

        Divergence sentinel (ISSUE 4): with ``guard=True`` the step checks
        ``isfinite(loss) & isfinite(|grad|²)`` on device and applies the
        optimizer update under ``lax.cond`` — a non-finite step returns
        params/opt_state UNCHANGED (the poison never reaches the weights)
        plus a trailing ``ok`` bool scalar, the single value the host-side
        ``resilience.GuardedTrainStep`` transfers per step."""
        import jax

        cached = self._guarded_train_step if guard else self._train_step
        if cached is not None:
            return cached

        mesh = self.mesh
        opt = self.optimizer
        has_cache = bool(self.cache_nodes)

        profiling = bool(getattr(self.config, "profiling", False))

        from .remat import resolve_remat_plan

        plan = resolve_remat_plan(self.config, self.strategy)
        # collective-compute overlap (ISSUE 10): per-remat-block grad
        # psums issued as each block's backward completes, instead of the
        # synchronous all-reduces at step end. Needs the block program
        # even at remat level "none" (blocks stay unwrapped — the
        # checkpoint policy is None — but give the backward its per-block
        # sync points).
        overlap = (getattr(self.config, "collective_overlap", "off")
                   or "off") == "on"
        remat_program = None
        if plan.level != "none" or overlap:
            # CacheOp graphs remat too (ISSUE 6 inversion of the old
            # opt-out): cache state threads through the checkpointed
            # blocks as explicit inputs/outputs
            remat_program = self._build_remat_program(plan)
        self.remat_plan = plan if (remat_program is not None
                                   and plan.level != "none") else None

        def loss_fn(params, xs, labels, rng, cache):
            params_c, xs = self._cast_for_compute(params, xs)
            cache_out = {}
            ctx = OpContext(training=True, rng=rng, mesh=mesh, aux_losses=[],
                            profiling=profiling,
                            cache_in=cache, cache_out=cache_out)
            if remat_program is not None:
                raw = self._forward_remat(params_c, self._bind_inputs(xs),
                                          ctx, remat_program)
            else:
                values = self.forward_outputs(params_c,
                                              self._bind_inputs(xs), ctx)
                raw = values[self.final_guid][self.final_out_idx]
            logits = self._logits_f32(raw)
            loss = loss_value(self.loss_type, logits, labels,
                              self.repl_labels)
            for aux in ctx.aux_losses:
                loss = loss + aux
            return loss, (logits, cache_out)

        def step(params, opt_state, xs, labels, rng, cache=None):
            if overlap:
                (loss, (logits, cache_out)), grads = \
                    self._blockwise_value_and_grad(
                        remat_program, params, xs, labels, rng, cache)
            else:
                (loss, (logits, cache_out)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, xs, labels, rng, cache)
            if guard:
                import jax.numpy as jnp

                # one reduction over all grads: any NaN/Inf anywhere in the
                # gradient (or the loss) poisons the scalar, so a single
                # isfinite pair is the whole check
                leaves = jax.tree_util.tree_leaves(grads)
                gsq = (sum(jnp.vdot(g, g) for g in leaves)
                       if leaves else jnp.zeros((), jnp.float32))
                ok = jnp.logical_and(jnp.isfinite(loss), jnp.isfinite(gsq))
                new_params, new_state = jax.lax.cond(
                    ok,
                    lambda: opt.update(params, grads, opt_state),
                    lambda: (params, opt_state))
            else:
                new_params, new_state = opt.update(params, grads, opt_state)
            m = self._compute_metrics(logits, labels)
            out = (new_params, new_state, loss, m)
            if has_cache:
                out = out + (cache_out,)
            if guard:
                out = out + (ok,)
            return out

        jit_kwargs = {"donate_argnums": (0, 1)}
        fn = jax.jit(step, **jit_kwargs)
        if guard:
            self._guarded_train_step = fn
        else:
            self._train_step = fn
        return fn

    def make_probe_step(self):
        """(params, xs, labels, rng[, cache]) -> (loss, grad_l2_norm):
        forward + loss + grad with NO optimizer update and NO donation —
        the parallel-correctness auditor's probe (resilience/audit.py).
        The same loss recipe as the train step (mixed-precision cast, aux
        losses, per-node guid-folded rng, so dropout masks replay
        identically across strategies over the same graph); the two
        returned scalars are the whole comparison surface."""
        import jax
        import jax.numpy as jnp

        if self._probe_step is not None:
            return self._probe_step
        mesh = self.mesh

        def loss_fn(params, xs, labels, rng, cache):
            params_c, xs = self._cast_for_compute(params, xs)
            ctx = OpContext(training=True, rng=rng, mesh=mesh, aux_losses=[],
                            cache_in=cache, cache_out={})
            values = self.forward_outputs(params_c, self._bind_inputs(xs),
                                          ctx)
            logits = self._logits_f32(
                values[self.final_guid][self.final_out_idx])
            loss = loss_value(self.loss_type, logits, labels,
                              self.repl_labels)
            for aux in ctx.aux_losses:
                loss = loss + aux
            return loss

        def probe(params, xs, labels, rng, cache=None):
            loss, grads = jax.value_and_grad(loss_fn)(params, xs, labels,
                                                      rng, cache)
            leaves = jax.tree_util.tree_leaves(grads)
            gsq = (sum(jnp.vdot(g, g).real.astype(jnp.float32)
                       for g in leaves)
                   if leaves else jnp.zeros((), jnp.float32))
            return loss, jnp.sqrt(gsq)

        self._probe_step = jax.jit(probe)
        return self._probe_step

    def profile_ops(self, params, xs, iters: int = 3):
        """ProfiledStep mode (ISSUE 8, docs/calibration.md): execute the
        graph node by node, each node through its own jitted function
        (per-op ``jax.named_scope`` preserved — the spans land in xprof
        timelines too), and time each DISTINCT op shape on device:
        block-until-ready per node, best-of-``iters`` repeats, with the
        jit dispatch overhead (measured once on an identity jit)
        subtracted — the same protocol as the Simulator's standalone
        microbench, but over the LIVE graph with the LIVE weights and
        batch, so the timings reflect the step the loop actually runs.

        Returns one raw record per distinct ``(op params, in-shapes)``
        key: ``{guid, name, op_type, in_shapes, measured_fwd_s, count}``
        (``guid`` is the first node carrying the key; ``count`` how many
        share it — BERT's 24 identical layers yield ONE timed record).
        ``obs.profile.profile_model`` joins these with the live sharding
        assignment into serializable OpRecords."""
        import time

        import jax
        import jax.numpy as jnp

        params_c, xs_c = self._cast_for_compute(params, list(xs))
        mesh = self.mesh
        profiling = bool(getattr(self.config, "profiling", False))
        ctx = OpContext(training=False, rng=None, mesh=mesh,
                        profiling=profiling)

        def timed(fn, *args):
            out = fn(*args)  # warmup: compile + settle
            jax.block_until_ready(out)
            best = float("inf")
            for _ in range(max(iters, 1)):
                t0 = time.perf_counter()
                out = fn(*args)
                jax.block_until_ready(out)
                best = min(best, time.perf_counter() - t0)
            return out, best

        ident = jax.jit(lambda t: t * 1.000001)
        _, overhead = timed(ident, jnp.ones((8, 8), jnp.float32))

        bound = self._bind_inputs(list(xs_c))
        values: Dict[int, List[Any]] = {}
        timings: Dict[Tuple, Optional[Dict[str, Any]]] = {}
        fns: Dict[Tuple, Any] = {}
        # liveness-based freeing: the node-by-node pass would otherwise
        # hold EVERY activation at once (the jitted step lets XLA free
        # intermediates; remat shrinks residency further) — a model sized
        # near HBM would OOM in the very pass meant to profile it. Drop a
        # producer's outputs once its last consumer has run.
        order = self.pcg.topo_order()
        uses: Dict[int, int] = {}
        for node in order:
            for g, _i in node.inputs:
                uses[g] = uses.get(g, 0) + 1
        for node in order:
            if node.op.op_type in (OperatorType.OP_INPUT,
                                   OperatorType.OP_WEIGHT):
                values[node.guid] = [bound[node.guid]]
                continue
            inputs = [values[g][i] for g, i in node.inputs]
            in_shapes = tuple(map(tuple, self._node_input_shapes(node)))
            key = (node.op.params_key(), in_shapes)
            node_params = params_c.get(node.name, {})

            def make_fn(node=node):
                def f(np_, ins):
                    return self._exec_node(node, np_, ins, ctx)
                return jax.jit(f)

            # one compile per distinct key: duplicate-key nodes (BERT's 24
            # identical layers) reuse the first node's jitted fn — their
            # op math is identical and ctx carries no rng to fold, so only
            # the named_scope label (cosmetic here) would differ; a fresh
            # closure per node would retrace+recompile every one
            fn = fns.get(key)
            if fn is None:
                fn = fns[key] = make_fn()
            rec = timings.get(key)
            if rec is None and node.op.op_type == OperatorType.OP_DROPOUT:
                # training-gated: the inference-mode forward is identity,
                # so a timing here would measure dispatch overhead and the
                # closed loop would slam the key's calibration to the
                # floor — execute for consumers, never emit a record
                # (backward ratios likewise stay on calibrate_from_pcg's
                # training-semantics measurement)
                rec = timings[key] = None
            if rec is None and key in timings:
                outs = fn(node_params, inputs)
            elif rec is None:
                outs, best = timed(fn, node_params, inputs)
                timings[key] = {
                    "guid": node.guid, "name": node.name,
                    "op_type": node.op.op_type.name,
                    "in_shapes": in_shapes,
                    "measured_fwd_s": max(best - overhead, 1e-9),
                    "count": 1,
                }
            else:
                # identical key: execute (values feed consumers) without
                # re-timing — the record just counts the extra occurrence
                outs = fn(node_params, inputs)
                rec["count"] += 1
            values[node.guid] = outs
            for g, _i in node.inputs:
                uses[g] -= 1
                if not uses[g]:
                    values.pop(g, None)
        jax.block_until_ready([values[g] for g in values])
        return [r for r in timings.values() if r is not None]

    def train_step_memory_analysis(self, params, opt_state, xs, labels):
        """XLA's compiled memory stats for the full training step
        (jax.stages.Compiled.memory_analysis) — the ground truth the
        analytic ``outputs*2 + weights*4`` model is validated against
        (reference: per-device memory validation vs the framebuffer budget,
        src/runtime/graph.cc:1984-2032). Returns the CompiledMemoryStats
        object (``peak_memory_in_bytes`` is the headline number)."""
        import jax

        step = self.make_train_step()
        rng = jax.random.PRNGKey(0)
        args = (params, opt_state, xs, labels, rng)
        if self.cache_nodes:
            args = args + (self.init_cache(),)
        return step.lower(*args).compile().memory_analysis()

    def _compute_metrics(self, logits, labels):
        if not self.metrics:
            return {}
        if self.repl_labels:
            import jax.numpy as jnp

            k = logits.shape[0] // labels.shape[0]
            labels = jnp.repeat(labels, k, axis=0)
        return self.metrics.compute(logits, labels)

    def make_eval_step(self):
        import jax

        if self._eval_step is not None:
            return self._eval_step
        mesh = self.mesh

        profiling = bool(getattr(self.config, "profiling", False))

        def estep(params, xs, labels):
            params, xs = self._cast_for_compute(params, xs)
            ctx = OpContext(training=False, rng=None, mesh=mesh,
                            profiling=profiling)
            values = self.forward_outputs(params, self._bind_inputs(xs), ctx)
            logits = self._logits_f32(values[self.final_guid][self.final_out_idx])
            loss = loss_value(self.loss_type, logits, labels, self.repl_labels)
            m = self._compute_metrics(logits, labels)
            return loss, m

        self._eval_step = jax.jit(estep)
        return self._eval_step

    def make_forward(self):
        """Inference-only forward (comp mode COMP_MODE_INFERENCE)."""
        import jax

        if self._forward_jit is not None:
            return self._forward_jit
        mesh = self.mesh

        profiling = bool(getattr(self.config, "profiling", False))

        def fwd(params, xs):
            params, xs = self._cast_for_compute(params, xs)
            ctx = OpContext(training=False, rng=None, mesh=mesh,
                            profiling=profiling)
            values = self.forward_outputs(params, self._bind_inputs(xs), ctx)
            return values[self.final_guid][self.final_out_idx]

        self._forward_jit = jax.jit(fwd)
        return self._forward_jit

    # ------------------------------------------------------------- serving
    # Prefill/decode split (ISSUE 6, flexflow_tpu/serving, docs/serving.md):
    # the graph's one forward recipe lowers into TWO inference programs —
    # a per-bucket prefill that populates the KV-cache pytree and ONE
    # static-shape decode step that consumes/extends it. Both reuse
    # forward_outputs (per-op named scopes, strategy output constraints,
    # mixed-precision cast), so the serving path inherits every training-
    # side op improvement for free.
    def _position_const_guids(self) -> List[int]:
        """Compute nodes holding the baked position-id constant (the
        ``broadcast(arange(seq))`` pattern of models/gpt2.py) — serving
        regenerates their value per phase via forward_outputs overrides."""
        from ..serving.kvcache import is_position_constant

        out = []
        for node in self.pcg.compute_nodes():
            if node.op.op_type == OperatorType.OP_CONSTANT and \
                    is_position_constant(node.op.attrs.get("value")):
                out.append(node.guid)
        return out

    def _serving_overrides(self, guids, value):
        return {g: [value] for g in guids}

    def make_prefill_step(self, bucket_len: int, max_decode_len: int):
        """Jitted ``(params, xs, lengths) -> (logits, last_logits, cache)``:
        run the whole right-padded prompt (padded to the scheduler's
        ``bucket_len`` — one compile per bucket, not per prompt length),
        populating a fresh ``max_decode_len`` KV ring buffer per stateful
        node. ``lengths`` (batch,) are the true prompt lengths; the
        returned ``last_logits`` (batch, vocab) are gathered at
        ``lengths - 1`` (the next-token distribution), ``logits`` is the
        full (batch, bucket_len, vocab) tensor for scoring/teacher-forcing
        consumers."""
        import jax

        key = ("prefill", int(bucket_len), int(max_decode_len))
        cached = self._serving_jits.get(key)
        if cached is not None:
            return cached
        mesh = self.mesh
        profiling = bool(getattr(self.config, "profiling", False))
        pos_guids = self._position_const_guids()

        from ..serving.kvcache import ServingState

        def prefill(params, xs, lengths):
            import jax.numpy as jnp

            params, xs = self._cast_for_compute(params, xs)
            lengths = lengths.astype(jnp.int32)
            sv = ServingState(mode="prefill", max_len=max_decode_len,
                              positions=jnp.zeros_like(lengths),
                              lengths=lengths)
            ctx = OpContext(training=False, rng=None, mesh=mesh,
                            profiling=profiling, serving=sv)
            b = xs[0].shape[0]
            pos = jnp.broadcast_to(
                jnp.arange(bucket_len, dtype=jnp.int32), (b, bucket_len))
            values = self.forward_outputs(
                params, self._bind_inputs(xs), ctx,
                overrides=self._serving_overrides(pos_guids, pos))
            logits = self._logits_f32(
                values[self.final_guid][self.final_out_idx])
            idx = jnp.clip(lengths - 1, 0, logits.shape[1] - 1)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]
            return logits, last, sv.cache_out

        fn = jax.jit(prefill)
        self._serving_jits[key] = fn
        return fn

    def make_chunk_prefill_step(self, chunk_len: int, max_decode_len: int,
                                block_size: int, kv_dtype: str = "native"):
        """Jitted ``(params, xs, state, table_row, start, n_new) ->
        (last_logits, new_state)``: ONE prefill chunk of ``chunk_len``
        token slots for a SINGLE request (batch 1) against the paged
        pool (ISSUE 14, chunked prefill + prefix-cache suffix prefill).
        ``xs`` carries the chunk's token ids ``(1, chunk_len)`` (rows
        beyond ``n_new`` are pad), ``table_row`` the slot's (mb,) int32
        block-table row, ``start`` the chunk's first position. The
        chunk's k/v rows are written into the slot's pool blocks and its
        queries attend over the slot's full gathered extent — the cached
        prefix (trie hit) and/or earlier chunks plus this chunk — so a
        long prompt prefills across several co-scheduled iterations and
        a trie-hit admission prefills only its suffix.

        ``last_logits`` (1, vocab) is the next-token distribution at the
        chunk's final REAL row — meaningful on the final chunk only
        (earlier chunks' logits are discarded). One compile per chunk
        shape (``chunk_len``), like the prefill buckets; ``start`` /
        ``n_new`` / the table row are traced, so chunk position and
        block choice never recompile. Numerics are bitwise the one-shot
        prefill's in every mode — see
        ``ops.attention._chunk_prefill_attention`` for the argument.
        ``state`` is donated: the pool updates in place; lengths and
        block tables pass through untouched (the engine arms the slot's
        device-side row and cursor only at prefill completion, so decode
        steps running BETWEEN chunks keep writing the slot's discarded
        tokens into the garbage block, never into its real blocks)."""
        import jax

        key = ("chunk", int(chunk_len), int(max_decode_len),
               int(block_size), str(kv_dtype))
        cached = self._serving_jits.get(key)
        if cached is not None:
            return cached
        mesh = self.mesh
        profiling = bool(getattr(self.config, "profiling", False))
        pos_guids = self._position_const_guids()

        from ..serving.kvcache import DecodeState, ServingState

        def chunk(params, xs, state, table_row, start, n_new):
            import jax.numpy as jnp

            params, xs = self._cast_for_compute(params, xs)
            start = jnp.asarray(start, jnp.int32)
            n_new = jnp.asarray(n_new, jnp.int32)
            sv = ServingState(mode="chunk", max_len=max_decode_len,
                              positions=start[None],
                              lengths=n_new[None],
                              cache_in=state.caches,
                              block_tables=table_row[None, :],
                              block_size=int(block_size),
                              kv_dtype=str(kv_dtype))
            ctx = OpContext(training=False, rng=None, mesh=mesh,
                            profiling=profiling, serving=sv)
            # pad rows (beyond n_new) can place past the position table
            # when start + chunk_len overhangs the context (a trie-hit
            # suffix chunk admitted deep into the prompt): jnp.take's
            # fill mode turns that gather into NaN embeddings, the pad
            # rows' NaN k/v land in the garbage block, and the gathered
            # extent's softmax-zero x NaN poisons the REAL rows. Clamp
            # pads to the chunk's last real position — real rows are
            # untouched, pads stay finite, garbage stays finite.
            pos = (start + jnp.arange(chunk_len, dtype=jnp.int32))[None, :]
            pos = jnp.minimum(pos, start + n_new - 1)
            values = self.forward_outputs(
                params, self._bind_inputs(xs), ctx,
                overrides=self._serving_overrides(pos_guids, pos))
            logits = self._logits_f32(
                values[self.final_guid][self.final_out_idx])
            idx = jnp.clip(n_new - 1, 0, logits.shape[1] - 1)
            last = jnp.take_along_axis(
                logits, idx[None, None, None], axis=1)[:, 0]
            caches = dict(state.caches)
            caches.update(sv.cache_out)
            new_state = DecodeState(caches=caches, lengths=state.lengths,
                                    block_tables=state.block_tables)
            return last, new_state

        fn = jax.jit(chunk, donate_argnums=(2,))
        self._serving_jits[key] = fn
        return fn

    def make_decode_step(self, max_decode_len: int, exact: bool = False,
                         guard: bool = False, block_size: int = 0,
                         kv_dtype: str = "native", seq_shards: int = 1):
        """Jitted ``(params, xs, state) -> (logits, new_state)``: ONE token
        per slot through the graph, consuming and extending the
        ``DecodeState`` ring buffers at each slot's ``lengths`` cursor.
        Static shapes throughout — after the single warmup compile the
        decode loop never recompiles (the engine asserts this via the jit
        cache size). The state argument is donated: the ring buffers
        update in place on device. ``exact=True`` selects the
        bitwise-vs-full-forward attention numerics (ServingState.exact) at
        a max_len-x score-compute premium — the verification mode the
        equivalence tests run. ``guard=True`` is the decode-health
        sentinel (ISSUE 9, mirroring ``make_train_step(guard=True)``): the
        step additionally returns ``ok`` — ``isfinite`` of each slot's
        logits reduced to a (n_slots,) bool vector — fused into the same
        program, so the only extra host traffic is that one bool vector
        per step. The logits themselves are untouched: a poisoned slot's
        quarantine decision is the HOST's (serving/resilience.py), and
        every healthy slot's values stay bitwise-identical to the
        unguarded step's.

        Paged KV (ISSUE 12): when the carried ``DecodeState`` has block
        tables, ``block_size``/``kv_dtype`` select the paged layout —
        the tables ride the jitted signature as one more int32 array, so
        the single-compile contract is unchanged (ring and paged are
        distinct programs, each compiled once).

        ``seq_shards`` (ISSUE 18) selects the sequence-parallel decode
        decomposition (ServingState.seq_shards): the gathered extent is
        scored as that many contiguous key segments merged by the flash
        segment combine — a static trace-time choice, so it joins the
        jit key and keeps the single-compile contract."""
        import jax

        key = ("decode", int(max_decode_len), bool(exact), bool(guard),
               int(block_size), str(kv_dtype), int(seq_shards))
        cached = self._serving_jits.get(key)
        if cached is not None:
            return cached
        mesh = self.mesh
        profiling = bool(getattr(self.config, "profiling", False))
        pos_guids = self._position_const_guids()

        from ..serving.kvcache import DecodeState, ServingState

        def decode(params, xs, state):
            import jax.numpy as jnp

            params, xs = self._cast_for_compute(params, xs)
            sv = ServingState(mode="decode", max_len=max_decode_len,
                              positions=state.lengths,
                              cache_in=state.caches, exact=exact,
                              block_tables=state.block_tables,
                              block_size=int(block_size),
                              kv_dtype=str(kv_dtype),
                              seq_shards=int(seq_shards))
            ctx = OpContext(training=False, rng=None, mesh=mesh,
                            profiling=profiling, serving=sv)
            values = self.forward_outputs(
                params, self._bind_inputs(xs), ctx,
                overrides=self._serving_overrides(
                    pos_guids, state.lengths[:, None]))
            logits = self._logits_f32(
                values[self.final_guid][self.final_out_idx])[:, 0]
            new_state = DecodeState(caches=sv.cache_out,
                                    lengths=state.lengths + 1,
                                    block_tables=state.block_tables)
            if guard:
                ok = jnp.all(jnp.isfinite(logits), axis=-1)
                return logits, new_state, ok
            return logits, new_state

        fn = jax.jit(decode, donate_argnums=(2,))
        self._serving_jits[key] = fn
        return fn
