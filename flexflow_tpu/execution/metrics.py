"""Training metrics.

Reference: src/metrics_functions/metrics_functions.cc — per-shard GPU compute
of ``PerfMetrics`` (metrics_functions.h:25-44) folded on CPU by
UPDATE_METRICS_TASK. TPU-native: metrics are computed inside the jitted train
step (sharded reduction is a psum XLA inserts); ``PerfMetrics`` accumulates the
per-step device scalars host-side, read lazily like the reference's Future.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List

from ..ffconst import LossType, MetricsType


@dataclasses.dataclass
class PerfMetrics:
    """Accumulated counters (reference: metrics_functions.h:25-44)."""

    train_all: int = 0
    train_correct: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0

    def update(self, other: Dict[str, float]) -> None:
        self.train_all += int(other.get("train_all", 0))
        self.train_correct += int(other.get("train_correct", 0))
        for f in ("cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss",
                  "mae_loss"):
            setattr(self, f, getattr(self, f) + float(other.get(f, 0.0)))

    def accuracy(self) -> float:
        return self.train_correct / max(self.train_all, 1)

    def get_accuracy(self) -> float:
        """reference name (flexflow_cffi.py PerfMetrics.get_accuracy —
        returns percent)."""
        return self.accuracy() * 100.0

    def mean(self, field: str) -> float:
        return getattr(self, field) / max(self.train_all, 1)


class Metrics:
    """reference: include/flexflow/metrics_functions.h — a loss type + a list
    of MetricsType computed against the final op's output."""

    def __init__(self, loss_type: LossType, metrics: List[MetricsType]):
        self.loss_type = loss_type
        self.measures = list(metrics)

    def compute(self, logits, labels) -> Dict[str, object]:
        """Device-side per-batch metrics; returns dict of scalars
        (reference: Metrics::compute, metrics_functions.cc:68)."""
        import jax.numpy as jnp

        out: Dict[str, object] = {"train_all": logits.shape[0]}
        sparse = self.loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
        for m in self.measures:
            if m == MetricsType.METRICS_ACCURACY:
                pred = jnp.argmax(logits, axis=-1)
                if sparse:
                    ref = labels.reshape(labels.shape[0]).astype(pred.dtype)
                else:
                    ref = jnp.argmax(labels, axis=-1)
                out["train_correct"] = jnp.sum(pred == ref)
            elif m == MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY:
                li = labels.reshape(labels.shape[0]).astype(jnp.int32)
                logp = jnp.log(jnp.clip(logits, 1e-12, 1.0))
                out["sparse_cce_loss"] = -jnp.sum(
                    jnp.take_along_axis(logp, li[:, None], axis=-1))
            elif m == MetricsType.METRICS_CATEGORICAL_CROSSENTROPY:
                logp = jnp.log(jnp.clip(logits, 1e-12, 1.0))
                out["cce_loss"] = -jnp.sum(labels * logp)
            elif m == MetricsType.METRICS_MEAN_SQUARED_ERROR:
                out["mse_loss"] = jnp.sum(
                    jnp.mean(jnp.square(logits - labels),
                             axis=tuple(range(1, logits.ndim))))
            elif m == MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR:
                out["rmse_loss"] = jnp.sum(jnp.sqrt(
                    jnp.mean(jnp.square(logits - labels),
                             axis=tuple(range(1, logits.ndim)))))
            elif m == MetricsType.METRICS_MEAN_ABSOLUTE_ERROR:
                out["mae_loss"] = jnp.sum(
                    jnp.mean(jnp.abs(logits - labels),
                             axis=tuple(range(1, logits.ndim))))
        return out
