"""Weight initializers.

Analog of the reference's initializer hierarchy (src/runtime/initializer.cc:349,
kernels in initializer_kernel.cu). Each initializer is a small object with
``__call__(key, shape, dtype) -> jnp.ndarray`` so weight creation is a pure jax
function that can be jitted with output shardings (giving sharded init for free,
where the reference launches per-shard Legion tasks).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


class Initializer:
    def __call__(self, key, shape: Sequence[int], dtype):
        raise NotImplementedError

    def _seeded(self, key):
        """Mix the initializer's own seed into the executor-provided key so
        two initializers with different seeds give different weights (the
        reference seeds each initializer task with its own seed,
        initializer.cc)."""
        seed = getattr(self, "seed", 0)
        if not seed:
            return key
        import jax

        return jax.random.fold_in(key, seed)


class GlorotUniformInitializer(Initializer):
    """Xavier/Glorot uniform (reference: initializer.cc GlorotUniform)."""

    def __init__(self, seed: int = 0):
        self.seed = seed

    @staticmethod
    def _fans(shape: Tuple[int, ...]) -> Tuple[int, int]:
        if len(shape) < 1:
            return 1, 1
        if len(shape) == 1:
            return shape[0], shape[0]
        if len(shape) == 2:
            return shape[0], shape[1]
        # conv kernels (H, W, Cin, Cout)
        receptive = int(np.prod(shape[:-2]))
        return shape[-2] * receptive, shape[-1] * receptive

    def __call__(self, key, shape, dtype):
        import jax

        fan_in, fan_out = self._fans(tuple(shape))
        limit = float(np.sqrt(6.0 / max(fan_in + fan_out, 1)))
        return jax.random.uniform(self._seeded(key), tuple(shape), dtype,
                                  -limit, limit)


class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype):
        import jax.numpy as jnp

        return jnp.zeros(tuple(shape), dtype)


class ConstantInitializer(Initializer):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, key, shape, dtype):
        import jax.numpy as jnp

        return jnp.full(tuple(shape), self.value, dtype)


class UniformInitializer(Initializer):
    def __init__(self, seed: int = 0, min_val: float = 0.0, max_val: float = 1.0):
        self.seed = seed
        self.min_val = min_val
        self.max_val = max_val

    def __call__(self, key, shape, dtype):
        import jax

        return jax.random.uniform(self._seeded(key), tuple(shape), dtype,
                                  self.min_val, self.max_val)


class NormInitializer(Initializer):
    def __init__(self, seed: int = 0, mean: float = 0.0, stddev: float = 1.0):
        self.seed = seed
        self.mean = mean
        self.stddev = stddev

    def __call__(self, key, shape, dtype):
        import jax

        return self.mean + self.stddev * jax.random.normal(
            self._seeded(key), tuple(shape), dtype)


DefaultWeightInitializer = GlorotUniformInitializer
DefaultBiasInitializer = ZeroInitializer
